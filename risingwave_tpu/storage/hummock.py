"""Hummock-lite: shared-storage LSM state tiering.

The storage half of the four-role cluster shape (frontend / compute /
compactor / meta — reference: docs/architecture-design.md:9-20). Where
``DurableStateStore`` (storage/checkpoint.py) writes per-epoch delta
SEGMENTS folded by an in-process thread, this tier writes per-epoch
**L0 SSTables** (storage/sstable.py) to an ObjectStore and hands all
rewriting to a compaction role scheduled by a meta-side version manager
(meta/hummock.py):

  * checkpoint flush  → one sorted L0 run per epoch (put, then the
    version manifest commits via atomic_put — a crash in between leaves
    an orphan object, never a torn version),
  * batch/backup read → pin a version; its runs survive any concurrent
    compaction until unpinned,
  * compaction        → a ``CompactTask`` rewrites every L0 run (plus
    overlapping L1) into fresh non-overlapping L1 runs, off the barrier
    path, in-process or on a dedicated compactor worker
    (worker/compactor.py),
  * vacuum            → deletes SSTs unreferenced by any pinned or
    current version.

Read path (newest wins): memory overlay → L0 newest→oldest → L1. A
tombstone found at any level STOPS the search; bottom-level compaction
drops tombstones and dropped tables' rows for good.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Tuple

from .checkpoint import PLAN_FORMAT_VERSION
from .object_store import ObjectStore, open_object_store, wrap_object_store
from .sstable import Sstable, SstBuilder, load_sst, merge_iter
from .state_store import MemoryStateStore

SST_PREFIX = "hummock/sst/"
VERSION_KEY = "hummock/version.json"


# -- version ------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class HummockVersion:
    """One immutable storage version: epoch → ordered run lists
    (reference: HummockVersion in the meta manager — the layer map every
    read resolves against). ``l0`` is newest-first overlapping runs;
    ``l1`` is non-overlapping sorted runs. Also carries the manifest
    duties the segment log's manifest carried (DDL log, dropped-table
    tombstones, plan format) so a Hummock data dir is self-describing."""

    vid: int
    committed_epoch: int
    l0: tuple = ()
    l1: tuple = ()
    ddl: tuple = ()
    dropped_tables: tuple = ()
    plan_format: int = PLAN_FORMAT_VERSION

    @classmethod
    def initial(cls) -> "HummockVersion":
        return cls(vid=0, committed_epoch=0)

    def replace(self, **kw) -> "HummockVersion":
        return dataclasses.replace(self, **kw)

    def all_runs(self) -> Tuple[str, ...]:
        return tuple(self.l0) + tuple(self.l1)

    def read_order(self) -> List[str]:
        """Runs in lookup priority order: L0 newest→oldest, then L1."""
        return list(self.l0) + list(self.l1)

    def fold_order(self) -> List[str]:
        """Runs in replay order (oldest first; later apply wins)."""
        return list(self.l1) + list(reversed(self.l0))

    def to_bytes(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "HummockVersion":
        d = json.loads(raw)
        return cls(vid=d["vid"], committed_epoch=d["committed_epoch"],
                   l0=tuple(d.get("l0", ())), l1=tuple(d.get("l1", ())),
                   ddl=tuple(d.get("ddl", ())),
                   dropped_tables=tuple(d.get("dropped_tables", ())),
                   plan_format=d.get("plan_format", 1))

    def summary(self) -> dict:
        return {"vid": self.vid, "committed_epoch": self.committed_epoch,
                "l0": list(self.l0), "l1": list(self.l1),
                "dropped_tables": list(self.dropped_tables)}


# -- compaction task ----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CompactTask:
    """One merge assignment from the version manager to a compactor.
    ``inputs`` are in lookup priority order (newest first) so the merge's
    duplicate-key rule is exactly the read path's."""

    task_id: int
    inputs: tuple
    dropped_tables: tuple = ()
    #: True when the task covers every live run: tombstones and dropped
    #: tables' rows may be discarded instead of rewritten
    bottom: bool = False
    base_vid: int = 0

    def to_wire(self) -> dict:
        return {"task_id": self.task_id, "inputs": list(self.inputs),
                "dropped_tables": list(self.dropped_tables),
                "bottom": self.bottom, "base_vid": self.base_vid}

    @classmethod
    def from_wire(cls, d: dict) -> "CompactTask":
        return cls(task_id=int(d["task_id"]), inputs=tuple(d["inputs"]),
                   dropped_tables=tuple(d.get("dropped_tables", ())),
                   bottom=bool(d.get("bottom", False)),
                   base_vid=int(d.get("base_vid", 0)))


def run_compact_task(store: ObjectStore, task: CompactTask,
                     target_sst_bytes: int = 4 << 20,
                     block_target_bytes: int = 4096) -> List[str]:
    """Execute one merge task: k-way merge the input runs (newest wins),
    drop dropped-table rows, drop tombstones iff bottom, and emit fresh
    L1 SSTs split at ``target_sst_bytes``. Pure function of the object
    store — runs identically in-process (background thread) and on the
    dedicated compactor worker. Crash-safe at every point: outputs are
    orphans until the meta-side version swap references them."""
    from ..common.failpoint import fail_point
    from ..common.tracing import CAT_STORAGE, trace_span
    fail_point("compactor.task.start")
    dropped = set(task.dropped_tables)
    runs = [load_sst(store, name) for name in task.inputs]
    outputs: List[str] = []
    builder: Optional[SstBuilder] = None
    size = 0
    with trace_span("compactor.task", CAT_STORAGE, tid="compactor",
                    task_id=task.task_id, inputs=len(task.inputs)):
        def flush_output() -> None:
            nonlocal builder, size
            if builder is None or builder.n_entries == 0:
                builder = None
                size = 0
                return
            name = (f"{SST_PREFIX}c{task.task_id:06d}-"
                    f"{len(outputs):03d}-{uuid.uuid4().hex[:8]}.sst")
            fail_point("compactor.output.write")
            store.put(name, builder.finish())
            outputs.append(name)
            builder = None
            size = 0

        for table_id, key, value in merge_iter(runs):
            fail_point("compactor.merge.step")
            if table_id in dropped:
                continue
            if value is None and task.bottom:
                continue
            if builder is None:
                builder = SstBuilder(block_target_bytes)
            builder.add(table_id, key, value)
            size += len(key) + (len(value) if value else 0) + 16
            if size >= target_sst_bytes:
                flush_output()
        flush_output()
    return outputs


# -- pinned snapshot reads ----------------------------------------------------

class PinnedSnapshot:
    """Consistent reads over one pinned version's runs: every lookup and
    scan resolves against the SAME SSTs no matter what compaction
    publishes meanwhile (reference: batch scans over a pinned
    HummockVersion, storage_table.rs reads at an epoch). Reads go through
    the object store — this is the path a serving replica or batch node
    without the writer's memory tier would use."""

    def __init__(self, manager, pin_id: int, version: HummockVersion,
                 store: ObjectStore):
        self._manager = manager
        self.pin_id = pin_id
        self.version = version
        self._store = store
        self._cache: Dict[str, Sstable] = {}
        self._folded: Optional[Dict[int, Dict[bytes, bytes]]] = None

    def _sst(self, name: str) -> Sstable:
        sst = self._cache.get(name)
        if sst is None:
            sst = load_sst(self._store, name)
            self._cache[name] = sst
        return sst

    def get(self, table_id: int, key: bytes) -> Optional[bytes]:
        if table_id in self.version.dropped_tables:
            return None
        for name in self.version.read_order():
            found, value = self._sst(name).lookup(table_id, key)
            if found:
                return value            # None = tombstone: stop here
        return None

    def fold_tables(self) -> Dict[int, Dict[bytes, bytes]]:
        """Materialize every table at this version (recovery/backup/
        batch full-scan base). Cached: the version is immutable, so a
        multi-table scan through one pin folds once, not once per
        table."""
        if self._folded is not None:
            return self._folded
        dropped = set(self.version.dropped_tables)
        tables: Dict[int, Dict[bytes, bytes]] = {}
        for name in self.version.fold_order():
            for table_id, key, value in self._sst(name).iter_entries():
                if table_id in dropped:
                    continue
                tbl = tables.setdefault(table_id, {})
                if value is None:
                    tbl.pop(key, None)
                else:
                    tbl[key] = value
        self._folded = tables
        return tables

    def iter_table(self, table_id: int) -> Iterator[Tuple[bytes, bytes]]:
        yield from sorted(self.fold_tables().get(table_id, {}).items())

    def unpin(self) -> None:
        self._manager.unpin_version(self.pin_id)

    def __enter__(self) -> "PinnedSnapshot":
        return self

    def __exit__(self, *exc) -> None:
        self.unpin()


# -- the store ----------------------------------------------------------------

class _LogFacade:
    """The slice of CheckpointLog's surface the Session drives
    (storage/checkpoint.py): DDL log, drop tombstones, background-fold
    lifecycle. Keeps ``session.store.log.*`` working unchanged across
    both durable tiers."""

    def __init__(self, store: "HummockStateStore"):
        self._store = store

    def exists(self) -> bool:
        return self._store.manager.exists()

    def ddl(self) -> List[str]:
        return self._store.manager.ddl()

    def log_ddl(self, sql: str) -> None:
        self._store.manager.log_ddl(sql)

    def drop_table(self, table_id: int) -> None:
        self._store.manager.drop_table(table_id)

    def compact(self) -> None:
        self._store.compact()

    def wait_compaction(self) -> None:
        self._store.wait_compaction()


class HummockStateStore(MemoryStateStore):
    """MemoryStateStore whose checkpoints persist as L0 SSTs under a
    meta-managed version (the Hummock backend of the reference's
    StateStoreImpl selection, store_impl.rs:49-64). Construction over a
    non-empty directory recovers the last committed version."""

    def __init__(self, data_dir: Optional[str] = None,
                 object_store: Optional[ObjectStore] = None,
                 l0_compact_trigger: Optional[int] = None,
                 inline_compaction: bool = True,
                 retry_policy=None):
        super().__init__()
        if object_store is None:
            if data_dir is None:
                raise ValueError("need data_dir or object_store")
            object_store = open_object_store(data_dir, retry_policy)
        # SST/manifest IO under the retry layer (idempotent whole-object
        # ops; common/retry.py) — the version manager shares the SAME
        # wrapped handle so vacuum and publish retry identically
        self.object_store = wrap_object_store(object_store, retry_policy)
        object_store = self.object_store
        from ..meta.hummock import HummockManager
        self.manager = HummockManager(object_store, l0_compact_trigger)
        self.log = _LogFacade(self)
        #: False routes compaction to a dedicated compactor worker the
        #: session drives (worker/compactor.py); True folds in a
        #: background thread like the segment log
        self.inline_compaction = inline_compaction
        self._compact_thread: Optional[threading.Thread] = None
        self._format_warned = False
        if self.manager.exists():
            epoch, tables = self._load_tables()
            self._committed = tables
            self.committed_epoch = epoch

    # -- recovery -------------------------------------------------------------

    def _load_tables(self) -> Tuple[int, Dict[int, Dict[bytes, bytes]]]:
        """Fold the current version's runs. A CROSS-process compactor may
        vacuum a run between our manifest read and the SST fetch; the
        manifest swap is atomic and runs are immutable, so re-reading
        converges — the same retry discipline as CheckpointLog."""
        for attempt in range(8):
            raw = self.object_store.get(VERSION_KEY)
            v = (HummockVersion.from_bytes(raw) if raw is not None
                 else HummockVersion.initial())
            if (v.plan_format != PLAN_FORMAT_VERSION
                    and not self._format_warned):
                self._format_warned = True
                import warnings
                warnings.warn(
                    f"data dir was written by plan-format {v.plan_format},"
                    f" this build is {PLAN_FORMAT_VERSION}: state-table "
                    "layout may not match the replayed DDL's rebuilt "
                    "plans — if recovery misbehaves, rebuild the MVs "
                    "(DROP/CREATE)")
            try:
                snap = PinnedSnapshot(self.manager, -1, v,
                                      self.object_store)
                return v.committed_epoch, snap.fold_tables()
            except FileNotFoundError:
                if attempt == 7:
                    raise
        raise AssertionError("unreachable")

    def refresh(self) -> int:
        """Adopt the latest PUBLISHED version: re-fold committed state
        and chase the committing process's epoch (serving sessions call
        this on every checkpoint notification — docs/control-plane.md).
        Local pending buffers are untouched; readers have none. Returns
        the committed epoch now visible."""
        if not self.manager.exists():
            return self.committed_epoch
        epoch, tables = self._load_tables()
        self.manager.reload()
        self._committed = tables
        self.committed_epoch = epoch
        return epoch

    def version_runs(self) -> list:
        """The SST runs the currently adopted version references —
        what a reader session reports to meta as its remote pin."""
        return sorted(self.manager.version.all_runs())

    # -- write path -----------------------------------------------------------

    def commit(self, epoch: int) -> None:
        if epoch <= self.committed_epoch:
            return
        from ..common.tracing import CAT_STORAGE, trace_span
        deltas: Dict[int, Dict[bytes, Optional[bytes]]] = {}
        for e in sorted(k for k in self._pending if k <= epoch):
            for table_id, buf in self._pending[e].items():
                deltas.setdefault(table_id, {}).update(buf)
        with trace_span("HummockStateStore.commit", CAT_STORAGE,
                        epoch=epoch, tid="storage", tables=len(deltas)):
            name = self._write_l0(epoch, deltas) if deltas else None
            try:
                self.manager.commit_epoch(epoch, name)
            except BaseException:
                if name is not None:
                    # failed publish: the uploaded object is a true
                    # orphan again — release it to vacuum
                    self.manager.abort_upload(name)
                raise
        super().commit(epoch)
        if self.inline_compaction:
            self._maybe_spawn_compact()

    def _write_l0(self, epoch: int,
                  deltas: Dict[int, Dict[bytes, Optional[bytes]]]) -> str:
        from ..common.failpoint import fail_point
        fail_point("hummock.sst.write")
        b = SstBuilder()
        for table_id in sorted(deltas):
            for key in sorted(deltas[table_id]):
                b.add(table_id, key, deltas[table_id][key])
        payload = b.finish()
        name = (f"{SST_PREFIX}e{epoch:012d}-"
                f"{uuid.uuid4().hex[:8]}.sst")
        # register BEFORE the put: a concurrently running vacuum (the
        # compaction pump's) must not delete the object in the window
        # between this put and the version publish referencing it. A
        # failed put aborts the registration HERE so the torn orphan is
        # not shielded from vacuum for the process lifetime.
        self.manager.begin_upload(name)
        try:
            try:
                # torn object mid-write: the version never references it,
                # so recovery ignores it and vacuum deletes it
                fail_point("hummock.sst.write.partial")
            except BaseException:
                self.object_store.put(name, payload[:16])
                raise
            self.object_store.put(name, payload)
        except BaseException:
            self.manager.abort_upload(name)
            raise
        return name

    def drop_table(self, table_id: int) -> None:
        super().drop_table(table_id)
        self.manager.drop_table(table_id)

    # -- reads at a pinned version --------------------------------------------

    def pin(self) -> PinnedSnapshot:
        pin_id, version = self.manager.pin_version()
        return PinnedSnapshot(self.manager, pin_id, version,
                              self.object_store)

    # -- compaction + vacuum --------------------------------------------------

    def _maybe_spawn_compact(self) -> None:
        t = self._compact_thread
        if t is not None and t.is_alive():
            return
        task = self.manager.get_compact_task()
        if task is None:
            return
        t = threading.Thread(target=self._compact_guarded, args=(task,),
                             daemon=True, name="hummock-compactor")
        self._compact_thread = t
        t.start()

    def _compact_guarded(self, task: CompactTask) -> None:
        try:
            outputs = run_compact_task(self.object_store, task)
            self.manager.report_compact_task(task.task_id, outputs)
            self.manager.vacuum()
        except Exception as e:  # never fatal: old runs stay valid
            self.manager.cancel_compact_task(task.task_id)
            import sys
            sys.stderr.write(
                f"hummock compaction failed (L0 keeps accumulating "
                f"until it succeeds): {e!r}\n")

    def wait_compaction(self) -> None:
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join()

    def compact(self, force: bool = True) -> None:
        """Synchronous full compaction cycle (tests / ctl): schedule,
        run, report, vacuum."""
        self.wait_compaction()
        task = self.manager.get_compact_task(force=force)
        if task is None:
            return
        try:
            outputs = run_compact_task(self.object_store, task)
        except BaseException:
            self.manager.cancel_compact_task(task.task_id)
            raise
        self.manager.report_compact_task(task.task_id, outputs)
        self.manager.vacuum()

    def vacuum(self) -> List[str]:
        return self.manager.vacuum()
