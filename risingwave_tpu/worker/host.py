"""Worker host: a compute-node process executing stream jobs shipped as
serialized plans.

Counterpart of the reference's compute node (reference:
src/compute/src/server.rs node bring-up; StreamService handlers
src/compute/src/rpc/service/stream_service.rs:46-233 build/drop actors +
barrier inject/collect; ExchangeService exchange_service.rs:74-133 moves
permit-metered data between processes). TPU-first scaling: ONE worker
process owns one accelerator's executors (device parallelism inside the
process rides the jax mesh), so the cross-process fabric only needs a
single multiplexed socket per worker, carrying:

  control   create_job / drop_job / barrier / commit / scan / shutdown
  data      channel frames (DML deltas, upstream changelogs) with
            consumption-acked permit flow (exchange/permit.rs:35-107)

Durability: the worker owns a DurableStateStore under its own directory.
Checkpointing is TWO-PHASE across the cluster: a checkpoint barrier seals
and stages worker state (ack = this worker's state for the epoch is
staged), and the session's later ``commit`` frame — sent only after every
worker acked and the session committed its own tier — makes it durable.
A worker killed between ack and commit recovers at the previous
checkpoint and its deterministic sources replay the gap (the reference
gets the same property from meta-owned Hummock version bumps:
src/meta/src/hummock/manager/ commit_epoch).
"""

from __future__ import annotations

import asyncio
import base64
import sys
from typing import AsyncIterator, Optional

from ..common.chunk import StreamChunk
from ..common.row import encode_value_row
from ..common.types import Field, INT64, Schema, VARCHAR
from ..frontend.build import BuildConfig, BuildContext, build_plan
from ..frontend.catalog import Catalog
from ..frontend.plan_json import defs_from_json, plan_from_json
from ..frontend.planner import PMvScan, PSource, PTableScan
from ..frontend.runtime import QueueSource, StreamJob
from ..rpc.wire import message_from_wire, read_frame, write_frame
from ..storage.checkpoint import DurableStateStore
from ..storage.state_table import StateTable
from ..stream.eowc import WatermarkFilterExecutor
from ..stream.executor import Executor
from ..stream.materialize import MaterializeExecutor
from ..stream.message import Barrier, Message, Mutation, MutationKind
from ..stream.row_id_gen import RowIdGenExecutor


class _Feed:
    """Worker-side source feed: connector reader + split-state table
    (mirrors the session's _SourceFeed; offsets persist with checkpoints
    and recovery seeks them)."""

    def __init__(self, queue: QueueSource, reader, state_table: StateTable,
                 job: str):
        self.queue = queue
        self.reader = reader
        self.state_table = state_table
        self.offsets_at_epoch: dict[int, dict] = {}
        self.job = job


class _ChannelSource(Executor):
    """Executor view of a wire data channel: frames decode lazily and the
    permit ack is sent only when the consumer TAKES a chunk — end-to-end
    consumption-based flow control (reference: permit.rs — data consumes
    credits, control always passes). Session data frames carry per-chan
    sequence numbers (frontend/remote.py send_data); duplicates are
    dropped un-acked and delayed frames re-enter in send order — the
    session→worker half of the exchange-edge dedup discipline."""

    identity = "RemoteExchangeSource"

    def __init__(self, host: "WorkerHost", chan: int, schema: Schema,
                 capacity: int):
        from ..rpc.exchange import SeqReorderBuffer
        self.host = host
        self.chan = chan
        self.schema = schema
        self.capacity = capacity
        self.queue: asyncio.Queue = asyncio.Queue()
        self._seqbuf = SeqReorderBuffer()
        self._ack_seq = 0

    @property
    def dup_frames(self) -> int:
        return self._seqbuf.dup_frames

    @property
    def reordered(self) -> int:
        return self._seqbuf.reordered

    def feed(self, wire_msg, seq: Optional[int] = None) -> None:
        """Session data frame arrival: dedup + re-order by seq before
        the frame reaches the executor queue (a dropped duplicate is
        NOT acked — the session consumed one permit for it)."""
        for item in self._seqbuf.feed(seq, wire_msg):
            self.queue.put_nowait(item)

    async def execute(self) -> AsyncIterator[Message]:
        while True:
            d = await self.queue.get()
            if d is None:
                return
            if isinstance(d, Message):        # locally injected (init cut)
                msg = d
            else:
                msg = message_from_wire(d, self.schema, self.capacity)
                if isinstance(msg, StreamChunk):
                    ack_seq = self._ack_seq
                    self._ack_seq += 1
                    await self.host.send({"type": "ack", "chan": self.chan,
                                          "seq": ack_seq})
            yield msg
            if isinstance(msg, Barrier) and msg.is_stop():
                return


class _RowIdAppend(Executor):
    """Append the hidden _row_id column slot to connector chunks (the
    session's _RowIdAppendSource, worker-side)."""

    def __init__(self, inner: QueueSource, out_schema: Schema):
        self.inner = inner
        self.schema = out_schema

    async def execute(self) -> AsyncIterator[Message]:
        import jax.numpy as jnp

        from ..common.chunk import Column
        async for msg in self.inner.execute():
            if isinstance(msg, StreamChunk):
                zero = Column(jnp.zeros(msg.capacity, jnp.int64),
                              jnp.ones(msg.capacity, jnp.bool_))
                msg = StreamChunk(msg.ops, msg.vis, msg.columns + (zero,))
            yield msg


class WorkerHost:
    """One worker process: jobs + durable store + the session socket."""

    def __init__(self, data_dir: str, worker_id: int = 0):
        from ..rpc.exchange import PeerClientPool
        self.data_dir = data_dir
        self.worker_id = worker_id
        # one durable store per JOB: recovery scope and id space are both
        # per-job, so a fresh rebuild wipes one directory without
        # tombstone bookkeeping leaking across incarnations
        self.stores: dict[str, DurableStateStore] = {}
        self.catalog = Catalog()
        self.jobs: dict[str, StreamJob] = {}
        self.feeds: list[_Feed] = []
        self.channels: dict[int, _ChannelSource] = {}
        # cross-worker exchange state (stream/remote_exchange.py): inputs
        # fed by peer connections, worker-local span channels, and the
        # pooled client connections toward peer workers
        self.exchange_inputs: dict[int, object] = {}
        self.span_chans: dict[int, object] = {}
        self.peer_pool = PeerClientPool(worker_id)
        # session-generation fencing (ISSUE 9): each job records the
        # generation its deployment frame carried; a barrier or commit
        # frame from an OLDER generation — a stale pre-recovery session
        # view, or a chaos-delayed frame arriving after scoped recovery
        # rebuilt the graph — is refused instead of acked/committed
        self.job_gens: dict[str, int] = {}
        self.fenced_frames = 0
        # elastic scaling plane counters (meta/rescale.py): rows exported
        # to / imported from handoff segments by live vnode migrations
        self.migrated_rows_out = 0
        self.migrated_rows_in = 0
        self.chunks_per_tick = 1
        self.chunk_capacity = 1024
        self.seed = 42
        # session-propagated fault-tolerance knobs (create_job frames):
        # worker-hosted broker readers must honor the SAME reconnect
        # budget as session-hosted ones
        self.fault = None
        self._next_shard = worker_id * 4096 + 1
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock = asyncio.Lock()
        # tracing-span outbox: drained batches are retained until the
        # session's NEXT stats request acknowledges their sequence
        # number, so a timed-out (discarded) stats reply loses no spans
        self._span_outbox: list = []
        self._span_seq = 0

    async def send(self, obj: dict, meta: bool = False) -> None:
        if self._writer is not None:
            await write_frame(self._writer, obj, self._wlock,
                              link=f"w{self.worker_id}->s", meta=meta)

    # -- job construction ------------------------------------------------------

    def span_chan(self, chan: int, permits: int):
        """Get-or-create a worker-LOCAL span edge channel (both endpoint
        fragments of the edge live in this process). Registered by id so
        whichever side builds first wires the same channel."""
        ch = self.span_chans.get(chan)
        if ch is None:
            from ..stream.dispatch import open_channel
            ch = open_channel(permits)
            self.span_chans[chan] = ch
        return ch

    def _source_leaf(self, leaf: PSource, job_name: str, store,
                     next_table_id, shard_id: Optional[int] = None) -> Executor:
        src = leaf.source
        q = QueueSource(src.schema)
        from ..connector.factory import make_reader
        reader = make_reader(src.connector, src.options, src.schema,
                             self.chunk_capacity, self.seed,
                             fault=self.fault)
        start_seq = 0
        if reader is not None:
            st = StateTable(store, next_table_id(),
                            Schema((Field("split_id", VARCHAR),
                                    Field("next_offset", INT64))), [0])
            offsets = {VARCHAR.to_python(r[0]): int(r[1])
                       for r in st.scan_all()}
            if offsets:           # recovered split state: seek
                reader.seek(offsets)
                start_seq = reader.rows_emitted()
            self.feeds.append(_Feed(q, reader, st, job_name))
        ex: Executor = _RowIdAppend(q, leaf.schema)
        # span fragments pin their shard id from the session (stable
        # across drop-and-rebuild recovery, so replayed rows reproduce
        # their pre-crash row ids — the exactly-once upsert condition for
        # row-id-keyed MVs); whole-job placement keeps the process-local
        # counter
        ex = RowIdGenExecutor(ex, row_id_index=leaf.row_id_index,
                              shard_id=(self._alloc_shard()
                                        if shard_id is None else shard_id),
                              start_seq=start_seq)
        if src.watermark is not None:
            col, delay = src.watermark
            ex = WatermarkFilterExecutor(ex, time_col=col, delay=delay)
        return ex

    def _alloc_shard(self) -> int:
        self._next_shard += 1
        return self._next_shard - 1

    def _set_fault(self, fault: dict) -> None:
        """Adopt the session's fault-tolerance knobs (shipped on every
        create frame) — including the exchange keepalive cadence the
        peer pool hands to new clients."""
        from ..common.config import FaultConfig
        self.fault = FaultConfig(**fault)
        self.peer_pool.keepalive_s = self.fault.exchange_keepalive_s
        self.peer_pool.keepalive_timeout_s = \
            self.fault.exchange_keepalive_timeout_s

    def _job_dir(self, name: str) -> str:
        import os
        return os.path.join(self.data_dir, "jobs", name)

    def _register_defs(self, defs_json: str) -> None:
        """Upsert the session's shipped catalog replicas (shared by job
        creation and batch tasks so the two cannot resolve different
        catalogs)."""
        for d in defs_from_json(defs_json):
            kind = type(d).__name__
            reg = {"SourceDef": self.catalog.sources,
                   "TableDef": self.catalog.tables,
                   "MaterializedViewDef": self.catalog.mvs}[kind]
            reg[d.name] = d

    async def handle_create_job(self, req: dict) -> dict:
        name = req["name"]
        if req.get("fresh"):
            # table-fed jobs rebuild from the upstream snapshot: wipe any
            # prior incarnation's durable state wholesale (in-memory AND
            # on-disk — the store object must not outlive the wipe)
            import shutil
            shutil.rmtree(self._job_dir(name), ignore_errors=True)
            self.stores.pop(name, None)
        store = self.stores.get(name)
        if store is None:
            store = DurableStateStore(self._job_dir(name))
            self.stores[name] = store
        self._register_defs(req["defs"])
        self.chunks_per_tick = req.get("chunks_per_tick", 1)
        self.chunk_capacity = req.get("chunk_capacity", 1024)
        self.seed = req.get("seed", 42)
        plan = plan_from_json(req["plan"], self.catalog)
        chan_of_leaf = {int(k): v for k, v in req.get("channels", {}).items()}
        ids = iter(range(req["id_start"], req["id_start"] + 10_000))
        leaf_i = [0]
        queues: list[QueueSource] = []

        def next_table_id() -> int:
            return next(ids)

        def factory(leaf) -> Executor:
            i = leaf_i[0]
            leaf_i[0] += 1
            if isinstance(leaf, PSource):
                ex = self._source_leaf(leaf, name, store, next_table_id)
                # find the root queue for barrier injection
                inner = ex
                while not isinstance(inner, QueueSource):
                    inner = getattr(inner, "inner", None) or inner.input
                queues.append(inner)
                return ex
            if isinstance(leaf, (PTableScan, PMvScan)):
                chan = chan_of_leaf.get(i)
                if chan is None:
                    raise ValueError(
                        f"scan leaf {i} of remote job {name!r} has no "
                        "exchange channel")
                ch = _ChannelSource(self, chan, leaf.schema,
                                    self.chunk_capacity)
                self.channels[chan] = ch
                return ch
            raise ValueError(
                f"cannot build remote leaf {type(leaf).__name__}")

        if req.get("fault"):
            self._set_fault(req["fault"])
        cfg = BuildConfig(**req.get("config", {}))
        ctx = BuildContext(store, next_table_id, factory, cfg,
                           durable=True)
        chans_before = set(self.channels)
        try:
            pipeline = build_plan(plan, ctx)
        except Exception:
            # half-built job: release anything the factory registered
            for c in set(self.channels) - chans_before:
                self.channels.pop(c, None)
            self.feeds = [f for f in self.feeds if f.job != name]
            raise
        mat = MaterializeExecutor(
            pipeline, StateTable(store, req["mv_table_id"],
                                 plan.schema, list(plan.pk)))
        job = StreamJob(name, mat, queues, actors=ctx.actors)
        self.jobs[name] = job
        self.job_gens[name] = int(req.get("gen", 0))
        job.start()                          # current (running) loop
        return {"ok": True, "state_table_ids": ctx.state_table_ids,
                "ids_end": next(ids)}

    async def handle_create_fragments(self, req: dict) -> dict:
        """Build this worker's fragments of a SPANNING job (the fragment
        scheduler placed the graph across workers; exchange edges name
        remote peers). Reference: stream_service.rs:46 build_actors — one
        request per compute node, naming the actors it hosts."""
        from ..stream.remote_exchange import build_fragments
        name = req["name"]
        if req.get("fresh"):
            import shutil
            shutil.rmtree(self._job_dir(name), ignore_errors=True)
            self.stores.pop(name, None)
        store = self.stores.get(name)
        created_store = store is None
        if store is None:
            # recover_at: the cluster-decided checkpoint cut — prepared
            # epochs ≤ it roll forward, later ones are discarded, so all
            # participants of the span rebuild the SAME epoch
            store = DurableStateStore(self._job_dir(name),
                                      recover_at=req.get("recover_at"))
            self.stores[name] = store
        # live-migration handoff: fragment specs may carry state REFS —
        # handoff segments a previous owner exported to shared storage
        # (storage/checkpoint.py write_handoff) for the vnode ranges this
        # actor is gaining. Import them into the committed tier BEFORE
        # the build below, so executors reload them like any other
        # recovered state (their load_vnodes filter scopes the reload to
        # the owned range either way).
        for spec in req.get("fragments", ()):
            for ref in spec.get("import_refs", ()) or ():
                from ..storage.checkpoint import read_handoff
                deltas = read_handoff(ref)
                self.migrated_rows_in += store.import_tables(
                    deltas, int(req.get("recover_at") or 0))
        self._register_defs(req["defs"])
        self.chunks_per_tick = req.get("chunks_per_tick", 1)
        self.chunk_capacity = req.get("chunk_capacity", 1024)
        self.seed = req.get("seed", 42)
        if req.get("fault"):
            self._set_fault(req["fault"])
        feeds0 = len(self.feeds)
        try:
            # (build_fragments rolls its own endpoint registrations back)
            job = build_fragments(self, req, store)
        except Exception:
            self.feeds = self.feeds[:feeds0]
            if created_store:
                # a retry must re-run recover_at against the on-disk
                # manifest, not reuse this half-initialized instance
                self.stores.pop(name, None)
            raise
        self.jobs[name] = job
        self.job_gens[name] = int(req.get("gen", 0))
        job.start()
        return {"ok": True,
                "state_table_ids": job.state_table_ids}

    def _release_span_job(self, job) -> None:
        """Unregister a FragmentJob's exchange endpoints so a later
        incarnation (recovery re-creates with FRESH channel ids) never
        collides with stale registrations."""
        for inp in getattr(job, "exchange_inputs", ()):
            if self.exchange_inputs.get(inp.chan) is inp:
                self.exchange_inputs.pop(inp.chan, None)
            inp.put_local(None)           # unblock a parked merge recv
        for out in getattr(job, "exchange_outputs", ()):
            out.client.unregister(out.chan)
        for chan in getattr(job, "local_chan_ids", ()):
            self.span_chans.pop(chan, None)

    async def handle_drop_job(self, req: dict) -> dict:
        name = req["name"]
        job = self.jobs.pop(name, None)
        if job is None:
            return {"ok": True}
        stop = Barrier.new(req["epoch"],
                           mutation=Mutation(MutationKind.STOP))
        for q in job.sources:
            q.push(stop)
        if getattr(job, "spanning", False):
            await job.stop()              # actors cancel mid-exchange
            self._release_span_job(job)
        else:
            for ch in _channel_roots(job):
                ch.queue.put_nowait(stop)
                self.channels.pop(ch.chan, None)
            await job.stop()
        self.feeds = [f for f in self.feeds if f.job != name]
        self.stores.pop(name, None)
        self.job_gens.pop(name, None)
        if req.get("drop_state", True):
            import shutil
            shutil.rmtree(self._job_dir(name), ignore_errors=True)
        return {"ok": True}

    # -- barrier conduction ----------------------------------------------------

    async def handle_barrier(self, req: dict) -> None:
        """Inject this epoch into worker-driven roots, then collect all
        in-scope jobs and ack with a PER-JOB failure map. Runs as its own
        task so data frames keep flowing while executors work (barrier
        pipelining). ``exclude`` names jobs the session already declared
        dead (a spanning job with a killed peer): they must be neither
        fed nor waited on — one starved job must not wedge this worker's
        healthy jobs."""
        epoch = int(req["epoch"])
        checkpoint = bool(req.get("checkpoint", False))
        only = req.get("only")
        scope = set(only) if only is not None else set(self.jobs)
        scope -= set(req.get("exclude") or ())
        gen = req.get("gen")
        if gen is not None:
            # fencing: a barrier from an older session generation must
            # not reach jobs a newer generation already rebuilt — acking
            # it would let a stale graph stage state under the cluster's
            # current epoch cut
            stale = {n for n in scope
                     if self.job_gens.get(n, 0) > int(gen)}
            if stale:
                self.fenced_frames += len(stale)
                scope -= stale
        mut = None
        if req.get("mutation"):
            mut = Mutation(MutationKind(req["mutation"]),
                           req.get("mutation_payload"))
        barrier = Barrier.new(epoch, checkpoint=checkpoint, mutation=mut)
        if req.get("generate", False):
            for feed in self.feeds:
                if feed.job not in scope:
                    continue
                for _ in range(self.chunks_per_tick):
                    chunk = feed.reader.next_chunk()
                    if chunk is not None:
                        feed.queue.push(chunk)
        for feed in self.feeds:
            if feed.job in scope:
                feed.offsets_at_epoch[epoch] = feed.reader.offsets
                feed.queue.push(barrier)
        if req.get("init", False):
            # init cut for a just-created job: its channel roots have no
            # live upstream stream yet, so the barrier is injected locally
            # (span fragments skip this — their exchange inputs have live
            # peers and the init barrier arrives over the wire)
            for name in scope:
                job = self.jobs.get(name)
                if job is not None and not getattr(job, "spanning", False):
                    for ch in _channel_roots(job):
                        ch.queue.put_nowait(barrier)
        failed: dict[str, str] = {}

        async def collect(name: str, job) -> None:
            from ..rpc.exchange import PeerLost
            try:
                await job.wait_barrier(epoch)
            except PeerLost as e:
                failed[name] = f"PEER_LOST: {e}"
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 - shipped per job
                if isinstance(getattr(job, "_failure", None), PeerLost):
                    failed[name] = f"PEER_LOST: {job._failure}"
                else:
                    failed[name] = repr(e)

        from ..common.barrier_ledger import timed_stage
        from ..common.tracing import CAT_EPOCH, trace_span
        with trace_span("barrier.collect", CAT_EPOCH, epoch=epoch,
                        tid="conductor", checkpoint=checkpoint), \
                timed_stage(epoch, "worker_collect"):
            await asyncio.gather(
                *(collect(n, self.jobs[n]) for n in scope
                  if n in self.jobs))
        if checkpoint:
            for feed in self.feeds:
                if feed.job not in scope or feed.job in failed:
                    continue
                latest = None
                for oe in sorted(list(feed.offsets_at_epoch)):
                    if oe <= epoch:
                        latest = feed.offsets_at_epoch.pop(oe)
                if latest is not None:
                    for sid, off in latest.items():
                        feed.state_table.insert(
                            (VARCHAR.to_physical(sid), int(off)))
                    feed.state_table.commit(epoch)
            # spanning jobs: phase 1 of the cluster 2PC — this ack asserts
            # the epoch is DURABLY staged (state + offsets), so a kill
            # between ack and the session's commit frame can be rolled
            # FORWARD at recovery to the epoch the peers committed
            for name in scope:
                job = self.jobs.get(name)
                if job is None or name in failed \
                        or not getattr(job, "spanning", False):
                    continue
                store = self.stores.get(name)
                if store is not None:
                    store.prepare(epoch)
        done = {"type": "barrier_complete", "epoch": epoch,
                "failed": failed, "init": bool(req.get("init", False))}
        if gen is not None:
            done["gen"] = int(gen)   # session drops acks from stale gens
        await self.send(done)

    def handle_job_epochs(self, req: dict) -> dict:
        """Recovery negotiation: what this worker durably holds for one
        job — its committed epoch and any prepared-but-uncommitted
        epochs. The session takes the MAX committed across participants
        as the decided cut and every store settles to it (roll forward
        or discard) via ``create_fragments``' ``recover_at``."""
        from ..storage.checkpoint import CheckpointLog
        name = req["name"]
        store = self.stores.get(name)
        log = store.log if store is not None \
            else CheckpointLog(self._job_dir(name))
        if not log.exists():
            return {"ok": True, "committed": 0, "prepared": []}
        committed, prepared = log.recovery_info()
        return {"ok": True, "committed": committed, "prepared": prepared}

    # -- elastic scaling plane (live vnode migration) --------------------------

    @staticmethod
    def _vnode_tables(ex) -> list:
        """The vnode-partitioned state tables under one fragment's
        executor subtree, as (StateTable, key_indices, key_types) —
        what a live migration must hand off for a moving range. Covers
        the shapes the scaling plane migrates (``shardable`` fragments:
        grouped-agg cores under row-wise operators, plus the root
        materialize); exchange leaves end the walk."""
        from ..stream.hash_agg import HashAggExecutor
        from ..stream.materialized_agg import MaterializedAggExecutor \
            as _MatAgg
        out = []
        stack, seen = [ex], set()
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, MaterializeExecutor) \
                    and node.table is not None:
                t = node.table
                out.append((t, tuple(t.pk_indices),
                            tuple(t.schema[i].type for i in t.pk_indices)))
            if isinstance(node, HashAggExecutor) \
                    and node.state_table is not None:
                nk = len(node.core.group_keys)
                out.append((node.state_table, tuple(range(nk)),
                            tuple(node.core.key_types)))
            if isinstance(node, _MatAgg) \
                    and node.state_table is not None and node.group_keys:
                nk = len(node.group_keys)
                out.append((node.state_table, tuple(range(nk)),
                            tuple(node.in_schema[i].type
                                  for i in node.group_keys)))
            for attr in ("input", "inner", "left", "right"):
                child = getattr(node, attr, None)
                if isinstance(child, Executor):
                    stack.append(child)
            for child in getattr(node, "inputs", ()):
                if isinstance(child, Executor):
                    stack.append(child)
        return out

    def handle_rescale_export(self, req: dict) -> dict:
        """Export the committed rows of one fragment's moving vnode
        ranges as handoff segments on shared storage, returning their
        REFS (paths). Runs on the quiesced pre-migration graph: the
        session drained + checkpoint-flushed first, so the committed
        tier is the complete state of the epoch being handed off
        (reference: scale.rs:657 shipping state as SST refs)."""
        import os

        from ..common.hashing import vnodes_of_rows
        from ..common.row import decode_value_row
        from ..storage.checkpoint import write_handoff
        name = req["name"]
        job = self.jobs.get(name)
        if job is None:
            return {"ok": False, "error": f"job {name!r} not found"}
        ex = getattr(job, "fragment_execs", {}).get(int(req["fragment"]))
        if ex is None:
            return {"ok": False,
                    "error": f"fragment {req['fragment']} not hosted here"}
        os.makedirs(req["dir"], exist_ok=True)
        refs = []
        tables = self._vnode_tables(ex)
        for start, end in req["ranges"]:
            deltas: dict[int, dict] = {}
            moved = 0
            for table, key_idx, key_types in tables:
                kept: dict[bytes, bytes] = {}
                pairs = list(table.store.iter_table(table.table_id))
                rows = [decode_value_row(v, table.schema.types)
                        for _k, v in pairs]
                vns = vnodes_of_rows(
                    key_types, [[r[i] for i in key_idx] for r in rows])
                for (k, v), vn in zip(pairs, vns):
                    if start <= vn < end:
                        kept[k] = v
                if kept:
                    deltas[table.table_id] = kept
                    moved += len(kept)
            path = os.path.join(
                req["dir"],
                f"f{int(req['fragment'])}_{start}_{end}"
                f"_w{self.worker_id}.seg")
            write_handoff(path, deltas)
            self.migrated_rows_out += moved
            refs.append({"path": path, "vnode_start": start,
                         "vnode_end": end, "rows": moved,
                         "tables": {str(t): len(r)
                                    for t, r in deltas.items()}})
        return {"ok": True, "refs": refs, "worker": self.worker_id}

    def handle_set_rate(self, req: dict) -> dict:
        """Adjust this worker's per-tick source generation rate live —
        the traffic-spike lever (sim.py run_traffic_spike drives it; the
        autoscaler reacts to the resulting backlog)."""
        self.chunks_per_tick = max(0, int(req["chunks_per_tick"]))
        return {"ok": True, "chunks_per_tick": self.chunks_per_tick}

    # -- distributed batch stage ----------------------------------------------

    def handle_batch_task(self, req: dict) -> dict:
        """Execute a batch plan FRAGMENT against this worker's job store
        and return its result rows — the distributed batch stage
        (reference: per-stage task execution on compute nodes,
        src/frontend/src/scheduler/distributed/query.rs:69,115 +
        BatchManager::fire_task, task_manager.rs:93). Only the stage's
        OUTPUT crosses the wire, not the scanned state."""
        from ..batch.executors import run_batch
        from ..batch.lower import lower_plan
        name = req["job"]
        store = self.stores.get(name)
        if store is None:
            return {"ok": False, "error": f"job {name!r} has no store"}
        self._register_defs(req["defs"])
        plan = plan_from_json(req["plan"], self.catalog)
        # optional per-task vnode slice (the serving plane's two-phase
        # partial tasks restrict their scans to the slice they own;
        # slice-unsafe shapes refuse by lowering to None)
        vnodes = req.get("vnodes")
        ex = lower_plan(plan, store, vnodes=vnodes)
        if ex is None:
            return {"ok": False,
                    "error": "stage plan is not batch-lowerable"}
        types = [f.type for f in plan.schema]
        rows = [base64.b64encode(encode_value_row(r, types)).decode()
                for r in run_batch(ex)]
        return {"ok": True, "rows": rows, "worker": self.worker_id,
                "n_rows": len(rows)}

    # -- monitor ---------------------------------------------------------------

    def handle_stats(self, req: dict) -> dict:
        """Monitor snapshot: per-job executor trees + counters + state
        bytes, exchange queue depths, and a drain of this process's
        tracing-span ring — the worker half of metrics federation
        (reference: MonitorService.stack_trace + Prometheus exporters,
        src/compute/src/rpc/service/monitor_service.rs:46)."""
        from ..common.memory import pipeline_state_bytes
        from ..common.profiling import GLOBAL_PROFILER
        from ..common.tracing import GLOBAL_TRACE
        from ..stream.metrics import pipeline_metrics
        from ..stream.trace import executor_tree
        jobs: dict = {}
        trees: dict = {}
        state_bytes: dict = {}
        for name, job in self.jobs.items():
            if job.pipeline is None:
                continue
            jobs[name] = pipeline_metrics(job.pipeline)
            trees[name] = executor_tree(job.pipeline)
            try:
                state_bytes[name] = pipeline_state_bytes(job.pipeline)
            except Exception:  # noqa: BLE001 - stats must never fail a job
                pass
        if req.get("span_ack") == self._span_seq:
            self._span_outbox = []         # previous batch safely landed
        new = GLOBAL_TRACE.drain()
        if new:
            self._span_outbox.extend(s.to_dict() for s in new)
            cap = GLOBAL_TRACE.capacity    # bound resends like the ring
            if len(self._span_outbox) > cap:
                del self._span_outbox[:-cap]
            self._span_seq += 1
        # barrier observatory: this process's epoch-stamped stage events
        # (storage prepare/settle/commit, worker collect) ride the SAME
        # stats frame as spans, with the same retained-until-acked outbox
        # discipline — no extra RPC, nothing on the barrier path
        from ..common.barrier_ledger import GLOBAL_STAGES
        stage_seq, stage_events = GLOBAL_STAGES.drain_outbox(
            req.get("stage_ack"))
        from ..rpc.faults import chaos_snapshot
        from ..stream.remote_exchange import exchange_stats
        return {
            "ok": True, "worker_id": self.worker_id,
            "jobs": jobs, "trees": trees, "state_bytes": state_bytes,
            "queue_depths": {str(c): ch.queue.qsize()
                             for c, ch in self.channels.items()},
            # per-exchange-edge counters (permits waited, chunks/bytes
            # forwarded, backlog) for every cross-worker edge endpoint
            # this process hosts — federated into metrics()["exchange"]
            "exchange": exchange_stats(self),
            # fault-plane state: this process's chaos injections plus
            # the fencing / dedup counters the plane's injection forced
            "chaos": {**chaos_snapshot(),
                      "fenced_frames": self.fenced_frames,
                      "pool_evictions": self.peer_pool.evictions,
                      "dup_data_frames": sum(
                          ch.dup_frames for ch in self.channels.values())},
            # elastic scaling plane: handoff rows this process exported /
            # imported across live vnode migrations (meta/rescale.py)
            "rescale": {"rows_out": self.migrated_rows_out,
                        "rows_in": self.migrated_rows_in},
            # device profiling plane: this process's per-dispatch
            # telemetry (common/profiling.py) — federated into
            # Session.metrics()["profiling"]["workers"]
            "profiling": GLOBAL_PROFILER.snapshot(),
            "spans": list(self._span_outbox), "span_seq": self._span_seq,
            "barrier_stages": stage_events, "stage_seq": stage_seq,
        }

    # -- scan ------------------------------------------------------------------

    def handle_scan(self, req: dict) -> dict:
        name = req["name"]
        job = self.jobs.get(name)
        if job is None:
            return {"ok": False, "error": f"job {name!r} not found"}
        if job.table is None:
            return {"ok": False,
                    "error": f"job {name!r} hosts no table on this worker"}
        schema = job.pipeline.schema
        types = [f.type for f in schema]
        rows = list(job.table.scan_all())
        rv = getattr(job, "root_vnodes", None)
        if rv is not None:
            # vnode-distributed root MV: serve only the owned range. A
            # live migration leaves moved-away rows behind in this store
            # (bounded leftovers, reloaded by nobody); without this
            # filter the scan union across root actors would double-read
            # them (meta/rescale.py, docs/scaling.md).
            from ..common.hashing import filter_rows_vnodes
            pk = list(job.table.pk_indices)
            rows = filter_rows_vnodes(
                [types[i] for i in pk], rows, rv[0], rv[1],
                key_indices=pk)
        rows = [base64.b64encode(encode_value_row(r, types)).decode()
                for r in rows]
        return {"ok": True, "rows": rows}

    # -- serve -----------------------------------------------------------------

    async def _reply(self, frame: dict, handler,
                     meta: bool = False) -> None:
        """Per-request error isolation: a failing handler (bad plan,
        unknown connector, missing file) answers THIS request with the
        error — it must never tear down the worker and its other jobs
        (the local path surfaces the same failures as per-statement
        SqlErrors). ``meta`` marks wall-clock-driven replies (stats
        polls) so the fault plane keeps them out of the deterministic
        frame-seq stream."""
        try:
            resp = await handler(frame)
        except Exception as e:  # noqa: BLE001 - shipped to the session
            resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        resp.update({"type": "reply", "rid": frame["rid"]})
        await self.send(resp, meta=meta)

    async def handle_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> str:
        """Dispatch a fresh inbound connection: the session's control
        socket, or a PEER worker's exchange socket (first frame is its
        ``exg_hello``). Returns which kind this was so the server only
        exits when the SESSION goes away."""
        first = await read_frame(reader)
        if first is None:
            # closed before identifying itself: a peer killed between
            # connect and its exg_hello, or a port probe. Treating it as
            # the session would clobber the real session's writer and
            # self-terminate a healthy worker.
            writer.close()
            return "empty"
        if first.get("type") == "exg_hello":
            await self._handle_peer_conn(reader, writer, first)
            return "peer"
        await self._handle_session_conn(reader, writer, first)
        return "session"

    async def _handle_peer_conn(self, reader, writer, hello: dict) -> None:
        """Exchange data plane from one peer worker: route exg_data
        frames to their registered inputs; the same socket carries the
        consumption acks back (reference: exchange_service.rs:74-133).
        On disconnect every edge fed by this peer is failed loudly —
        a silently starved merge would wedge barrier collection."""
        wlock = asyncio.Lock()
        fed: set[int] = set()
        peer = hello.get("worker")
        link = (f"w{self.worker_id}->w{peer}" if peer is not None
                else f"w{self.worker_id}->peer")
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                t = frame.get("type")
                if t == "exg_data":
                    chan = frame["chan"]
                    inp = self.exchange_inputs.get(chan)
                    if inp is not None:
                        fed.add(chan)
                        inp.feed_wire(frame["msg"], writer, wlock,
                                      seq=frame.get("seq"))
                elif t == "exg_ping":
                    # keepalive probe: answer on the same socket so a
                    # half-open link (answer eaten, or this process
                    # wedged) times out on the prober's side
                    try:
                        await write_frame(
                            writer, {"type": "exg_pong",
                                     "seq": frame.get("seq", 0)},
                            wlock, link=link, meta=True)
                    except (ConnectionError, OSError):
                        break
        finally:
            for chan in fed:
                inp = self.exchange_inputs.get(chan)
                if inp is not None:
                    inp.peer_lost()
            writer.close()

    async def _handle_session_conn(self, reader, writer,
                                   first: Optional[dict]) -> None:
        self._writer = writer
        tasks: list[asyncio.Task] = []
        frame = first
        try:
            while True:
                if frame is None:
                    break                        # session died: exit
                t = frame["type"]
                if t == "data":
                    ch = self.channels.get(frame["chan"])
                    if ch is not None:
                        ch.feed(frame["msg"], frame.get("seq"))
                elif t == "barrier":
                    tasks.append(
                        asyncio.ensure_future(self.handle_barrier(frame)))
                elif t == "commit":
                    # phase 2 of the cluster checkpoint: every job's
                    # staged state for the epoch becomes durable —
                    # except jobs the session excludes (a spanning job
                    # with a dead peer must not have its SURVIVING
                    # fragments' torn epochs committed under it) and
                    # jobs whose deployment generation FENCES this frame
                    # (a stale pre-recovery commit must not promote a
                    # rebuilt job's staged epochs)
                    skip = set(frame.get("skip_jobs") or ())
                    cgen = frame.get("gen")
                    for jname, store in self.stores.items():
                        if jname in skip:
                            continue
                        if cgen is not None \
                                and self.job_gens.get(jname, 0) > int(cgen):
                            self.fenced_frames += 1
                            continue
                        store.commit(int(frame["epoch"]))
                elif t == "create_job":
                    await self._reply(frame, self.handle_create_job)
                elif t == "create_fragments":
                    await self._reply(frame, self.handle_create_fragments)
                elif t == "job_epochs":
                    async def _je(f):
                        return self.handle_job_epochs(f)
                    await self._reply(frame, _je)
                elif t == "rescale_export":
                    async def _re(f):
                        return self.handle_rescale_export(f)
                    await self._reply(frame, _re)
                elif t == "set_rate":
                    async def _sr(f):
                        return self.handle_set_rate(f)
                    await self._reply(frame, _sr)
                elif t == "drop_job":
                    await self._reply(frame, self.handle_drop_job)
                elif t == "scan":
                    async def _scan(f):
                        return self.handle_scan(f)
                    await self._reply(frame, _scan)
                elif t == "stats":
                    async def _stats(f):
                        return self.handle_stats(f)
                    await self._reply(frame, _stats, meta=True)
                elif t == "batch_task":
                    async def _bt(f):
                        return self.handle_batch_task(f)
                    await self._reply(frame, _bt)
                elif t == "shutdown":
                    await self.send({"type": "reply", "rid": frame["rid"],
                                     "ok": True})
                    break
                else:
                    await self.send({"type": "reply",
                                     "rid": frame.get("rid"),
                                     "ok": False,
                                     "error": f"unknown frame {t!r}"})
                frame = await read_frame(reader)
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
            for job in self.jobs.values():
                await job.stop()
            writer.close()


def _channel_roots(job: StreamJob):
    """The _ChannelSource leaves of a job's pipeline (walked, not
    registered: channels are created inside the build factory)."""
    out = []
    stack = [job.pipeline]
    while stack:
        node = stack.pop()
        if isinstance(node, _ChannelSource):
            out.append(node)
            continue
        for attr in ("input", "inner", "left", "right"):
            child = getattr(node, attr, None)
            if isinstance(child, Executor):
                stack.append(child)
        for child in getattr(node, "inputs", ()):
            stack.append(child)
    return out


async def amain(data_dir: str, worker_id: int, port: int) -> None:
    import os
    from ..common.failpoint import arm_from_env
    from ..rpc.faults import install_from_env
    # adopt the spawning session's chaos schedule (RWTPU_CHAOS env);
    # injections append to a per-worker trace file so a killed worker's
    # pre-death trace survives for seeded-replay comparison. The
    # crash-point sweep arms process-exit failpoints the same way
    # (RWTPU_FAILPOINTS) — a worker dies AT the armed 2PC site.
    install_from_env(trace_path=os.path.join(data_dir,
                                             "chaos_trace.jsonl"))
    arm_from_env(worker_id=worker_id)
    host = WorkerHost(data_dir, worker_id)
    done = asyncio.Event()

    async def conn(reader, writer):
        kind = None
        try:
            kind = await host.handle_conn(reader, writer)
        finally:
            # peer (worker↔worker exchange) connections come and go with
            # jobs. Losing the SESSION's control socket — or an
            # unexpected handler crash (kind still None) — ends the
            # process. An "empty" close (no frame before EOF) is a stray
            # probe IF a session already attached; before any session
            # ever attached it can only be the spawning session dying
            # mid-connect — exit rather than orphan the process.
            if kind == "peer":
                return
            if kind == "empty" and host._writer is not None:
                return
            done.set()

    server = await asyncio.start_server(conn, "127.0.0.1", port)
    actual = server.sockets[0].getsockname()[1]
    print(f"WORKER_READY {actual}", flush=True)
    async with server:
        await done.wait()


def main(argv=None) -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    asyncio.run(amain(args.data_dir, args.worker_id, args.port))


if __name__ == "__main__":
    main()
