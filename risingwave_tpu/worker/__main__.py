"""``python -m risingwave_tpu.worker`` — worker-node entry point
(reference: the compute-node binary, src/cmd/src/bin/compute_node.rs)."""

import os

# a worker spawned for a CPU session must not touch the TPU tunnel
if os.environ.get("JAX_PLATFORMS") == "cpu":
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

from .host import main

main()
