"""Compactor worker: the dedicated, stateless LSM-compaction role.

Counterpart of the reference's standalone compactor node (reference:
src/storage/compactor/src/server.rs:57 — a stateless worker that pulls
``CompactTask``s from the meta's Hummock manager, rewrites overlapping
L0 runs into sorted L1 runs against the SHARED object store, and reports
results back; the meta commits the version swap). Completing the
four-role cluster shape: frontend / compute / compactor / meta.

Process protocol (length-prefixed JSON frames, rpc/wire.py):

    meta → compactor   {"type":"compact_task","rid",
                        "task": CompactTask.to_wire(), "delay_ms"?}
    compactor → meta   {"type":"reply","rid","ok":true,
                        "outputs":[names],"n_inputs","duration_ms"}
    meta → compactor   {"type":"stats","rid"} → counters + span drain
    meta → compactor   {"type":"shutdown","rid"}

The compactor never touches the version manifest: it only reads input
SSTs and writes output SSTs (orphans until the meta's version swap
references them), so a ``kill -9`` at ANY point leaves the store exactly
at its last committed version — the meta cancels the task and
reschedules; half-written outputs are vacuum food.

``CompactorClient`` is the meta/session-side handle: subprocess spawn +
synchronous request/reply socket (mirrors frontend/remote.py's
RemoteWorker, minus the data plane the compactor doesn't have).
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional

from ..rpc.wire import (
    read_frame, read_frame_sync, write_frame, write_frame_sync,
)
from ..storage.hummock import CompactTask, run_compact_task
from ..storage.object_store import open_object_store


class CompactorHost:
    """One compactor process: object store handle + task loop."""

    def __init__(self, data_dir: str, worker_id: int = 0):
        # retried IO: a transient read/write fault mid-merge costs a
        # backoff, not a failed task report + rescheduled compaction
        self.store = open_object_store(data_dir)
        self.worker_id = worker_id
        self.stats = {
            "tasks_completed": 0,
            "tasks_failed": 0,
            "ssts_written": 0,
            "busy_ms": 0.0,
        }

    def handle_compact(self, frame: dict) -> dict:
        task = CompactTask.from_wire(frame["task"])
        delay = frame.get("delay_ms")
        if delay:
            # test hook: widen the in-flight window deterministically so
            # chaos tests can kill -9 mid-task (tests/test_compactor.py)
            time.sleep(delay / 1000)
        t0 = time.perf_counter()
        try:
            outputs = run_compact_task(self.store, task)
        except Exception as e:  # noqa: BLE001 - shipped to the meta side
            self.stats["tasks_failed"] += 1
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        dur = (time.perf_counter() - t0) * 1e3
        self.stats["tasks_completed"] += 1
        self.stats["ssts_written"] += len(outputs)
        self.stats["busy_ms"] += dur
        return {"ok": True, "outputs": outputs,
                "n_inputs": len(task.inputs),
                "duration_ms": round(dur, 3)}

    def handle_stats(self) -> dict:
        from ..common.tracing import GLOBAL_TRACE
        return {"ok": True, "worker_id": self.worker_id,
                "compactor": dict(self.stats),
                "spans": [s.to_dict() for s in GLOBAL_TRACE.drain()]}

    async def handle_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break                      # meta side went away
                t = frame.get("type")
                if t == "compact_task":
                    # the merge is CPU+IO bound: run it off the event
                    # loop so a long task doesn't starve stats requests
                    resp = await asyncio.get_running_loop()\
                        .run_in_executor(None, self.handle_compact, frame)
                elif t == "stats":
                    resp = self.handle_stats()
                elif t == "shutdown":
                    await write_frame(writer, {"type": "reply",
                                               "rid": frame.get("rid"),
                                               "ok": True})
                    break
                else:
                    resp = {"ok": False, "error": f"unknown frame {t!r}"}
                resp.update({"type": "reply", "rid": frame.get("rid")})
                await write_frame(writer, resp)
        finally:
            writer.close()


async def amain(data_dir: str, worker_id: int, port: int) -> None:
    host = CompactorHost(data_dir, worker_id)
    done = asyncio.Event()

    async def conn(reader, writer):
        try:
            await host.handle_conn(reader, writer)
        finally:
            done.set()

    server = await asyncio.start_server(conn, "127.0.0.1", port)
    actual = server.sockets[0].getsockname()[1]
    print(f"COMPACTOR_READY {actual}", flush=True)
    async with server:
        await done.wait()


def main(argv=None) -> None:
    import argparse
    ap = argparse.ArgumentParser(
        description="dedicated Hummock-lite compaction worker")
    ap.add_argument("--data-dir", required=True,
                    help="shared object-store root (same dir the "
                         "session's state store writes)")
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    asyncio.run(amain(args.data_dir, args.worker_id, args.port))


# -- meta/session-side client -------------------------------------------------

class CompactorDied(RuntimeError):
    pass


class CompactorClient:
    """Spawn + drive one compactor process, synchronously (the caller is
    the session's background compaction pump thread, never the barrier
    path)."""

    SPAWN_TIMEOUT_S = 60.0

    def __init__(self, data_dir: str, worker_id: int = 0):
        self.data_dir = data_dir
        self.worker_id = worker_id
        self.proc: Optional[subprocess.Popen] = None
        self.sock: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._rid = 0
        self.dead = True

    def spawn(self) -> None:
        env = dict(os.environ)
        # the compactor never touches an accelerator: force CPU so a
        # wedged TPU tunnel can't hang its (jax-free) startup path
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "risingwave_tpu.worker.compactor",
             "--data-dir", self.data_dir,
             "--worker-id", str(self.worker_id), "--port", "0"],
            stdout=subprocess.PIPE, stderr=None, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        assert self.proc.stdout is not None
        deadline = time.monotonic() + self.SPAWN_TIMEOUT_S
        import select
        buf = b""
        fd = self.proc.stdout.fileno()
        port = None
        while time.monotonic() < deadline:
            ready, _, _ = select.select(
                [fd], [], [], max(0.05, deadline - time.monotonic()))
            if not ready:
                continue
            chunk = os.read(fd, 4096)
            if not chunk:
                raise CompactorDied(
                    f"compactor {self.worker_id} exited during startup "
                    f"(rc={self.proc.poll()})")
            buf += chunk
            for line in buf.decode(errors="replace").splitlines():
                if line.startswith("COMPACTOR_READY"):
                    port = int(line.split()[1])
                    break
            if port is not None:
                break
        if port is None:
            self.proc.kill()
            raise CompactorDied(
                f"compactor {self.worker_id} startup timed out")
        self.port = port
        self.sock = socket.create_connection(("127.0.0.1", port))
        self.dead = False

    def respawn(self) -> None:
        """Fresh process over the same shared store (it is stateless —
        nothing to recover)."""
        self.terminate()
        self.spawn()

    def terminate(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.dead = True

    def kill9(self) -> None:
        """Chaos hook: SIGKILL mid-task (tests/test_compactor.py)."""
        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, signal.SIGKILL)
            self.proc.wait()
        self.dead = True

    # -- request/reply ---------------------------------------------------------

    def request(self, obj: dict, timeout: Optional[float] = None) -> dict:
        if self.dead or self.sock is None:
            raise CompactorDied("compactor is down")
        self._rid += 1
        obj = {**obj, "rid": self._rid}
        try:
            self.sock.settimeout(timeout)
            # compactor control frames ride the fault plane too
            # (rpc/faults.py link "s->c<k>"): a chaos schedule can drop
            # or delay the meta→compactor conversation deterministically
            write_frame_sync(self.sock, obj,
                             link=f"s->c{self.worker_id}")
            while True:
                resp = read_frame_sync(self.sock)
                if resp is None:
                    raise CompactorDied("compactor connection lost")
                if resp.get("rid") == self._rid:
                    return resp
        except (OSError, socket.timeout) as e:
            self.dead = True
            raise CompactorDied(f"compactor request failed: {e}") from e

    def compact(self, task: CompactTask,
                delay_ms: Optional[int] = None,
                timeout: Optional[float] = 600.0) -> List[str]:
        req: dict = {"type": "compact_task", "task": task.to_wire()}
        if delay_ms:
            req["delay_ms"] = delay_ms
        resp = self.request(req, timeout=timeout)
        if resp.get("ok") is False:
            raise RuntimeError(
                f"compactor {self.worker_id}: {resp.get('error')}")
        return list(resp["outputs"])

    def get_stats(self, timeout: float = 10.0) -> dict:
        return self.request({"type": "stats"}, timeout=timeout)

    def shutdown(self) -> None:
        try:
            self.request({"type": "shutdown"}, timeout=5.0)
        except (CompactorDied, RuntimeError):
            pass
        self.terminate()


if __name__ == "__main__":
    main()
