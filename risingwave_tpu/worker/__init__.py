from .host import WorkerHost  # noqa: F401

# NOTE: worker.compactor is deliberately NOT imported here — the module
# doubles as a ``python -m risingwave_tpu.worker.compactor`` entry point,
# and importing it from the package __init__ would shadow runpy's module
# execution (sys.modules warning). Import it explicitly.
