from .host import WorkerHost  # noqa: F401
