"""Plan-graph serialization: plan tree ↔ JSON.

Counterpart of the reference's proto plan boundary
(reference: proto/stream_plan.proto + src/prost/ — the serialized plan
graph is the ONLY contract between frontend, meta, and compute nodes;
from_proto/mod.rs:119 rebuilds executors from it). Here the wire format
is JSON over the same shapes: every plan node / expression dataclass
round-trips, with catalog objects (tables/MVs/sources) carried as named
references resolved against the receiving side's catalog — exactly how
the reference ships table ids, not table contents.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from ..common.types import DataType, Field, Schema, TypeKind
from ..expr.agg import AggCall
from ..expr.expr import Cast, Expr, FunctionCall, InputRef, Literal
from ..ops.topn import OrderSpec
from ..stream.over_window import WindowCall
from ..stream.project_set import TableFuncCall
from . import planner as P

_PLAN_CLASSES = {
    cls.__name__: cls for cls in [
        P.PSource, P.PTableScan, P.PMvScan, P.PProject, P.PFilter,
        P.PHopWindow, P.PAgg, P.PJoin, P.PTopN, P.PDynFilter, P.PUnion,
        P.PValues, P.POverWindow, P.PProjectSet, P.PTemporalJoin,
        P.PExchange,
    ]
}
_AUX_CLASSES = {
    cls.__name__: cls for cls in [
        InputRef, Literal, FunctionCall, Cast, TableFuncCall, AggCall,
        OrderSpec, WindowCall, Field,
    ]
}


def _enc(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, DataType):
        return {"__dt__": v.kind.name, "scale": v.scale}
    if isinstance(v, Schema):
        return {"__schema__": [_enc(f) for f in v]}
    if isinstance(v, dict):
        return {"__map__": [[_enc(k), _enc(val)] for k, val in v.items()]}
    if isinstance(v, (tuple, list)):
        return {"__seq__": [_enc(x) for x in v]}
    cls = type(v).__name__
    if cls in _PLAN_CLASSES or cls in _AUX_CLASSES:
        out = {"__cls__": cls}
        for f in dataclasses.fields(v):
            out[f.name] = _enc(getattr(v, f.name))
        return out
    # catalog objects travel as named references (reference: plans carry
    # table ids, the receiving node resolves them against its catalog)
    for attr in ("name",):
        if hasattr(v, attr) and hasattr(v, "schema"):
            return {"__catalog__": getattr(v, attr)}
    raise TypeError(f"cannot serialize {type(v).__name__}")


def _dec(v: Any, catalog) -> Any:
    if not isinstance(v, dict):
        return v
    if "__dt__" in v:
        return DataType(TypeKind[v["__dt__"]], scale=v.get("scale", 0))
    if "__schema__" in v:
        return Schema(tuple(_dec(f, catalog) for f in v["__schema__"]))
    if "__map__" in v:
        return {_dec(k, catalog): _dec(val, catalog)
                for k, val in v["__map__"]}
    if "__seq__" in v:
        return tuple(_dec(x, catalog) for x in v["__seq__"])
    if "__catalog__" in v:
        name = v["__catalog__"]
        _, d = catalog.resolve_relation(name)
        return d
    cls_name = v["__cls__"]
    cls = _PLAN_CLASSES.get(cls_name) or _AUX_CLASSES[cls_name]
    kwargs = {
        k: _dec(val, catalog) for k, val in v.items() if k != "__cls__"
    }
    return cls(**kwargs)


def plan_to_json(plan: P.PlanNode) -> str:
    return json.dumps(_enc(plan))


def plan_from_json(data: str, catalog) -> P.PlanNode:
    return _dec(json.loads(data), catalog)


# -- catalog-def shipping -----------------------------------------------------
# Plans carry catalog objects as NAMED references (above); a remote worker
# therefore needs the referenced definitions delivered out-of-band — the
# reference ships catalog snapshots to compute nodes via meta notifications
# (src/meta/src/manager/notification.rs); here the session sends the defs
# a job's plan closes over, right before the plan itself.

def defs_to_json(defs: list) -> str:
    from .catalog import MaterializedViewDef, SourceDef, TableDef
    kinds = {SourceDef: "source", TableDef: "table",
             MaterializedViewDef: "mv"}
    out = []
    for d in defs:
        kind = kinds[type(d)]
        enc = {f.name: _enc(getattr(d, f.name))
               for f in dataclasses.fields(d)}
        out.append({"__def__": kind, **enc})
    return json.dumps(out)


def defs_from_json(data: str) -> list:
    from .catalog import MaterializedViewDef, SourceDef, TableDef
    kinds = {"source": SourceDef, "table": TableDef, "mv": MaterializedViewDef}
    out = []
    for item in json.loads(data):
        cls = kinds[item.pop("__def__")]
        kwargs = {k: _dec(v, None) for k, v in item.items()}
        out.append(cls(**kwargs))
    return out
