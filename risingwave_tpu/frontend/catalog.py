"""Catalog: sources, tables, materialized views, indexes.

Counterpart of the reference's CatalogManager + frontend catalog cache
(reference: src/meta/src/manager/catalog/, src/frontend/src/catalog/ —
single-process here, one authoritative copy; the meta/frontend split returns
when the cluster runtime lands).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from ..common.types import (
    BOOL, DATE, FLOAT32, FLOAT64, INT16, INT32, INT64, INTERVAL, JSONB,
    TIME, TIMESTAMP, VARCHAR, DataType, Field, Schema, decimal,
)

_TYPE_NAMES: dict[str, DataType] = {
    "boolean": BOOL, "bool": BOOL,
    "smallint": INT16, "int2": INT16,
    "int": INT32, "integer": INT32, "int4": INT32,
    "bigint": INT64, "int8": INT64,
    "real": FLOAT32, "float4": FLOAT32,
    "double": FLOAT64, "float8": FLOAT64, "float": FLOAT64,
    "decimal": decimal(), "numeric": decimal(),
    "date": DATE, "time": TIME,
    "timestamp": TIMESTAMP, "timestamptz": TIMESTAMP,
    "interval": INTERVAL,
    "varchar": VARCHAR, "text": VARCHAR, "string": VARCHAR,
    "serial": INT64,
    "jsonb": JSONB, "json": JSONB,
}


def _split_struct_body(body: str) -> list:
    """Split 'a bigint, b struct<x int, y int>' on TOP-LEVEL commas."""
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts


def type_from_name(name: str) -> DataType:
    low = name.lower().strip()
    if low.startswith("struct<") and low.endswith(">"):
        from ..common.types import struct_of
        fields = []
        for part in _split_struct_body(low[len("struct<"):-1]):
            fname, _, ftype = part.strip().partition(" ")
            fields.append((fname, type_from_name(ftype.strip())))
        return struct_of(*fields)
    t = _TYPE_NAMES.get(low)
    if t is None:
        raise ValueError(f"unknown type name {name!r}")
    return t


@dataclasses.dataclass
class SourceDef:
    name: str
    schema: Schema
    connector: str
    options: dict
    watermark: Optional[tuple] = None      # (col_name, delay_us)
    append_only: bool = True


@dataclasses.dataclass
class TableDef:
    name: str
    schema: Schema
    pk: tuple                               # column indices
    table_id: int = -1
    append_only: bool = False


@dataclasses.dataclass
class MaterializedViewDef:
    name: str
    schema: Schema
    pk: tuple                               # column indices into schema
    table_id: int = -1
    definition: str = ""


@dataclasses.dataclass
class SinkDef:
    """Reference: sink catalog entry (src/connector/src/sink/catalog/).
    ``table_id`` is the log-store table; ``progress_table_id`` holds the
    delivered-epoch/position row (stream/sink.py)."""

    name: str
    schema: Schema
    connector: str
    options: dict
    from_name: str = ""
    table_id: int = -1
    progress_table_id: int = -1


@dataclasses.dataclass
class IndexDef:
    name: str
    table: str
    columns: tuple
    #: hidden MV materializing (index cols ⧺ remaining visible cols) with
    #: state-table pk = index cols ⧺ base pk — the arrangement batch
    #: lookups prefix-scan (reference: index = StreamMaterialize ordered
    #: by index columns, src/frontend/src/handler/create_index.rs)
    mv_name: str = ""


class CatalogError(ValueError):
    pass


def strip_schema(name: str) -> str:
    """Normalize a possibly schema-qualified relation name: the catalog
    is keyed on bare names and everything lives in 'public' (BI tools
    qualify with the schema pg_tables reports)."""
    return name[len("public."):] if name.startswith("public.") else name


class Catalog:
    def __init__(self) -> None:
        self.sources: dict[str, SourceDef] = {}
        self.tables: dict[str, TableDef] = {}
        self.mvs: dict[str, MaterializedViewDef] = {}
        self.sinks: dict[str, SinkDef] = {}
        self.indexes: dict[str, IndexDef] = {}
        # plain int (not itertools.count) so DDL can roll it back on failure:
        # a failed statement must not shift later statements' table ids or
        # recovery replay (which skips failed DDL) would allocate different
        # ids than the original run
        self._next_table_id = 1

    def next_table_id(self) -> int:
        i = self._next_table_id
        self._next_table_id += 1
        return i

    def _check_free(self, name: str) -> None:
        for reg in (self.sources, self.tables, self.mvs, self.sinks,
                    self.indexes):
            if name in reg:
                raise CatalogError(f"name {name!r} already in use")

    def add_source(self, s: SourceDef) -> None:
        self._check_free(s.name)
        self.sources[s.name] = s

    def add_table(self, t: TableDef) -> None:
        self._check_free(t.name)
        if t.table_id < 0:
            t.table_id = self.next_table_id()
        self.tables[t.name] = t

    def add_mv(self, mv: MaterializedViewDef) -> None:
        self._check_free(mv.name)
        if mv.table_id < 0:
            mv.table_id = self.next_table_id()
        self.mvs[mv.name] = mv

    def add_sink(self, s: SinkDef) -> None:
        self._check_free(s.name)
        self.sinks[s.name] = s

    def add_index(self, ix: IndexDef) -> None:
        self._check_free(ix.name)
        self.indexes[ix.name] = ix

    def resolve_relation(self, name: str):
        """-> ("source"|"table"|"mv", def)"""
        name = strip_schema(name)
        if name in self.sources:
            return "source", self.sources[name]
        if name in self.tables:
            return "table", self.tables[name]
        if name in self.mvs:
            return "mv", self.mvs[name]
        raise CatalogError(f"relation {name!r} not found")

    def drop(self, kind: str, name: str, if_exists: bool = False) -> bool:
        name = strip_schema(name)
        reg = {
            "source": self.sources, "table": self.tables,
            "materialized_view": self.mvs, "sink": self.sinks,
            "index": self.indexes,
        }[kind]
        if name not in reg:
            if if_exists:
                return False
            raise CatalogError(f"{kind} {name!r} not found")
        del reg[name]
        return True
