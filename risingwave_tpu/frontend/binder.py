"""Binder: name resolution of AST expressions against a column scope.

Counterpart of the reference's Binder (reference: src/frontend/src/binder/
mod.rs:78,269). One deliberate simplification vs the reference: bound
expressions ARE the runtime expression objects (risingwave_tpu.expr) — there
is no separate frontend IR to re-lower, because the runtime exprs are
already pure plan-time trees that inline into jitted steps (expr/expr.py).
Aggregate calls are extracted (not evaluable row-wise) and replaced by
references into the agg operator's output.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ..common.types import (
    BOOL, DATE, FLOAT64, INT32, INT64, INTERVAL, TIMESTAMP, VARCHAR,
    DataType, Field, Schema, TypeKind,
)


def _parse_date(s: str) -> int:
    """ISO date string → days since the Unix epoch (DATE physical)."""
    import datetime as _dt
    return (_dt.date.fromisoformat(s.strip()) - _dt.date(1970, 1, 1)).days


def _parse_timestamp(s: str) -> int:
    """ISO timestamp string (naive = UTC) → epoch microseconds (exact
    integer arithmetic; float seconds would drop microseconds)."""
    import datetime as _dt
    dt = _dt.datetime.fromisoformat(s.strip())
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
    return (dt - epoch) // _dt.timedelta(microseconds=1)
from ..expr.agg import AggCall
from ..expr.expr import Cast as RCast, Expr, InputRef, Literal, call, cast
from . import sqlast as A
from .catalog import type_from_name


class BindError(ValueError):
    pass


@dataclasses.dataclass
class ScopeColumn:
    name: str
    table: Optional[str]
    index: int
    type: DataType


class Scope:
    """Visible columns during binding, with table-alias qualification."""

    def __init__(self, columns: Sequence[ScopeColumn]):
        self.columns = list(columns)

    @staticmethod
    def of_schema(schema: Schema, table: Optional[str] = None,
                  offset: int = 0) -> "Scope":
        return Scope([
            ScopeColumn(f.name, table, offset + i, f.type)
            for i, f in enumerate(schema)
        ])

    def concat(self, other: "Scope", offset: int) -> "Scope":
        """``offset``: width of the left relation's SCHEMA (not scope — a
        scope may hide internal pk columns, but indices address the schema)."""
        return Scope(self.columns + [
            dataclasses.replace(c, index=c.index + offset)
            for c in other.columns
        ])

    def resolve(self, name: str, table: Optional[str]) -> ScopeColumn:
        matches = [
            c for c in self.columns
            if c.name == name and (table is None or c.table == table)
        ]
        if not matches:
            raise BindError(f"column {table + '.' if table else ''}{name} not found")
        if len(matches) > 1:
            raise BindError(f"column reference {name!r} is ambiguous")
        return matches[0]


_BINOP_FN = {
    "+": "add", "-": "subtract", "*": "multiply", "/": "divide",
    "%": "modulus", "=": "equal", "<>": "not_equal", "<": "less_than",
    "<=": "less_than_or_equal", ">": "greater_than",
    ">=": "greater_than_or_equal", "AND": "and", "OR": "or",
    "||": "concat_op", "LIKE": "like", "NOT LIKE": "not_like",
}

AGG_KINDS = {"count", "sum", "min", "max", "avg",
             "approx_count_distinct",
             # materialized-input kinds (stream/materialized_agg.py)
             "array_agg", "string_agg", "percentile_cont", "mode"}

#: aggs taking a constant second argument, stored on AggCall.extra
_EXTRA_ARG_AGGS = {"string_agg", "percentile_cont"}

RANK_FUNC_KINDS = {"row_number", "rank", "dense_rank"}
WINDOW_ONLY_KINDS = RANK_FUNC_KINDS | {"lag", "lead"}


@dataclasses.dataclass
class BoundAgg:
    """An aggregate call found during binding + where its output will land."""

    call: AggCall
    output_index: int     # index in the agg operator's output (after keys)


@dataclasses.dataclass
class BoundWindow:
    """A window function call found during binding (planner turns the set
    of these into one POverWindow node; all calls must share the same
    PARTITION BY / ORDER BY)."""

    kind: str
    output_type: DataType
    arg_expr: Optional[Expr]           # lag/lead/agg argument
    offset: int                        # lag/lead distance
    partition_exprs: tuple             # Expr...
    order_exprs: tuple                 # (Expr, desc, nulls_last)...


def _const_int(e: Expr) -> Optional[int]:
    """Constant-fold an integer literal (incl. unary minus)."""
    from ..expr.expr import FunctionCall
    if isinstance(e, Literal) and e.value is not None:
        return int(e.value)
    if (isinstance(e, FunctionCall) and e.name == "neg"
            and len(e.args) == 1 and isinstance(e.args[0], Literal)
            and e.args[0].value is not None):
        return -int(e.args[0].value)
    return None


class ExprBinder:
    """Binds one expression tree. ``agg_ctx`` non-None => aggregate calls are
    allowed and collected (SELECT/HAVING position in a GROUP BY query)."""

    def __init__(self, scope: Scope, agg_ctx: Optional[list] = None,
                 subquery_sink: Optional[list] = None,
                 win_ctx: Optional[list] = None):
        self.scope = scope
        self.agg_ctx = agg_ctx
        self.subquery_sink = subquery_sink
        self.win_ctx = win_ctx

    def bind(self, node) -> Expr:
        if isinstance(node, A.ColumnRef):
            c = self.scope.resolve(node.name, node.table)
            return InputRef(c.index, c.type)
        if isinstance(node, A.Lit):
            return self._literal(node)
        if isinstance(node, A.BinaryOp):
            return self._binop(node)
        if isinstance(node, A.UnaryOp):
            if node.op == "NOT":
                return call("not", self.bind(node.operand))
            if node.op == "-":
                b = self.bind(node.operand)
                if isinstance(b, Literal) and b.value is not None:
                    return Literal(-b.value, b.type)
                return call("neg", b)
            raise BindError(f"unsupported unary op {node.op}")
        if isinstance(node, A.FuncCall):
            return self._func(node)
        if isinstance(node, A.Case):
            args = []
            for cond, res in node.branches:
                args.append(self.bind(cond))
                args.append(self.bind(res))
            if node.else_result is not None:
                args.append(self.bind(node.else_result))
            return call("case", *args)
        if isinstance(node, A.InList):
            e = self.bind(node.expr)
            cmps = [call("equal", e, self.bind(i)) for i in node.items]
            out = cmps[0]
            for c in cmps[1:]:
                out = call("or", out, c)
            return call("not", out) if node.negated else out
        if isinstance(node, A.Between):
            e = self.bind(node.expr)
            lo = call("greater_than_or_equal", e, self.bind(node.low))
            hi = call("less_than_or_equal", e, self.bind(node.high))
            rng = call("and", lo, hi)
            return call("not", rng) if node.negated else rng
        if isinstance(node, A.IsNull):
            fn = "is_not_null" if node.negated else "is_null"
            return call(fn, self.bind(node.expr))
        if isinstance(node, A.Cast):
            return cast(self.bind(node.expr), type_from_name(node.type_name))
        if isinstance(node, A.WindowFunc):
            return self._bind_window(node)
        if isinstance(node, A.ArrayLit):
            items = [self.bind(it) for it in node.items]
            if not all(isinstance(it, Literal) for it in items):
                raise BindError("ARRAY[…] elements must be constants")
            # unify element types: ints widen to INT64, any float makes
            # the whole array FLOAT64; mixed classes are a bind error
            kinds = {it.type.kind for it in items if it.value is not None}
            int_kinds = {TypeKind.INT16, TypeKind.INT32, TypeKind.INT64}
            float_kinds = {TypeKind.FLOAT32, TypeKind.FLOAT64}
            if not kinds:
                elem_kind = TypeKind.INT64
            elif kinds <= int_kinds:
                elem_kind = TypeKind.INT64
            elif kinds <= int_kinds | float_kinds:
                elem_kind = TypeKind.FLOAT64
            elif len(kinds) == 1:
                elem_kind = next(iter(kinds))
            else:
                raise BindError(
                    "ARRAY[…] elements must share one type; got "
                    + ", ".join(sorted(k.value for k in kinds)))
            conv = (float if elem_kind == TypeKind.FLOAT64 else
                    int if elem_kind == TypeKind.INT64 else
                    (lambda v: v))
            return Literal(
                tuple(None if it.value is None else conv(it.value)
                      for it in items),
                DataType(TypeKind.LIST, elem_kind=elem_kind))
        if isinstance(node, A.Subscript):
            return self._bind_subscript(node)
        if isinstance(node, A.FieldAccess):
            base = self.bind(node.expr)
            if not base.type.is_struct:
                raise BindError(
                    f"cannot access field {node.field!r} of a "
                    f"{base.type.kind.value} value")
            from ..expr.expr import FunctionCall as RFunctionCall
            idx = base.type.field_index(node.field)
            return RFunctionCall(
                "struct_field",
                (base, Literal(idx, INT64)),
                base.type.field_type(node.field))
        if isinstance(node, A.ScalarSubquery):
            if self.subquery_sink is None:
                raise BindError("scalar subquery not supported here")
            self.subquery_sink.append(node.query)
            # placeholder: planner rewrites the comparison into DynamicFilter
            return _SubqueryPlaceholder(len(self.subquery_sink) - 1)
        raise BindError(f"cannot bind {type(node).__name__}")

    def _bind_subscript(self, node: A.Subscript) -> Expr:
        """1-based element access. (regexp_match(s, p))[n] is rewritten to
        the scalar regexp_match_group(s, p, n) — the match-groups array
        never materializes (PG semantics: regexp_match returns text[] of
        capture groups; reference: src/expr/src/vector_op/regexp.rs)."""
        idx = self.bind(node.index)
        if (isinstance(node.expr, A.FuncCall)
                and node.expr.name.lower() == "regexp_match"):
            args = [self.bind(a) for a in node.expr.args]
            return call("regexp_match_group", *args, idx)
        base = self.bind(node.expr)
        if not base.type.is_list:
            raise BindError(
                f"cannot subscript a {base.type.kind.value} value")
        return call("array_access", base, idx)

    def _literal(self, node: A.Lit) -> Literal:
        v = node.value
        if v is None:
            return Literal(None, INT64)
        if node.type_hint == "interval":
            return Literal(v, INTERVAL)
        if node.type_hint == "varchar":
            return Literal(v, VARCHAR)
        if node.type_hint == "date":
            return Literal(_parse_date(str(v)), DATE)
        if node.type_hint == "timestamp":
            return Literal(_parse_timestamp(str(v)), TIMESTAMP)
        if isinstance(v, bool):
            return Literal(v, BOOL)
        if isinstance(v, int):
            return Literal(v, INT64 if abs(v) > 2**31 - 1 else INT32)
        if isinstance(v, float):
            return Literal(v, FLOAT64)
        raise BindError(f"cannot bind literal {v!r}")

    def _binop(self, node: A.BinaryOp) -> Expr:
        if node.op in ("->", "->>"):
            left = self.bind(node.left)
            right = self.bind(node.right)
            if left.type.kind != TypeKind.JSONB:
                raise BindError(
                    f"{node.op} requires a jsonb left operand; got "
                    f"{left.type.kind.value}")
            text = node.op == "->>"
            if right.type.kind == TypeKind.VARCHAR:
                fn = "jsonb_get_field_text" if text else "jsonb_get_field"
            elif right.type.is_integral:
                fn = "jsonb_get_elem_text" if text else "jsonb_get_elem"
            else:
                # is_string also covers JSONB/BYTEA — their serialized
                # text silently used as a key would mask a type error
                raise BindError(f"{node.op} key must be text or integer")
            return call(fn, left, right)
        fn = _BINOP_FN.get(node.op)
        if fn is None:
            raise BindError(f"unsupported operator {node.op}")
        left, right = self.bind(node.left), self.bind(node.right)
        if fn in ("concat_op", "like", "not_like"):
            # the impls interpret values as dictionary ids — a non-string
            # operand would silently decode garbage
            for side in (left, right):
                if not side.type.is_string:
                    raise BindError(
                        f"{node.op} requires varchar operands; got "
                        f"{side.type.kind.value} (cast to varchar first)")
        return call(fn, left, right)

    def _func(self, node: A.FuncCall) -> Expr:
        name = node.name.lower()
        if name in WINDOW_ONLY_KINDS:
            raise BindError(f"{name}() requires an OVER clause")
        from ..stream.project_set import TABLE_FUNC_KINDS, TableFuncCall
        if name in TABLE_FUNC_KINDS:
            args = tuple(self.bind(a) for a in node.args)
            from ..common.types import VARCHAR as _VC
            if name == "regexp_split_to_table":
                out_t = _VC
            elif name == "unnest":
                if not args or not args[0].type.is_list:
                    raise BindError("unnest() requires an array argument")
                out_t = args[0].type.elem_type
            else:
                out_t = INT64
            return TableFuncCall(name, args, out_t)
        if name == "extract":
            from ..expr.expr import make_extract
            field = node.args[0]
            assert isinstance(field, A.Lit)
            return make_extract(str(field.value), self.bind(node.args[1]))
        if name == "row":
            # ROW(c1, c2, …) composite constructor; PG names fields f1…fn
            items = [self.bind(a) for a in node.args]
            # const-fold a literal cast (ROW(1.23::decimal)) so the field
            # carries the cast's target type, scale included. Only a
            # VALUE-PRESERVING cast folds: a lossy one (1.9::bigint,
            # 1::varchar) would need the runtime Cast's rounding rules,
            # so it falls through to the constants check below instead of
            # silently diverging from `SELECT 1.9::bigint`
            def _fold(it: Expr) -> Expr:
                if not (isinstance(it, RCast)
                        and isinstance(it.arg, Literal)):
                    return it
                if it.arg.value is None:
                    return Literal(None, it.type)
                try:
                    v = it.type.to_python(it.type.to_physical(it.arg.value))
                    if v != it.arg.value:
                        return it
                except Exception:
                    return it
                return Literal(v, it.type)

            items = [_fold(it) for it in items]
            if not all(isinstance(it, Literal) for it in items):
                raise BindError("ROW(…) fields must be constants")
            from ..common.types import struct_of
            # full DataTypes, not bare kinds: decimal scale and list
            # element types must survive into the struct's field types or
            # field access / persistence decode at the wrong scale
            t = struct_of(*((f"f{i + 1}", it.type)
                            for i, it in enumerate(items)))
            return Literal(tuple(it.value for it in items), t)
        if name in AGG_KINDS:
            if self.agg_ctx is None:
                raise BindError(f"aggregate {name}() not allowed here")
            return self._bind_agg(name, node)
        args = [self.bind(a) for a in node.args]
        return call(name, *args)

    def _bind_window(self, node: A.WindowFunc) -> Expr:
        if self.win_ctx is None:
            raise BindError("window functions are not allowed here")
        kind = node.func.name.lower()
        if kind not in WINDOW_ONLY_KINDS | AGG_KINDS:
            raise BindError(f"{kind}() is not a window function")
        plain = ExprBinder(self.scope)
        args = [plain.bind(a) for a in node.func.args
                if not isinstance(a, A.Star)]
        arg_expr: Optional[Expr] = None
        offset = 1
        if kind in RANK_FUNC_KINDS:
            if args:
                raise BindError(f"{kind}() takes no arguments")
            out_t = INT64
        elif kind in ("lag", "lead"):
            if not 1 <= len(args) <= 2:
                raise BindError(f"{kind}(value [, offset]) expected")
            arg_expr = args[0]
            if len(args) == 2:
                off = _const_int(args[1])
                if off is None:
                    raise BindError(f"{kind}() offset must be a literal")
                if off < 0:
                    raise BindError(
                        f"{kind}() offset must be non-negative")
                offset = off
            out_t = arg_expr.type
        else:   # windowed aggregate
            if kind == "count" and not args:
                out_t = INT64
            else:
                if len(args) != 1:
                    raise BindError(f"{kind}() takes one argument")
                arg_expr = args[0]
                if arg_expr.type.is_string and kind != "count":
                    raise BindError(
                        f"window {kind}() over varchar is unsupported")
                out_t = AggCall(kind, -1, arg_expr.type).output_type
        partition = tuple(plain.bind(p) for p in node.partition_by)
        order = tuple(
            (plain.bind(oi.expr), oi.desc,
             oi.nulls_last if oi.nulls_last is not None else not oi.desc)
            for oi in node.order_by)
        bw = BoundWindow(kind, out_t, arg_expr, offset, partition, order)
        self.win_ctx.append(bw)
        return _WindowPlaceholder(len(self.win_ctx) - 1, out_t)

    def _bind_agg(self, kind: str, node: A.FuncCall) -> Expr:
        extra = None
        if kind in _EXTRA_ARG_AGGS:
            if len(node.args) != 2:
                raise BindError(
                    f"{kind}(value, constant) takes two arguments")
            const = self.bind(node.args[1])
            if not isinstance(const, Literal):
                raise BindError(f"{kind}()'s second argument must be a "
                                "constant")
            extra = const.value
            node = dataclasses.replace(node, args=node.args[:1])
        if len(node.args) > 1:
            raise BindError(f"{kind}() takes at most one argument")
        if node.filter is not None:
            # FILTER (WHERE c) rewrites to a CASE-wrapped argument: rows
            # failing c contribute NULL, which the aggregates here skip
            # (count counts non-NULL). count(*) FILTER (c) == count(CASE
            # WHEN c THEN 1 END). Works under DISTINCT too: distinct-ness
            # is over the surviving non-NULL values. (reference:
            # src/frontend/src/optimizer/plan_node/logical_agg.rs agg
            # filter support.) array_agg is the one NULL-KEEPING
            # aggregate — the rewrite would turn excluded rows into NULL
            # elements — so it is rejected rather than silently wrong.
            if kind == "array_agg":
                raise BindError(
                    "FILTER on array_agg is not supported (array_agg "
                    "keeps NULL elements; filter in a subquery instead)")
            if not node.args or isinstance(node.args[0], A.Star):
                if kind != "count":
                    raise BindError(f"{kind}(*) is not valid")
                wrapped: tuple = (A.Case(((node.filter, A.Lit(1)),), None),)
            else:
                wrapped = (A.Case(((node.filter, node.args[0]),), None),)
            node = dataclasses.replace(node, args=wrapped, filter=None)
        if not node.args or isinstance(node.args[0], A.Star):
            if kind != "count":
                raise BindError(f"{kind}(*) is not valid")
            acall = AggCall("count", -1, distinct=node.distinct)
        else:
            arg = ExprBinder(self.scope).bind(node.args[0])
            if not isinstance(arg, InputRef):
                # non-trivial agg args get a pre-projection by the planner;
                # record the expression itself
                acall = AggCall(kind, -2, arg.type, distinct=node.distinct,
                                extra=extra)
                bound = BoundAgg(acall, -1)
                bound.arg_expr = arg  # type: ignore[attr-defined]
                self.agg_ctx.append(bound)
                return _AggPlaceholder(len(self.agg_ctx) - 1, acall.output_type)
            acall = AggCall(kind, arg.index, arg.type, distinct=node.distinct,
                            extra=extra)
        # dedup identical agg calls
        for i, b in enumerate(self.agg_ctx):
            if b.call == acall and not hasattr(b, "arg_expr"):
                return _AggPlaceholder(i, acall.output_type)
        self.agg_ctx.append(BoundAgg(acall, -1))
        return _AggPlaceholder(len(self.agg_ctx) - 1, acall.output_type)


@dataclasses.dataclass(frozen=True, eq=False)
class _AggPlaceholder(Expr):
    """Stands for 'output of agg call #i'; the planner rewrites it to an
    InputRef over the agg operator's output schema."""

    agg_index: int
    type: DataType

    def eval(self, chunk):  # pragma: no cover
        raise RuntimeError("unresolved aggregate placeholder")


@dataclasses.dataclass(frozen=True, eq=False)
class _WindowPlaceholder(Expr):
    """Stands for 'output of window call #i'; the planner rewrites it to an
    InputRef over the over-window operator's output schema."""

    win_index: int
    type: DataType

    def eval(self, chunk):  # pragma: no cover
        raise RuntimeError("unresolved window placeholder")


@dataclasses.dataclass(frozen=True, eq=False)
class _SubqueryPlaceholder(Expr):
    """Stands for 'the scalar value of subquery #i' inside WHERE — only
    allowed as one side of a comparison, which the planner turns into a
    DynamicFilter (reference: dynamic_filter.rs pattern)."""

    subquery_index: int
    type: DataType = INT64

    def eval(self, chunk):  # pragma: no cover
        raise RuntimeError("unresolved subquery placeholder")


def rewrite_placeholders(e: Expr, mapping) -> Expr:
    """Replace _AggPlaceholder nodes via ``mapping(agg_index) -> Expr``."""
    from ..expr.expr import FunctionCall
    if isinstance(e, _AggPlaceholder):
        return mapping(e.agg_index)
    if isinstance(e, FunctionCall):
        new_args = tuple(rewrite_placeholders(a, mapping) for a in e.args)
        return dataclasses.replace(e, args=new_args)
    if isinstance(e, RCast):
        return dataclasses.replace(e, arg=rewrite_placeholders(e.arg, mapping))
    return e


def contains_placeholder(e: Expr, kind) -> bool:
    from ..expr.expr import FunctionCall
    if isinstance(e, kind):
        return True
    if isinstance(e, FunctionCall):
        return any(contains_placeholder(a, kind) for a in e.args)
    if isinstance(e, RCast):
        return contains_placeholder(e.arg, kind)
    return False
