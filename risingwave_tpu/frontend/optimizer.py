"""Logical optimizer: rule engine + column pruning over the stream plan.

Counterpart of the reference's optimizer pass pipeline
(reference: src/frontend/src/optimizer/logical_optimization.rs — ordered
stages, each a set of rules applied to fixpoint; rule trait at
src/frontend/src/optimizer/rule/mod.rs). The reference ships 45+ rules
over a Rust plan-node hierarchy; here the same architecture is scaled to
the plan tree in ``planner.py``:

* ``Rule`` — one local rewrite: ``apply(node) -> Optional[PlanNode]``
  (None = no match). Rules never inspect more than the node and its
  children, exactly like the reference's ``Rule::apply``.
* ``rewrite_fixpoint`` — bottom-up driver applying a stage's rules until
  no rule fires (the reference's ``HeuristicOptimizer`` with
  ``ApplyOrder::BottomUp``).
* ``prune_columns`` — the column-pruning pass (reference:
  ``prune_col`` on every plan node, optimizer/plan_node/*.rs): a
  top-down required-column analysis that narrows every operator's
  output to what its consumers read, inserting projections over wide
  leaves. On a TPU this is not cosmetic: chunk columns are device
  arrays, so every pruned column is HBM bandwidth saved in every
  executor step downstream.

Pushdown rules shipped (reference names in parens):

* FilterMerge          (``LogicalFilter::merge``)
* FilterProjectTranspose  (PushCalculationOfJoinRule / filter-project)
* FilterJoinPushdown   (``FilterJoinRule`` — conjunct routing by side,
                        outer-join safety table)
* FilterAggTranspose   (``FilterAggRule`` — group-key conjuncts only)
* FilterUnionTranspose (``FilterUnionRule``)
* ProjectMerge         (``ProjectMergeRule``)

Scalar-subquery unnesting lives in the planner (DynamicFilter lowering
for comparisons, constant-key left join otherwise — the uncorrelated
half of the reference's ApplyToJoinRule family); see
``planner._plan_dynamic_filter`` / ``_plan_scalar_subqueries``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from ..expr.expr import Cast, Expr, FunctionCall, InputRef, Literal, call
from ..ops.topn import OrderSpec
from . import planner as P

# -- expression utilities -----------------------------------------------------


def _expr_fields(e: Expr):
    """(field_name, value) pairs of e's dataclass fields."""
    return [(f.name, getattr(e, f.name)) for f in dataclasses.fields(e)]


def map_expr(e: Expr, fn) -> Expr:
    """Rebuild ``e`` with ``fn`` applied to every direct child Expr
    (generic over all Expr dataclasses: FunctionCall.args, Cast.arg,
    TableFuncCall.args, ...)."""
    changes = {}
    for name, v in _expr_fields(e):
        if isinstance(v, Expr):
            nv = fn(v)
            if nv is not v:
                changes[name] = nv
        elif isinstance(v, tuple) and any(isinstance(x, Expr) for x in v):
            nv = tuple(fn(x) if isinstance(x, Expr) else x for x in v)
            # identity compare: Expr overloads __eq__ into SQL sugar, so
            # tuple != would silently report "unchanged"
            if any(a is not b for a, b in zip(nv, v)):
                changes[name] = nv
    return dataclasses.replace(e, **changes) if changes else e


def expr_refs(e: Expr) -> frozenset:
    """Set of input column indices referenced by ``e``."""
    if isinstance(e, InputRef):
        return frozenset((e.index,))
    out: set = set()
    for _, v in _expr_fields(e):
        if isinstance(v, Expr):
            out |= expr_refs(v)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, Expr):
                    out |= expr_refs(x)
    return frozenset(out)


def remap_expr(e: Expr, mapping: dict) -> Expr:
    """Renumber every InputRef through ``mapping`` (old index -> new)."""
    if isinstance(e, InputRef):
        return InputRef(mapping[e.index], e.type)
    return map_expr(e, lambda c: remap_expr(c, mapping))


def subst_expr(e: Expr, exprs: Sequence[Expr]) -> Expr:
    """Replace every InputRef i with ``exprs[i]`` (projection compose)."""
    if isinstance(e, InputRef):
        return exprs[e.index]
    return map_expr(e, lambda c: subst_expr(c, exprs))


def conjuncts_of(e: Expr) -> list:
    if isinstance(e, FunctionCall) and e.name == "and":
        out: list = []
        for a in e.args:
            out.extend(conjuncts_of(a))
        return out
    return [e]


def conjoin(cs: Sequence[Expr]) -> Expr:
    out = cs[0]
    for c in cs[1:]:
        out = call("and", out, c)
    return out


# -- rule engine --------------------------------------------------------------


class Rule:
    """One local rewrite. ``apply`` returns the replacement node or None."""

    name = "rule"

    def apply(self, node: P.PlanNode) -> Optional[P.PlanNode]:
        raise NotImplementedError


_CHILD_FIELDS = {
    P.PProject: ("input",), P.PFilter: ("input",), P.PHopWindow: ("input",),
    P.PAgg: ("input",), P.PTopN: ("input",), P.POverWindow: ("input",),
    P.PProjectSet: ("input",), P.PTemporalJoin: ("input",),
    P.PJoin: ("left", "right"), P.PDynFilter: ("input", "right"),
}


def _with_children(node: P.PlanNode, kids: Sequence[P.PlanNode]) -> P.PlanNode:
    if isinstance(node, P.PUnion):
        return dataclasses.replace(node, inputs=tuple(kids))
    names = _CHILD_FIELDS.get(type(node))
    if not names:
        return node
    return dataclasses.replace(node, **dict(zip(names, kids)))


def rewrite_fixpoint(plan: P.PlanNode, rules: Sequence[Rule],
                     max_passes: int = 32) -> P.PlanNode:
    """Bottom-up rewrite to fixpoint. Each pass rewrites children first,
    then offers the node to every rule; repeated until a full pass makes
    no change (bounded — every shipped rule strictly reduces node count
    or moves filters downward, so this converges well before the cap)."""

    def one_pass(node: P.PlanNode):
        changed = False
        kids = list(node.children)
        if kids:
            new_kids = []
            for k in kids:
                nk, ch = one_pass(k)
                changed |= ch
                new_kids.append(nk)
            if changed:
                node = _with_children(node, new_kids)
        for r in rules:
            repl = r.apply(node)
            if repl is not None:
                return repl, True
        return node, changed

    for _ in range(max_passes):
        plan, changed = one_pass(plan)
        if not changed:
            break
    return plan


# -- pushdown rules -----------------------------------------------------------


class FilterMerge(Rule):
    """Filter(Filter(x, p1), p2) -> Filter(x, p1 AND p2)."""

    name = "filter_merge"

    def apply(self, node):
        if isinstance(node, P.PFilter) and isinstance(node.input, P.PFilter):
            inner = node.input
            return P.PFilter(
                schema=node.schema, pk=node.pk, input=inner.input,
                predicate=call("and", inner.predicate, node.predicate))
        return None


class FilterProjectTranspose(Rule):
    """Filter(Project(x, es), p) -> Project(Filter(x, p∘es), es).

    Sound because every projection expr is pure; the predicate is
    rewritten by substituting each InputRef with the projection expr it
    names, then evaluated against the projection's input."""

    name = "filter_project"

    def apply(self, node):
        if not (isinstance(node, P.PFilter)
                and isinstance(node.input, P.PProject)):
            return None
        proj = node.input
        pred = subst_expr(node.predicate, proj.exprs)
        return dataclasses.replace(
            proj,
            input=P.PFilter(schema=proj.input.schema, pk=proj.input.pk,
                            input=proj.input, predicate=pred))


#: join kinds through which a predicate on one side may be pushed into
#: that side's input. For outer joins only the PRESERVED side's
#: predicates push (a null-supplying side's predicate above the join also
#: rejects the padded rows, which pushing would instead convert into
#: pass-through padded rows — reference: FilterJoinRule's
#: can_push_left_from_filter / can_push_right_from_filter).
_PUSH_LEFT_KINDS = {"inner", "left", "left_semi", "left_anti"}
_PUSH_RIGHT_KINDS = {"inner", "right"}


class FilterJoinPushdown(Rule):
    """Route filter conjuncts above a join into the side they reference."""

    name = "filter_join"

    def apply(self, node):
        if not (isinstance(node, P.PFilter) and isinstance(node.input, P.PJoin)):
            return None
        j = node.input
        nl = len(j.left.schema)
        to_left, to_right, keep = [], [], []
        for c in conjuncts_of(node.predicate):
            refs = expr_refs(c)
            if refs and max(refs) < nl and j.kind in _PUSH_LEFT_KINDS:
                to_left.append(c)
            elif refs and min(refs) >= nl and j.kind in _PUSH_RIGHT_KINDS:
                to_right.append(remap_expr(c, {i: i - nl for i in refs}))
            else:
                keep.append(c)
        if not to_left and not to_right:
            return None
        left, right = j.left, j.right
        if to_left:
            left = P.PFilter(schema=left.schema, pk=left.pk, input=left,
                             predicate=conjoin(to_left))
        if to_right:
            right = P.PFilter(schema=right.schema, pk=right.pk, input=right,
                              predicate=conjoin(to_right))
        new_join = dataclasses.replace(j, left=left, right=right)
        if keep:
            return P.PFilter(schema=node.schema, pk=node.pk, input=new_join,
                             predicate=conjoin(keep))
        return new_join


class FilterAggTranspose(Rule):
    """Push group-key-only conjuncts below a hash agg (a group exists
    above iff its key rows exist below, so key predicates commute with
    grouping; agg-output predicates — HAVING — must stay above)."""

    name = "filter_agg"

    def apply(self, node):
        if not (isinstance(node, P.PFilter) and isinstance(node.input, P.PAgg)):
            return None
        agg = node.input
        nk = len(agg.group_keys)
        if nk == 0:
            return None
        down, keep = [], []
        for c in conjuncts_of(node.predicate):
            refs = expr_refs(c)
            if refs and max(refs) < nk:
                down.append(remap_expr(
                    c, {i: agg.group_keys[i] for i in refs}))
            else:
                keep.append(c)
        if not down:
            return None
        inp = P.PFilter(schema=agg.input.schema, pk=agg.input.pk,
                        input=agg.input, predicate=conjoin(down))
        new_agg = dataclasses.replace(agg, input=inp)
        if keep:
            return P.PFilter(schema=node.schema, pk=node.pk, input=new_agg,
                             predicate=conjoin(keep))
        return new_agg


class FilterUnionTranspose(Rule):
    """Filter(UnionAll(xs), p) -> UnionAll(Filter(x, p)...)."""

    name = "filter_union"

    def apply(self, node):
        if not (isinstance(node, P.PFilter) and isinstance(node.input, P.PUnion)):
            return None
        u = node.input
        return dataclasses.replace(u, inputs=tuple(
            P.PFilter(schema=i.schema, pk=i.pk, input=i,
                      predicate=node.predicate)
            for i in u.inputs))


class ProjectMerge(Rule):
    """Project(Project(x, inner), outer) -> Project(x, outer∘inner)."""

    name = "project_merge"

    def apply(self, node):
        if not (isinstance(node, P.PProject)
                and isinstance(node.input, P.PProject)):
            return None
        inner = node.input
        return dataclasses.replace(
            node, input=inner.input,
            exprs=tuple(subst_expr(e, inner.exprs) for e in node.exprs))


class RankFilterToGroupTopN(Rule):
    """``rownum <= k`` over a rank-family window → GroupTopN.

    Matches the planner's over-window shape  PProject(outer) → PFilter
    (rank CMP k) → PProject(post) → POverWindow([one rank-kind call])
    and replaces the window with  PTopN(group_by=partition,
    order=order, limit=k, with_ties = kind=='rank')  over the window's
    input; the dead rank column becomes a NULL literal for pruning to
    remove. This turns q9/q18-style "top row per key" from O(partition)
    window recompute per barrier into incremental per-group TopN
    maintenance (reference: over_window_to_topn_rule.rs; e2e q18 "covers
    group top-n").

    Runs BEFORE the pushdown stage: FilterProjectTranspose would
    otherwise dissolve the exact shape this matches."""

    name = "rank_filter_to_group_topn"

    def apply(self, node):
        if not isinstance(node, P.PProject):
            return None
        filt = node.input
        if not isinstance(filt, P.PFilter):
            return None
        post = filt.input
        if not isinstance(post, P.PProject):
            return None
        win = post.input
        if not isinstance(win, P.POverWindow) or win.eowc:
            return None
        if len(win.calls) != 1:
            return None
        wcall = win.calls[0]
        if wcall.kind not in ("row_number", "rank"):
            return None
        n_in = len(win.input.schema)
        rank_cols = [i for i, e in enumerate(post.exprs)
                     if isinstance(e, InputRef) and e.index == n_in]
        if len(rank_cols) != 1:
            return None
        rank_col = rank_cols[0]
        for i, e in enumerate(post.exprs):
            if i != rank_col and any(r >= n_in for r in expr_refs(e)):
                return None
        limit = self._limit_from_pred(filt.predicate, rank_col,
                                      wcall.kind)
        if limit is None:
            return None
        for e in node.exprs:                  # rank must be dead above
            if rank_col in expr_refs(e):
                return None
        topn = P.PTopN(
            schema=win.input.schema, pk=win.input.pk, input=win.input,
            order=tuple(wcall.order_by), limit=limit, offset=0,
            with_ties=(wcall.kind == "rank"),
            group_by=tuple(wcall.partition_by))
        from ..common.types import INT64
        new_exprs = list(post.exprs)
        new_exprs[rank_col] = Literal(None, INT64)
        new_post = dataclasses.replace(post, input=topn,
                                       exprs=tuple(new_exprs))
        return dataclasses.replace(node, input=new_post)

    @staticmethod
    def _limit_from_pred(pred, rank_col: int, kind: str):
        if not isinstance(pred, FunctionCall) or len(pred.args) != 2:
            return None
        a, b = pred.args
        if not (isinstance(a, InputRef) and a.index == rank_col
                and isinstance(b, Literal)
                and isinstance(b.value, int)):
            return None
        if pred.name == "less_than_or_equal" and b.value >= 1:
            return b.value
        if pred.name == "less_than" and b.value > 1:
            return b.value - 1
        if pred.name == "equal" and b.value == 1:
            return 1
        return None


#: shape-dependent rewrites that must see the planner's raw tree
PREPASS_RULES = (RankFilterToGroupTopN(),)

PUSHDOWN_RULES = (
    FilterMerge(), FilterProjectTranspose(), FilterJoinPushdown(),
    FilterAggTranspose(), FilterUnionTranspose(),
)
CLEANUP_RULES = (ProjectMerge(), FilterMerge())


# -- column pruning -----------------------------------------------------------


def _ident(n: int) -> dict:
    return {i: i for i in range(n)}


def prune_columns(plan: P.PlanNode) -> P.PlanNode:
    """Top-down required-column analysis. The root keeps its full schema
    (it is the MV / query output contract); interior operators narrow to
    the columns their consumers actually read, and wide leaves gain a
    narrowing projection."""
    node, _ = _prune(plan, set(range(len(plan.schema))))
    return node


def _prune(node: P.PlanNode, needed: set):
    """Returns (node', cmap) where node' produces a superset of
    ``needed ∪ node.pk`` of node's output columns (in original order)
    and cmap maps each kept original index to its new position."""
    needed = set(needed) | set(node.pk)

    if isinstance(node, P.PProject):
        kept = sorted(needed)
        child_req: set = set()
        for i in kept:
            child_req |= expr_refs(node.exprs[i])
        child, cc = _prune(node.input, child_req)
        exprs = tuple(remap_expr(node.exprs[i], cc) for i in kept)
        cmap = {o: n for n, o in enumerate(kept)}
        return dataclasses.replace(
            node, input=child, exprs=exprs,
            schema=node.schema.select(tuple(kept)),
            pk=tuple(cmap[p] for p in node.pk)), cmap

    if isinstance(node, P.PFilter):
        child, cc = _prune(node.input,
                           needed | set(expr_refs(node.predicate)))
        return dataclasses.replace(
            node, input=child, schema=child.schema,
            pk=tuple(cc[p] for p in node.pk),
            predicate=remap_expr(node.predicate, cc)), cc

    if isinstance(node, P.PJoin):
        nl = len(node.left.schema)
        cond_refs = expr_refs(node.condition) if node.condition is not None \
            else frozenset()
        lreq = ({i for i in needed if i < nl} | set(node.left_keys)
                | {i for i in cond_refs if i < nl})
        rreq = ({i - nl for i in needed if i >= nl}
                | set(node.right_keys)
                | {i - nl for i in cond_refs if i >= nl})
        lc, lcm = _prune(node.left, lreq)
        rc, rcm = _prune(node.right, rreq)
        nnl = len(lc.schema)
        cmap = {**{o: n for o, n in lcm.items()},
                **{o + nl: n + nnl for o, n in rcm.items()}}
        from ..common.types import Schema
        return dataclasses.replace(
            node, left=lc, right=rc,
            schema=Schema(tuple(lc.schema) + tuple(rc.schema)),
            pk=tuple(cmap[p] for p in node.pk),
            left_keys=tuple(lcm[k] for k in node.left_keys),
            right_keys=tuple(rcm[k] for k in node.right_keys),
            condition=(remap_expr(node.condition, cmap)
                       if node.condition is not None else None)), cmap

    if isinstance(node, P.PAgg):
        nk = len(node.group_keys)
        kept_aggs = sorted({i - nk for i in needed if i >= nk})
        child_req = set(node.group_keys) | {
            node.agg_calls[j].arg for j in kept_aggs
            if node.agg_calls[j].arg >= 0}
        child, cc = _prune(node.input, child_req)
        calls = tuple(
            dataclasses.replace(node.agg_calls[j],
                                arg=(cc[node.agg_calls[j].arg]
                                     if node.agg_calls[j].arg >= 0 else -1))
            for j in kept_aggs)
        from ..common.types import Schema
        fields = tuple(node.schema[i] for i in range(nk)) + tuple(
            node.schema[nk + j] for j in kept_aggs)
        cmap = {**_ident(nk),
                **{nk + j: nk + n for n, j in enumerate(kept_aggs)}}
        return dataclasses.replace(
            node, input=child, schema=Schema(fields),
            group_keys=tuple(cc[k] for k in node.group_keys),
            agg_calls=calls), cmap

    if isinstance(node, P.PTopN):
        req = (needed | {o.col for o in node.order} | set(node.group_by))
        child, cc = _prune(node.input, req)
        return dataclasses.replace(
            node, input=child, schema=child.schema,
            pk=tuple(cc[p] for p in node.pk),
            order=tuple(dataclasses.replace(o, col=cc[o.col])
                        for o in node.order),
            group_by=tuple(cc[g] for g in node.group_by)), cc

    if isinstance(node, P.PDynFilter):
        child, cc = _prune(node.input, needed | {node.key_col})
        right, _ = _prune(node.right, {0})
        return dataclasses.replace(
            node, input=child, right=right, schema=child.schema,
            pk=tuple(cc[p] for p in node.pk), key_col=cc[node.key_col]), cc

    if isinstance(node, (P.PSource, P.PTableScan, P.PMvScan, P.PValues)):
        kept = sorted(needed)
        if len(kept) == len(node.schema):
            return node, _ident(len(node.schema))
        cmap = {o: n for n, o in enumerate(kept)}
        proj = P.PProject(
            schema=node.schema.select(tuple(kept)),
            pk=tuple(cmap[p] for p in node.pk), input=node,
            exprs=tuple(InputRef(i, node.schema[i].type) for i in kept))
        return proj, cmap

    # conservative nodes (HopWindow / OverWindow / ProjectSet / Union /
    # TemporalJoin): all input columns stay live; recurse requiring all
    kids = [(_prune(k, set(range(len(k.schema))))) for k in node.children]
    if kids and any(k is not orig for (k, _), orig
                    in zip(kids, node.children)):
        node = _with_children(node, [k for k, _ in kids])
    return node, _ident(len(node.schema))


# -- entry --------------------------------------------------------------------


def optimize(plan: P.PlanNode) -> P.PlanNode:
    """The pass pipeline: pushdown stage to fixpoint, then column
    pruning, then a cleanup stage merging the projections pruning
    introduced (reference: logical_optimization.rs stage list)."""
    plan = rewrite_fixpoint(plan, PREPASS_RULES)
    plan = rewrite_fixpoint(plan, PUSHDOWN_RULES)
    plan = prune_columns(plan)
    plan = rewrite_fixpoint(plan, CLEANUP_RULES)
    return plan


def explain_text(plan: P.PlanNode) -> str:
    return plan.explain()
