"""System catalogs: pg_catalog / information_schema / rw_catalog views.

Counterpart of the reference's frontend system catalogs
(reference: src/frontend/src/catalog/system_catalog/ — pg_catalog,
information_schema and rw_catalog tables BI tools introspect through).
Served as constant VALUES plans materialized from the live catalog at
plan time — a batch SELECT over them reads a consistent snapshot, the
same way the reference serves them from the frontend catalog cache.

Two tiers of relations:

* catalog-backed (pg_tables, rw_relations, …) — derived from the
  Catalog alone, available everywhere a Planner runs.
* session-backed (rw_barrier_history, rw_actors, rw_hbm_ledger, …) —
  the live telemetry estate, materialized from the owning Session at
  plan time under the session API lock, so one SELECT reads one
  consistent snapshot of the cluster (reference: rw_catalog's
  meta-backed system tables, e.g. rw_fragments / rw_actors served from
  the meta client). In session-less contexts (``DESCRIBE``, DDL
  replay) they plan with their schema and zero rows.

System relations are deliberately EXCLUDED from the serving plan
cache (frontend/serving.py): their "data" is whatever the telemetry
says right now, so a cached plan over yesterday's VALUES would be a
stale lie that no data_version seqlock invalidates.
"""

from __future__ import annotations

import json
from typing import Optional

from ..common.types import BOOL, FLOAT64, INT64, Schema, VARCHAR

#: relation name (lowercase, optionally qualified) → builder(catalog)
_SCHEMA_STR = "public"


def _pg_tables(catalog):
    schema = Schema.of(("schemaname", VARCHAR), ("tablename", VARCHAR),
                       ("tableowner", VARCHAR))
    rows = [(_SCHEMA_STR, name, "root") for name in catalog.tables]
    rows += [(_SCHEMA_STR, name, "root") for name in catalog.sources]
    return schema, rows


def _pg_matviews(catalog):
    schema = Schema.of(("schemaname", VARCHAR), ("matviewname", VARCHAR),
                       ("definition", VARCHAR))
    rows = [(_SCHEMA_STR, name, mv.definition or "")
            for name, mv in catalog.mvs.items()
            if not name.startswith("__idx_")]
    return schema, rows


def _info_tables(catalog):
    schema = Schema.of(("table_schema", VARCHAR), ("table_name", VARCHAR),
                       ("table_type", VARCHAR))
    rows = [(_SCHEMA_STR, n, "BASE TABLE") for n in catalog.tables]
    rows += [(_SCHEMA_STR, n, "SYSTEM SOURCE") for n in catalog.sources]
    rows += [(_SCHEMA_STR, n, "MATERIALIZED VIEW") for n in catalog.mvs
             if not n.startswith("__idx_")]
    return schema, rows


def _info_columns(catalog):
    schema = Schema.of(
        ("table_schema", VARCHAR), ("table_name", VARCHAR),
        ("column_name", VARCHAR), ("ordinal_position", INT64),
        ("data_type", VARCHAR))
    rows = []
    for reg in (catalog.tables, catalog.sources, catalog.mvs):
        for name, d in reg.items():
            n_vis = getattr(d, "n_visible", len(d.schema))
            for i, f in enumerate(d.schema):
                if i >= n_vis or f.name.startswith("_"):
                    continue
                rows.append((_SCHEMA_STR, name, f.name, i + 1,
                             f.type.kind.value))
    return schema, rows


def _rw_relations(catalog):
    schema = Schema.of(("name", VARCHAR), ("kind", VARCHAR))
    rows = [(n, "table") for n in catalog.tables]
    rows += [(n, "source") for n in catalog.sources]
    rows += [(n, "materialized view") for n in catalog.mvs
             if not n.startswith("__idx_")]
    rows += [(n, "sink") for n in catalog.sinks]
    rows += [(n, "index") for n in catalog.indexes]
    return schema, rows


# -- session-backed telemetry relations ---------------------------------------
#
# Builders take (catalog, session); session=None (DESCRIBE, recovery
# replay) plans the schema with zero rows. Stage column order mirrors
# barrier_ledger.ALL_STAGES so the waterfall reads left→right.

_STAGE_COLUMNS = ("inject", "pending", "collect", "commit",
                  "storage_prepare", "storage_settle", "storage_commit",
                  "sink_deliver", "worker_collect")


def _rw_barrier_history(catalog, session):
    schema = Schema.of(
        ("epoch", INT64), ("checkpoint", BOOL), ("result", VARCHAR),
        ("injected_at", FLOAT64), ("total_ms", FLOAT64),
        *((f"{s}_ms", FLOAT64) for s in _STAGE_COLUMNS),
        ("workers", VARCHAR))
    if session is None:
        return schema, []
    rows = []
    for rec in session._barrier_ledger.history():
        stages = rec.get("stages", {})
        rows.append((
            rec["epoch"], bool(rec["checkpoint"]), rec.get("result"),
            rec.get("injected_at"), rec.get("total_ms"),
            *(stages.get(s) for s in _STAGE_COLUMNS),
            json.dumps(rec.get("workers", {}), sort_keys=True)))
    return schema, rows


def _rw_barrier_inflight(catalog, session):
    schema = Schema.of(
        ("epoch", INT64), ("checkpoint", BOOL), ("age_ms", FLOAT64),
        ("kind", VARCHAR), ("job", VARCHAR), ("worker", INT64),
        ("fragment", INT64), ("actor", INT64), ("link", VARCHAR),
        ("edge", VARCHAR), ("reason", VARCHAR))
    if session is None:
        return schema, []
    rows = [(f["epoch"], f["checkpoint"], f["age_ms"], f["kind"],
             f["job"], f["worker"], f["fragment"], f["actor"],
             f["link"], f["edge"], f["reason"])
            for f in session.barrier_blame()]
    return schema, rows


def _rw_fragments(catalog, session):
    schema = Schema.of(("job", VARCHAR), ("fragment_id", INT64),
                       ("kind", VARCHAR), ("n_actors", INT64),
                       ("workers", VARCHAR))
    if session is None:
        return schema, []
    rows = []
    for name, spec in sorted(session._spanning_specs.items()):
        placement = spec["placement"]
        for fid, acts in sorted(placement.actors.items()):
            rows.append((name, fid, "spanning", len(acts),
                         ",".join(str(a.worker) for a in acts)))
    for name, spec in sorted(session._remote_specs.items()):
        rows.append((name, 0, "remote", 1,
                     str(spec["worker"].worker_id)))
    for name, job in sorted(session.jobs.items()):
        if getattr(job, "pipeline", None) is not None \
                and name not in session._spanning_specs \
                and name not in session._remote_specs:
            rows.append((name, 0, "local",
                         1 + len(getattr(job, "actors", ())), "-1"))
    return schema, rows


def _rw_actors(catalog, session):
    schema = Schema.of(("job", VARCHAR), ("fragment_id", INT64),
                       ("actor_id", INT64), ("worker", INT64),
                       ("vnode_start", INT64), ("vnode_end", INT64))
    if session is None:
        return schema, []
    rows = []
    for name, spec in sorted(session._spanning_specs.items()):
        placement = spec["placement"]
        for fid, acts in sorted(placement.actors.items()):
            for a in acts:
                rows.append((name, fid, a.actor, a.worker,
                             a.vnode_start, a.vnode_end))
    return schema, rows


def _rw_placements(catalog, session):
    schema = Schema.of(("job", VARCHAR), ("root_worker", INT64),
                       ("workers", VARCHAR), ("n_fragments", INT64),
                       ("n_actors", INT64))
    if session is None:
        return schema, []
    rows = []
    for name, spec in sorted(session._spanning_specs.items()):
        placement = spec["placement"]
        rows.append((name, placement.root_worker,
                     ",".join(str(w) for w in placement.workers()),
                     len(placement.actors),
                     sum(len(a) for a in placement.actors.values())))
    return schema, rows


def _rw_worker_nodes(catalog, session):
    schema = Schema.of(("worker_id", INT64), ("pid", INT64),
                       ("dead", BOOL), ("link", VARCHAR),
                       ("jobs", VARCHAR))
    if session is None:
        return schema, []
    stats = session._federate_worker_stats()
    rows = []
    for w in session.workers:
        jobs = sorted(stats.get(w.worker_id, {}).get("jobs", {}))
        rows.append((w.worker_id,
                     getattr(getattr(w, "proc", None), "pid", None),
                     bool(w.dead), w.link, ",".join(jobs)))
    return schema, rows


def _rw_dispatch_profiles(catalog, session):
    schema = Schema.of(
        ("worker", INT64), ("qualname", VARCHAR), ("calls", INT64),
        ("total_s", FLOAT64), ("mean_ms", FLOAT64), ("max_ms", FLOAT64),
        ("compiles", INT64), ("compile_s", FLOAT64),
        ("complete_mean_ms", FLOAT64))
    if session is None:
        return schema, []
    from ..common.profiling import GLOBAL_PROFILER

    def _rows(wid, dispatch):
        return [(wid, qn, d.get("calls"), d.get("total_s"),
                 d.get("mean_ms"), d.get("max_ms"), d.get("compiles"),
                 d.get("compile_s"), d.get("complete_mean_ms"))
                for qn, d in sorted((dispatch or {}).items())]

    rows = _rows(-1, GLOBAL_PROFILER.snapshot())
    for wid, st in sorted(session._federate_worker_stats().items()):
        rows += _rows(wid, (st.get("profiling") or {}).get("dispatch"))
    return schema, rows


def _rw_hbm_ledger(catalog, session):
    schema = Schema.of(
        ("job", VARCHAR), ("worker", INT64), ("state_bytes", INT64),
        ("flagged", BOOL), ("capacity_bytes", INT64),
        ("used_bytes", INT64), ("headroom_bytes", INT64),
        ("utilization", FLOAT64))
    if session is None:
        return schema, []
    hbm = session.metrics()["profiling"]["hbm"]
    flagged = set(hbm.get("flagged", ()))
    rows = [(name, j.get("worker"), j.get("bytes", 0), name in flagged,
             hbm["capacity_bytes"], hbm["used_bytes"],
             hbm["headroom_bytes"], hbm["utilization"])
            for name, j in sorted(hbm.get("jobs", {}).items())]
    return schema, rows


def _rw_autoscaler_decisions(catalog, session):
    schema = Schema.of(
        ("seq", INT64), ("kind", VARCHAR), ("job", VARCHAR),
        ("reason", VARCHAR), ("from_parallelism", INT64),
        ("to_parallelism", INT64), ("moved_vnodes", INT64),
        ("pause_ms", FLOAT64), ("epoch", INT64))
    if session is None:
        return schema, []
    rows = []
    for i, d in enumerate(session.autoscaler.status()["decisions"]):
        rows.append((i, "decision", d.get("job"), d.get("reason"),
                     d.get("from"), d.get("to"), None, None, None))
    for i, r in enumerate(session._rescale_stats["history"]):
        rows.append((i, "rescale", r.get("job"), None, None,
                     r.get("parallelism"), r.get("moved_vnodes"),
                     r.get("pause_ms"), r.get("epoch")))
    return schema, rows


def _rw_leader_history(catalog, session):
    """Leader-lease acquisition history (meta/server.py persists it):
    one row per term — who held it, when, and why (bootstrap, takeover
    attach, or a TTL-expiry election). In-process meta has no lease, so
    the relation is empty there."""
    schema = Schema.of(
        ("term", INT64), ("holder", VARCHAR), ("acquired_at", FLOAT64),
        ("reason", VARCHAR), ("leaderless_s", FLOAT64),
        ("current", BOOL))
    if session is None:
        return schema, []
    lease_info = getattr(session.meta, "lease_info", None)
    if lease_info is None:
        return schema, []          # in-process meta: no lease surface
    try:
        info = lease_info()
    except Exception:
        return schema, []
    rows = [(h.get("term"), h.get("holder"), h.get("acquired_at"),
             h.get("reason"), h.get("leaderless_s"),
             h.get("term") == info.get("term"))
            for h in info.get("history", ())]
    return schema, rows


_RELATIONS = {
    "pg_tables": _pg_tables,
    "pg_catalog.pg_tables": _pg_tables,
    "pg_matviews": _pg_matviews,
    "pg_catalog.pg_matviews": _pg_matviews,
    "information_schema.tables": _info_tables,
    "information_schema.columns": _info_columns,
    "rw_relations": _rw_relations,
    "rw_catalog.rw_relations": _rw_relations,
}

_SESSION_RELATIONS = {
    "rw_barrier_history": _rw_barrier_history,
    "rw_barrier_inflight": _rw_barrier_inflight,
    "rw_fragments": _rw_fragments,
    "rw_actors": _rw_actors,
    "rw_placements": _rw_placements,
    "rw_worker_nodes": _rw_worker_nodes,
    "rw_dispatch_profiles": _rw_dispatch_profiles,
    "rw_hbm_ledger": _rw_hbm_ledger,
    "rw_autoscaler_decisions": _rw_autoscaler_decisions,
    "rw_leader_history": _rw_leader_history,
}
_SESSION_RELATIONS.update({f"rw_catalog.{n}": b
                           for n, b in list(_SESSION_RELATIONS.items())})

#: every system-relation name (bare + qualified, lowercase) — the
#: serving plane's cache-exclusion check keys on this set
SYSTEM_RELATION_NAMES = frozenset(_RELATIONS) | frozenset(
    _SESSION_RELATIONS)


def system_relation(catalog, name: str,
                    session=None) -> Optional[tuple]:
    """(Schema, rows) for a system view name, or None."""
    key = name.lower()
    builder = _RELATIONS.get(key)
    if builder is not None:
        return builder(catalog)
    builder = _SESSION_RELATIONS.get(key)
    if builder is not None:
        return builder(catalog, session)
    return None
