"""System catalogs: pg_catalog / information_schema / rw_catalog views.

Counterpart of the reference's frontend system catalogs
(reference: src/frontend/src/catalog/system_catalog/ — pg_catalog,
information_schema and rw_catalog tables BI tools introspect through).
Served as constant VALUES plans materialized from the live catalog at
plan time — a batch SELECT over them reads a consistent snapshot, the
same way the reference serves them from the frontend catalog cache.
"""

from __future__ import annotations

from typing import Optional

from ..common.types import INT64, Field, Schema, VARCHAR

#: relation name (lowercase, optionally qualified) → builder(catalog)
_SCHEMA_STR = "public"


def _pg_tables(catalog):
    schema = Schema.of(("schemaname", VARCHAR), ("tablename", VARCHAR),
                       ("tableowner", VARCHAR))
    rows = [(_SCHEMA_STR, name, "root") for name in catalog.tables]
    rows += [(_SCHEMA_STR, name, "root") for name in catalog.sources]
    return schema, rows


def _pg_matviews(catalog):
    schema = Schema.of(("schemaname", VARCHAR), ("matviewname", VARCHAR),
                       ("definition", VARCHAR))
    rows = [(_SCHEMA_STR, name, mv.definition or "")
            for name, mv in catalog.mvs.items()
            if not name.startswith("__idx_")]
    return schema, rows


def _info_tables(catalog):
    schema = Schema.of(("table_schema", VARCHAR), ("table_name", VARCHAR),
                       ("table_type", VARCHAR))
    rows = [(_SCHEMA_STR, n, "BASE TABLE") for n in catalog.tables]
    rows += [(_SCHEMA_STR, n, "SYSTEM SOURCE") for n in catalog.sources]
    rows += [(_SCHEMA_STR, n, "MATERIALIZED VIEW") for n in catalog.mvs
             if not n.startswith("__idx_")]
    return schema, rows


def _info_columns(catalog):
    schema = Schema.of(
        ("table_schema", VARCHAR), ("table_name", VARCHAR),
        ("column_name", VARCHAR), ("ordinal_position", INT64),
        ("data_type", VARCHAR))
    rows = []
    for reg in (catalog.tables, catalog.sources, catalog.mvs):
        for name, d in reg.items():
            n_vis = getattr(d, "n_visible", len(d.schema))
            for i, f in enumerate(d.schema):
                if i >= n_vis or f.name.startswith("_"):
                    continue
                rows.append((_SCHEMA_STR, name, f.name, i + 1,
                             f.type.kind.value))
    return schema, rows


def _rw_relations(catalog):
    schema = Schema.of(("name", VARCHAR), ("kind", VARCHAR))
    rows = [(n, "table") for n in catalog.tables]
    rows += [(n, "source") for n in catalog.sources]
    rows += [(n, "materialized view") for n in catalog.mvs
             if not n.startswith("__idx_")]
    rows += [(n, "sink") for n in catalog.sinks]
    rows += [(n, "index") for n in catalog.indexes]
    return schema, rows


_RELATIONS = {
    "pg_tables": _pg_tables,
    "pg_catalog.pg_tables": _pg_tables,
    "pg_matviews": _pg_matviews,
    "pg_catalog.pg_matviews": _pg_matviews,
    "information_schema.tables": _info_tables,
    "information_schema.columns": _info_columns,
    "rw_relations": _rw_relations,
    "rw_catalog.rw_relations": _rw_relations,
}


def system_relation(catalog, name: str) -> Optional[tuple]:
    """(Schema, rows) for a system view name, or None."""
    builder = _RELATIONS.get(name.lower())
    if builder is None:
        return None
    return builder(catalog)
