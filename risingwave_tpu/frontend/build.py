"""Executor-graph builder: plan tree → wired executor pipeline.

Counterpart of the reference's create_executor dispatch
(reference: src/stream/src/from_proto/mod.rs:119-165 — proto plan node →
executor, recursively). The builder also allocates state tables for every
stateful operator (the reference's fragmenter fills internal-table ids,
src/meta/src/stream/stream_graph/fragment.rs:258).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from ..common.types import Field, INT64, Schema
from ..expr.expr import InputRef
from ..ops.join_state import JoinType
from ..storage.state_store import MemoryStateStore
from ..storage.state_table import StateTable
from ..stream.dynamic_filter import DynamicFilterExecutor
from ..stream.eowc import SortExecutor
from ..stream.executor import Executor, SingleInputExecutor
from ..stream.hash_agg import HashAggExecutor, agg_state_schema
from ..stream.hash_join import HashJoinExecutor
from ..stream.hop_window import HopWindowExecutor
from ..stream.materialize import MaterializeExecutor
from ..stream.project import FilterExecutor, ProjectExecutor
from ..stream.row_id_gen import RowIdGenExecutor
from ..stream.simple_agg import SimpleAggExecutor
from ..stream.top_n import TopNExecutor
from ..stream.union import UnionExecutor
from . import planner as P
from .runtime import QueueSource

_JOIN_TYPES = {
    "inner": JoinType.INNER, "left": JoinType.LEFT_OUTER,
    "right": JoinType.RIGHT_OUTER, "full": JoinType.FULL_OUTER,
    "left_semi": JoinType.LEFT_SEMI, "left_anti": JoinType.LEFT_ANTI,
}


@dataclasses.dataclass
class BuildConfig:
    chunk_capacity: int = 1024
    agg_table_capacity: int = 1 << 16
    join_key_capacity: int = 1 << 13
    join_bucket_width: int = 16
    topn_table_capacity: int = 1 << 16
    # Data parallelism: a jax.sharding.Mesh routes grouped aggs and joins
    # through the mesh-sharded executors (parallel/executors.py); None keeps
    # every operator single-chip. Capacities above are per shard when set.
    mesh: Optional[object] = None
    # Pipeline parallelism via the dispatch fabric (stream/dispatch.py):
    # >1 builds grouped aggs as MULTI-FRAGMENT jobs — the upstream fragment
    # hash-dispatches over PermitChannels to N parallel agg actors whose
    # outputs merge-fan-in (reference: fragments + exchanges,
    # dispatch.rs:532 / merge.rs:114). Orthogonal to ``mesh`` (host actor
    # concurrency vs device sharding); ignored for batch builds.
    fragment_parallelism: int = 1
    exchange_permits: int = 32
    # Epoch co-scheduling (stream/coschedule.py): CREATE MATERIALIZED
    # VIEW routes eligible source+agg plans into a fused multi-job
    # dispatch group — K co-scheduled MVs tick in ONE jit dispatch.
    # Opt-in ([streaming] coschedule = true); ineligible shapes build
    # the normal executor pipeline.
    coschedule: bool = False
    # The heterogeneous tick compiler (stream/tick_compiler.py):
    # eligible MVs join a compiled dispatch schedule — shape-class
    # padded supergroups plus jitted mega-epochs — so DISSIMILAR small
    # MVs fuse too. Opt-in ([streaming] tick_compiler = true); wins
    # over ``coschedule`` for eligible shapes.
    tick_compiler: bool = False
    # HBM pressure: cap on live groups per grouped-agg executor; coldest
    # groups evict to the state table at checkpoints and fault back in on
    # access (reference: cache/managed_lru.rs). None = grow-or-raise.
    agg_hbm_budget: Optional[int] = None
    # HBM pressure for joins: cap on live join KEYS per arena; coldest
    # keys' buckets evict from BOTH sides to the state tables at
    # checkpoints and fault back on mention (reference: JoinHashMap's
    # ManagedLruCache, managed_state/join/mod.rs:228-258).
    join_hbm_budget: Optional[int] = None
    # max snapshot rows per barrier during concurrent backfill
    # (stream/backfill.py); None = max(4 * chunk capacity, 4096)
    backfill_batch_rows: Optional[int] = None
    # wrap every built executor with the logical sanitizers (schema /
    # epoch / update-pair checks — reference:
    # src/stream/src/executor/wrapper/); debug & sim runs, off in prod
    sanity_checks: bool = False


def join_state_pk(join_keys, stream_pk) -> list:
    """Join state tables lay their pk out as join_keys ++ stream_pk: rows
    of one join key are contiguous in key order, so cold-tier fault-in is
    a pk prefix scan (the reference's JoinHashMap tables are likewise
    keyed join-key-first, managed_state/join/mod.rs)."""
    return list(join_keys) + [i for i in stream_pk if i not in join_keys]


class BuildContext:
    """Per-job build state: allocated sources and state tables.

    ``source_factory(plan_node) -> Executor`` supplies the leaves — the
    Session passes a factory that creates queue-fed sources for streaming
    jobs or snapshot replays for batch queries."""

    def __init__(
        self,
        store: MemoryStateStore,
        next_table_id: Callable[[], int],
        source_factory: Callable[[P.PlanNode], Executor],
        config: Optional[BuildConfig] = None,
        durable: bool = True,
        vnode_range: Optional[tuple] = None,
    ):
        self.store = store
        self.next_table_id = next_table_id
        self.source_factory = source_factory
        self.config = config or BuildConfig()
        self.durable = durable
        # (vnode_start, vnode_end) owned by a SPANNING fragment actor:
        # stateful executors reload only rows in this range, so a store
        # holding ranges that migrated away (meta/rescale.py) never
        # resurrects them into device state
        self.vnode_range = vnode_range
        self.state_table_ids: list[int] = []
        # actor coroutine factories for multi-fragment builds; the
        # StreamJob spawns one task per entry alongside the root pipeline
        self.actors: list = []

    def state_table(self, schema: Schema, pk) -> Optional[StateTable]:
        if not self.durable:
            return None
        tid = self.next_table_id()
        self.state_table_ids.append(tid)
        return StateTable(self.store, tid, schema, list(pk))


def build_plan(plan: P.PlanNode, ctx: BuildContext) -> Executor:
    """Build one plan node (recursively); with ``cfg.sanity_checks`` every
    built executor is wrapped in the logical sanitizers, mirroring the
    reference's WrapperExecutor around every actor node
    (src/stream/src/task/stream_manager.rs WrapperExecutor +
    executor/wrapper/{schema_check,epoch_check,update_check}.rs)."""
    ex = _build_plan(plan, ctx)
    if ctx.config.sanity_checks:
        from ..stream.executor import (
            EpochCheckExecutor, SchemaCheckExecutor, UpdateCheckExecutor,
        )
        ex = SchemaCheckExecutor(UpdateCheckExecutor(EpochCheckExecutor(ex)))
    return ex


def _build_plan(plan: P.PlanNode, ctx: BuildContext) -> Executor:
    cfg = ctx.config
    if isinstance(plan, (P.PSource, P.PTableScan, P.PMvScan, P.PValues,
                         P.PRemoteFragment, P.PExchange)):
        return ctx.source_factory(plan)

    if isinstance(plan, P.PProject):
        inp = build_plan(plan.input, ctx)
        return ProjectExecutor(inp, list(plan.exprs),
                               names=plan.schema.names)

    if isinstance(plan, P.PFilter):
        inp = build_plan(plan.input, ctx)
        return FilterExecutor(inp, plan.predicate)

    if isinstance(plan, P.PHopWindow):
        inp = build_plan(plan.input, ctx)
        return HopWindowExecutor(inp, plan.time_col, plan.slide, plan.size)

    if isinstance(plan, P.PAgg):
        from ..stream.materialized_agg import (
            MaterializedAggExecutor, call_needs_materialized,
            materialized_agg_state_schema,
        )
        if any(call_needs_materialized(c, plan.append_only_input)
               for c in plan.agg_calls):
            # exact DISTINCT / array_agg / string_agg / percentile / mode /
            # min-max-under-retraction: materialized-input state on the
            # host tier (reference: AggStateStorage::MaterializedInput);
            # ragged per-group multisets have no fixed-lane device layout.
            # ALL sibling calls ride along — approx_count_distinct included
            # (evaluated there exactly, a superset of its approx contract)
            if plan.eowc:
                raise ValueError(
                    "EMIT ON WINDOW CLOSE does not support materialized-"
                    "input aggregates")
            inp = build_plan(plan.input, ctx)
            key_fields = [plan.input.schema[i] for i in plan.group_keys]
            nk = len(plan.group_keys)
            st = ctx.state_table(
                materialized_agg_state_schema(key_fields),
                list(range(nk + 5)))     # keys + agg_idx/is_null/vi/vf/vs
            return MaterializedAggExecutor(
                inp, list(plan.group_keys), list(plan.agg_calls),
                state_table=st, out_capacity=cfg.chunk_capacity,
                load_vnodes=ctx.vnode_range)
        if (plan.group_keys and cfg.fragment_parallelism > 1
                and cfg.mesh is None and ctx.durable):
            # multi-fragment build over the dispatch fabric; batch builds
            # (durable=False) have no actor runtime and stay fused
            from .fragments import build_fragmented_agg
            return build_fragmented_agg(plan, ctx)
        inp = build_plan(plan.input, ctx)
        if plan.group_keys:
            key_fields = [plan.input.schema[i] for i in plan.group_keys]
            st = ctx.state_table(
                agg_state_schema(key_fields, plan.agg_calls),
                list(range(len(plan.group_keys))))
            if cfg.mesh is not None:
                from ..parallel.executors import ShardedHashAggExecutor
                return ShardedHashAggExecutor(
                    inp, cfg.mesh, list(plan.group_keys),
                    list(plan.agg_calls), state_table=st,
                    table_capacity=cfg.agg_table_capacity,
                    out_capacity=cfg.chunk_capacity)
            return HashAggExecutor(
                inp, list(plan.group_keys), list(plan.agg_calls),
                state_table=st, table_capacity=cfg.agg_table_capacity,
                out_capacity=cfg.chunk_capacity,
                load_vnodes=ctx.vnode_range,
                hbm_group_budget=cfg.agg_hbm_budget)
        from ..stream.simple_agg import simple_agg_state_schema
        st = ctx.state_table(simple_agg_state_schema(plan.agg_calls), [0])
        return SimpleAggExecutor(inp, list(plan.agg_calls), state_table=st)

    if isinstance(plan, P.PJoin):
        if getattr(plan, "null_aware", False) and (
                cfg.mesh is not None or (
                    plan.left_keys and cfg.fragment_parallelism > 1
                    and ctx.durable)):
            # sharded/fragmented anti joins don't carry the NOT IN null
            # guard; fail at build time, not with silently wrong rows
            raise ValueError(
                "NOT IN (SELECT ...) is not supported on sharded or "
                "fragmented join layouts; use NOT EXISTS or the default "
                "layout")
        if (plan.left_keys and cfg.fragment_parallelism > 1
                and cfg.mesh is None and ctx.durable):
            # multi-fragment build: both sides hash-dispatch by join key
            # to N parallel join actors (reference: hash-distributed
            # HashJoin fragments, dispatch.rs:532)
            from .fragments import build_fragmented_join
            return build_fragmented_join(plan, ctx, _JOIN_TYPES)
        left = build_plan(plan.left, ctx)
        right = build_plan(plan.right, ctx)
        lst = ctx.state_table(plan.left.schema,
                              join_state_pk(plan.left_keys, plan.left.pk))
        rst = ctx.state_table(plan.right.schema,
                              join_state_pk(plan.right_keys, plan.right.pk))
        if cfg.mesh is not None:
            from ..parallel.executors import ShardedHashJoinExecutor
            return ShardedHashJoinExecutor(
                left, right, cfg.mesh, list(plan.left_keys),
                list(plan.right_keys), join_type=_JOIN_TYPES[plan.kind],
                condition=plan.condition,
                left_state_table=lst, right_state_table=rst,
                key_capacity=cfg.join_key_capacity,
                bucket_width=cfg.join_bucket_width,
                out_capacity=cfg.chunk_capacity)
        return HashJoinExecutor(
            left, right, list(plan.left_keys), list(plan.right_keys),
            join_type=_JOIN_TYPES[plan.kind], condition=plan.condition,
            left_state_table=lst, right_state_table=rst,
            key_capacity=cfg.join_key_capacity,
            bucket_width=cfg.join_bucket_width,
            out_capacity=cfg.chunk_capacity,
            hbm_key_budget=cfg.join_hbm_budget,
            null_aware_anti=getattr(plan, "null_aware", False))

    if isinstance(plan, P.PTopN):
        inp = build_plan(plan.input, ctx)
        st = ctx.state_table(plan.schema, list(plan.pk))
        return TopNExecutor(
            inp, list(plan.order), plan.offset, plan.limit,
            pk_indices=list(plan.pk), group_by=list(plan.group_by),
            with_ties=plan.with_ties, state_table=st,
            table_capacity=cfg.topn_table_capacity,
            out_capacity=cfg.chunk_capacity)

    if isinstance(plan, P.PDynFilter):
        left = build_plan(plan.input, ctx)
        right = build_plan(plan.right, ctx)
        st = ctx.state_table(plan.schema, list(plan.pk))
        bt = None
        if st is not None:
            bt = ctx.state_table(
                Schema((Field("id", INT64),
                        Field("bound", plan.schema[plan.key_col].type))), [0])
        return DynamicFilterExecutor(
            left, right, key_col=plan.key_col, cmp=plan.cmp,
            pk_indices=list(plan.pk), state_table=st, bound_table=bt,
            table_capacity=cfg.topn_table_capacity,
            out_capacity=cfg.chunk_capacity)

    if isinstance(plan, P.PTemporalJoin):
        from ..stream.temporal_join import TemporalJoinExecutor
        inp = build_plan(plan.input, ctx)
        rdef = plan.right_def
        right_table = StateTable(ctx.store, rdef.table_id, rdef.schema,
                                 list(rdef.pk))
        return TemporalJoinExecutor(
            inp, right_table, list(plan.left_keys), list(plan.right_keys),
            outer=plan.outer, condition=plan.condition,
            out_capacity=cfg.chunk_capacity)

    if isinstance(plan, P.POverWindow):
        from ..stream.over_window import (
            EowcOverWindowExecutor, OverWindowExecutor, eowc_acc_schema,
        )
        inp = build_plan(plan.input, ctx)
        in_schema = plan.input.schema
        pk = list(plan.input.pk)
        if plan.eowc:
            order_col = plan.calls[0].order_by[0].col
            sort_st = ctx.state_table(in_schema, pk)
            inp = SortExecutor(inp, time_col=order_col, pk_indices=pk,
                               state_table=sort_st,
                               table_capacity=cfg.topn_table_capacity,
                               out_capacity=cfg.chunk_capacity)
            acc_schema = eowc_acc_schema(in_schema, plan.calls)
            npart = len(plan.calls[0].partition_by)
            acc_st = ctx.state_table(acc_schema, list(range(npart)))
            buf_st = ctx.state_table(in_schema, pk)
            return EowcOverWindowExecutor(
                inp, plan.calls, pk_indices=pk, acc_table=acc_st,
                buffer_table=buf_st, out_capacity=cfg.chunk_capacity)
        st = ctx.state_table(in_schema, pk)
        return OverWindowExecutor(inp, plan.calls, pk_indices=pk,
                                  state_table=st,
                                  out_capacity=cfg.chunk_capacity)

    if isinstance(plan, P.PProjectSet):
        from ..stream.project_set import ProjectSetExecutor
        inp = build_plan(plan.input, ctx)
        return ProjectSetExecutor(inp, list(plan.exprs),
                                  names=plan.schema.names,
                                  out_capacity=cfg.chunk_capacity)

    if isinstance(plan, P.PUnion):
        return UnionExecutor([build_plan(i, ctx) for i in plan.inputs])

    raise NotImplementedError(f"cannot build {type(plan).__name__}")


def config_to_json(cfg: BuildConfig) -> str:
    """Durable form of a BuildConfig (reschedule persistence). A live
    ``mesh`` can't be pickled across processes/restarts; what IS durable
    is its topology — axis names + shape — from which an equivalent mesh
    reassembles over the restarted process's devices (the reference
    persists vnode mappings in meta for the same reason,
    src/meta/src/stream/scale.rs:657)."""
    import json
    d = {f.name: getattr(cfg, f.name) for f in dataclasses.fields(cfg)
         if f.name != "mesh"}
    if cfg.mesh is not None:
        d["mesh"] = {"axis_names": list(cfg.mesh.axis_names),
                     "shape": list(cfg.mesh.devices.shape)}
    else:
        d["mesh"] = None
    return json.dumps(d, sort_keys=True)


def config_from_json(s: str, allow_reshard: bool = False) -> BuildConfig:
    """Rebuild a BuildConfig from its durable form.

    When the persisted mesh topology needs more devices than the process
    has this REFUSES loudly (``MeshUnavailableError``) — the silent
    alternative was an N-shard job quietly reopening on the session's
    default (unsharded) layout. ``allow_reshard=True`` is the explicit
    escape hatch: a 1-D mesh shrinks to the available device count, which
    is safe because the sharded executors and the fused sharded path
    re-shard durable state by replaying the vnode mapping on load
    (parallel/fused.load_shard_states, ShardedHashAggExecutor's
    load-shard filter)."""
    import json
    d = json.loads(s)
    mesh_spec = d.pop("mesh", None)
    known = {f.name for f in dataclasses.fields(BuildConfig)}
    cfg = BuildConfig(**{k: v for k, v in d.items() if k in known})
    if mesh_spec is not None:
        import math
        import jax
        import numpy as _np
        from ..common.config import MeshUnavailableError
        shape = list(mesh_spec["shape"])
        n = math.prod(shape)
        devs = jax.devices()
        if len(devs) < n:
            if allow_reshard and len(shape) == 1 and devs:
                shape = [len(devs)]
                n = len(devs)
            else:
                raise MeshUnavailableError(
                    f"persisted mesh needs {n} devices, process has "
                    f"{len(devs)}")
        cfg = dataclasses.replace(cfg, mesh=jax.sharding.Mesh(
            _np.array(devs[:n]).reshape(shape),
            tuple(mesh_spec["axis_names"])))
    return cfg


def collect_leaves(plan: P.PlanNode) -> list:
    """All leaf nodes (sources/scans/values) in plan order."""
    if not plan.children:
        return [plan] if isinstance(
            plan, (P.PSource, P.PTableScan, P.PMvScan, P.PValues,
                   P.PRemoteFragment, P.PExchange)) else []
    out = []
    for c in plan.children:
        out.extend(collect_leaves(c))
    return out
