"""Session-side handles for remote worker processes.

Counterpart of the reference's rpc_client pools + stream client
(reference: src/rpc_client/src/meta_client.rs:92, stream_client.rs — the
frontend/meta side of the compute-node RPC boundary). One
``RemoteWorker`` per worker process: it owns the subprocess, the
multiplexed socket, permit accounting for outbound data channels, and
the per-epoch barrier-completion events. ``RemoteJob`` adapts a
worker-hosted job to the StreamJob surface the Session's conduction loop
drives (wait_barrier / stop / sources / bus).
"""

from __future__ import annotations

import asyncio
import itertools
import os
import signal
import subprocess
import sys
import time
from typing import Optional

from ..common.config import FaultConfig as _FaultConfig
from ..rpc.wire import message_to_wire, read_frame, write_frame
from ..stream.message import Message
from .runtime import ChangelogBus, QueueSource

_FAULT_DEFAULTS = _FaultConfig()


class WorkerDied(RuntimeError):
    pass


class RemoteWorker:
    """Spawn + drive one worker process over a multiplexed socket."""

    SPAWN_TIMEOUT_S = 60.0
    #: default deadline on control-frame request/reply cycles: a worker
    #: wedged before replying (accelerator hang, livelock) used to hang
    #: handle_create_job/scan forever — now it trips WorkerDied and the
    #: recovery machinery. Defaults come from FaultConfig (the single
    #: source of the numbers; configurable via rw_config fault.*).
    REQUEST_TIMEOUT_S = _FAULT_DEFAULTS.worker_request_timeout_s
    #: deadline on barrier collection per epoch: a worker that stops
    #: acking barriers without closing its socket is declared failed
    #: (fail-stop) so the heartbeat-TTL scoped recovery can respawn it
    EPOCH_TIMEOUT_S = _FAULT_DEFAULTS.worker_epoch_timeout_s

    def __init__(self, data_dir: str, worker_id: int, loop,
                 permits: int = 32):
        self.data_dir = data_dir
        self.worker_id = worker_id
        self.loop = loop
        self.permits = permits
        self.request_timeout = self.REQUEST_TIMEOUT_S
        self.epoch_timeout = self.EPOCH_TIMEOUT_S
        self.dead = False
        self.proc: Optional[subprocess.Popen] = None
        #: fault-plane link name of the session→worker direction
        self.link = f"s->w{worker_id}"
        #: session-generation fencing token (ISSUE 9): stamped on every
        #: frame this handle sends; the Session bumps it on every scoped
        #: recovery so a stale pre-recovery worker's barrier acks are
        #: dropped here and its commits are refused worker-side
        self.generation = 1
        self.stale_acks_dropped = 0
        self.dup_replies_dropped = 0
        self.dup_acks_dropped = 0
        self._rid = itertools.count(1)
        self._chan = itertools.count(worker_id * 100_000 + 1)
        self._pending: dict[int, asyncio.Future] = {}
        self._done_rids: "set[int]" = set()
        self._epoch_events: dict[int, asyncio.Event] = {}
        self._epoch_errors: dict[int, str] = {}
        self._init_fut: Optional[asyncio.Future] = None
        self._sems: dict[int, asyncio.Semaphore] = {}
        self._data_seqs: dict[int, int] = {}
        from ..rpc.exchange import AckWatermark
        self._acks: dict[int, AckWatermark] = {}
        self._forwarders: dict[str, list[asyncio.Task]] = {}
        self._wlock: Optional[asyncio.Lock] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._writer = None

    # -- lifecycle -------------------------------------------------------------

    def spawn(self) -> None:
        env = dict(os.environ)
        if env.get("JAX_PLATFORMS") == "cpu":
            # a wedged TPU tunnel must not hang a CPU-mode worker
            env.pop("PALLAS_AXON_POOL_IPS", None)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "risingwave_tpu.worker",
             "--data-dir", self.data_dir,
             "--worker-id", str(self.worker_id), "--port", "0"],
            stdout=subprocess.PIPE, stderr=None, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        deadline = time.monotonic() + self.SPAWN_TIMEOUT_S
        port = None
        assert self.proc.stdout is not None
        import select
        buf = b""
        fd = self.proc.stdout.fileno()
        while time.monotonic() < deadline:
            # select-bounded read: a worker that hangs during startup
            # WITHOUT printing (wedged accelerator init) must still trip
            # the timeout instead of blocking readline forever
            ready, _, _ = select.select([fd], [], [],
                                        max(0.05, deadline - time.monotonic()))
            if not ready:
                continue
            chunk = os.read(fd, 4096)
            if not chunk:
                raise WorkerDied(
                    f"worker {self.worker_id} exited during startup "
                    f"(rc={self.proc.poll()})")
            buf += chunk
            for line in buf.decode(errors="replace").splitlines():
                if line.startswith("WORKER_READY"):
                    port = int(line.split()[1])
                    break
            if port is not None:
                break
        if port is None:
            self.proc.kill()
            raise WorkerDied(f"worker {self.worker_id} startup timed out")
        self.port = port
        self.dead = False

    async def connect(self) -> None:
        reader, writer = await asyncio.open_connection("127.0.0.1", self.port)
        self._writer = writer
        self._wlock = asyncio.Lock()
        self._reader_task = asyncio.ensure_future(self._read_loop(reader))

    async def aclose(self) -> None:
        """Tear down the socket INSIDE the loop (cancelled reader awaited,
        writer closed) so no task or transport outlives the session."""
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._reader_task = None
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None

    def respawn(self, connect_await) -> None:
        """Fresh process over the SAME durable directory (state + offsets
        recover from the last committed checkpoint)."""
        connect_await(self.aclose())
        self.terminate()
        self._pending.clear()
        self._epoch_events.clear()
        self._epoch_errors.clear()
        self._sems.clear()
        self._data_seqs.clear()
        self._acks.clear()
        # sibling jobs' forwarders feed a process that no longer exists;
        # cancel (not just forget) so they cannot leak across recoveries
        for tasks in self._forwarders.values():
            for t in tasks:
                t.cancel()
        self._forwarders.clear()
        self.spawn()
        connect_await(self.connect())

    def terminate(self) -> None:
        if self._reader_task is not None:   # not yet aclosed
            self._reader_task.cancel()
            self._reader_task = None
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.dead = True

    def kill9(self) -> None:
        """Chaos hook: SIGKILL the worker process (the madsim node-kill
        analogue across a REAL process boundary)."""
        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, signal.SIGKILL)
            self.proc.wait()

    # -- socket ----------------------------------------------------------------

    async def _read_loop(self, reader) -> None:
        while True:
            frame = await read_frame(reader)
            if frame is None:
                self._mark_dead()
                return
            t = frame.get("type")
            if t == "reply":
                rid = frame.get("rid")
                fut = self._pending.pop(rid, None)
                if fut is not None and not fut.done():
                    fut.set_result(frame)
                    if rid is not None:
                        self._done_rids.add(rid)
                        if len(self._done_rids) > 4096:
                            self._done_rids = set(
                                sorted(self._done_rids)[-2048:])
                elif rid in self._done_rids:
                    # at-least-once reply delivery (duplicated frame on a
                    # faulty link) stays exactly-once at the caller: the
                    # first copy resolved the future, later copies drop
                    self.dup_replies_dropped += 1
            elif t == "ack":
                chan = frame["chan"]
                wm = self._acks.get(chan)
                if wm is not None and not wm.accept(frame.get("seq")):
                    # duplicated data ack: releasing a permit for it
                    # would inflate the channel's credit (reordered
                    # acks are accepted exactly once by the watermark)
                    self.dup_acks_dropped += 1
                    continue
                sem = self._sems.get(chan)
                if sem is not None:
                    sem.release()
            elif t == "barrier_complete":
                gen = frame.get("gen")
                if gen is not None and int(gen) != self.generation:
                    # fencing: a barrier ack carrying a stale generation
                    # (pre-recovery incarnation, or a chaos-delayed
                    # frame) must not count toward the CURRENT graph's
                    # epoch collection
                    self.stale_acks_dropped += 1
                    continue
                # per-JOB failure map: one poisoned or peer-starved job
                # must not read as a whole-worker failure (legacy
                # ok/error frames fold into the wildcard entry)
                failed = dict(frame.get("failed") or {})
                if frame.get("ok", True) is False:
                    failed["*"] = frame.get("error", "worker job failed")
                if failed:
                    self._epoch_errors[frame["epoch"]] = failed
                if frame.get("init") and self._init_fut is not None:
                    if not self._init_fut.done():
                        self._init_fut.set_result(frame)
                else:
                    ev = self._epoch_events.setdefault(
                        frame["epoch"], asyncio.Event())
                    ev.set()

    def _mark_dead(self) -> None:
        self.dead = True
        for ev in self._epoch_events.values():
            ev.set()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(WorkerDied("worker connection lost"))
        self._pending.clear()
        if self._init_fut is not None and not self._init_fut.done():
            self._init_fut.set_exception(WorkerDied("worker connection lost"))
        for sem in self._sems.values():
            sem.release()          # unblock forwarders; send() will raise

    async def send(self, obj: dict, meta: bool = False) -> None:
        if self.dead or self._writer is None:
            raise WorkerDied("worker is down")
        if "gen" not in obj:
            # fencing token on every session→worker frame: the worker
            # records it at job creation and refuses barrier/commit
            # frames older than a job's deployment generation
            obj = {**obj, "gen": self.generation}
        try:
            await write_frame(self._writer, obj, self._wlock,
                              link=self.link, meta=meta)
        except (ConnectionError, BrokenPipeError, OSError):
            self._mark_dead()
            raise WorkerDied("worker connection lost") from None

    async def request(self, obj: dict,
                      timeout: Optional[float] = None,
                      meta: bool = False) -> dict:
        """Request/reply with a DEFAULT deadline (``request_timeout``; a
        worker wedged before replying is declared dead instead of hanging
        the caller forever). Pass ``timeout=0`` to wait unbounded."""
        rid = next(self._rid)
        obj = {**obj, "rid": rid}
        fut = self.loop.create_future()
        self._pending[rid] = fut
        t = self.request_timeout if timeout is None else timeout
        try:
            await self.send(obj, meta=meta)
            if t and t > 0:
                try:
                    resp = await asyncio.wait_for(fut, t)
                except asyncio.TimeoutError:
                    # fail-stop: a worker that missed a control deadline
                    # is indistinguishable from a dead one — mark it so
                    # recovery (respawn over durable state) takes over
                    self._mark_dead()
                    raise WorkerDied(
                        f"worker {self.worker_id} request "
                        f"{obj.get('type')!r} timed out after {t}s") \
                        from None
            else:
                resp = await fut
        finally:
            # a caller-side wait_for timeout cancels ``fut`` but would
            # otherwise leave its rid in _pending forever (the late
            # reply, if any, is discarded by _read_loop's pop)
            self._pending.pop(rid, None)
        if resp.get("ok") is False:
            raise RuntimeError(
                f"worker {self.worker_id}: {resp.get('error')}")
        return resp

    # -- data channels ---------------------------------------------------------

    def alloc_chan(self) -> int:
        from ..rpc.exchange import AckWatermark
        chan = next(self._chan)
        self._sems[chan] = asyncio.Semaphore(self.permits)
        self._data_seqs[chan] = 0
        self._acks[chan] = AckWatermark()
        return chan

    async def send_data(self, chan: int, msg: Message, schema) -> None:
        from ..common.chunk import StreamChunk
        if isinstance(msg, StreamChunk):
            sem = self._sems.get(chan)
            if sem is not None:
                await sem.acquire()
            if self.dead:
                raise WorkerDied("worker is down")
        seq = self._data_seqs.get(chan, 0)
        self._data_seqs[chan] = seq + 1
        await self.send({"type": "data", "chan": chan, "seq": seq,
                         "msg": message_to_wire(msg, schema)})

    def start_forwarder(self, job: str, q: QueueSource, chan: int,
                        schema) -> None:
        """Forward an upstream bus subscription over a data channel —
        the session side of the remote exchange edge."""

        async def run() -> None:
            try:
                async for msg in q.execute():
                    await self.send_data(chan, msg, schema)
            except WorkerDied:
                pass                      # recovery re-wires the edge
            except Exception as e:        # noqa: BLE001 - must be LOUD:
                import sys                # a dead forwarder starves the job
                sys.stderr.write(
                    f"exchange forwarder {job!r}/chan {chan} died: "
                    f"{e!r}\n")
                raise

        self._forwarders.setdefault(job, []).append(
            asyncio.ensure_future(run(), loop=self.loop))

    def stop_forwarders(self, job: str) -> list[asyncio.Task]:
        tasks = self._forwarders.pop(job, [])
        for t in tasks:
            t.cancel()
        return tasks

    # -- barrier conduction ----------------------------------------------------

    async def inject_barrier(self, epoch: int, checkpoint: bool,
                             generate: bool, mutation=None,
                             exclude=None) -> None:
        for old in [e for e in self._epoch_events if e < epoch - 64]:
            self._epoch_events.pop(old, None)
            self._epoch_errors.pop(old, None)
        frame = {"type": "barrier", "epoch": epoch, "checkpoint": checkpoint,
                 "generate": generate}
        if exclude:
            # jobs the session already declared dead (spanning jobs with
            # a killed peer): the worker must not feed or wait on them
            frame["exclude"] = sorted(exclude)
        if mutation is not None:
            frame["mutation"] = mutation.kind.value
            if isinstance(mutation.payload, str):
                frame["mutation_payload"] = mutation.payload
        await self.send(frame)

    async def init_barrier(self, name: str, epoch: int) -> None:
        """Init cut for a just-created job (replaces the local path's
        direct queue push)."""
        self._init_fut = self.loop.create_future()
        await self.send({"type": "barrier", "epoch": epoch,
                         "checkpoint": False, "generate": False,
                         "only": [name], "init": True})
        try:
            if self.epoch_timeout and self.epoch_timeout > 0:
                frame = await asyncio.wait_for(self._init_fut,
                                               self.epoch_timeout)
            else:
                frame = await self._init_fut
        except asyncio.TimeoutError:
            self._mark_dead()
            raise WorkerDied(
                f"worker {self.worker_id} init barrier for {name!r} "
                f"timed out after {self.epoch_timeout}s") from None
        finally:
            self._init_fut = None
        failed = dict(frame.get("failed") or {})
        if frame.get("ok", True) is False:
            failed["*"] = frame.get("error")
        err = failed.get(name) or failed.get("*")
        if err:
            raise RuntimeError(
                f"remote job {name!r} failed at init: {err}")

    def _job_error(self, epoch: int, job: Optional[str]) -> Optional[str]:
        failed = self._epoch_errors.get(epoch)
        if not failed:
            return None
        if isinstance(failed, dict):
            if job is not None:
                return failed.get(job) or failed.get("*")
            return "; ".join(f"{k}: {v}" for k, v in sorted(failed.items()))
        return str(failed)

    async def wait_epoch(self, epoch: int, job: Optional[str] = None) -> bool:
        """True iff the worker collected the epoch cleanly for ``job``
        (all jobs when None). Bounded by ``epoch_timeout``: a worker that
        stops acking barriers while its socket stays open (SIGSTOP,
        accelerator wedge) is declared dead instead of deadlocking the
        conductor — the heartbeat-TTL scoped recovery then respawns it
        over durable state. A ``PEER_LOST`` per-job error (this worker's
        fragment lost its exchange peer) also returns False — it is a
        kill signal for scoped recovery, not a poisoned job."""
        if self.dead:
            return False
        err = self._job_error(epoch, job)
        if err:
            if err.startswith("PEER_LOST"):
                return False
            raise RuntimeError(f"remote job failed: {err}")
        ev = self._epoch_events.setdefault(epoch, asyncio.Event())
        if self.epoch_timeout and self.epoch_timeout > 0:
            try:
                await asyncio.wait_for(ev.wait(), self.epoch_timeout)
            except asyncio.TimeoutError:
                self._mark_dead()
                return False
        else:
            await ev.wait()
        # NOT popped here: several RemoteJobs on this worker wait the same
        # epoch; entries are pruned by inject_barrier's horizon instead
        err = self._job_error(epoch, job)
        if err:
            if err.startswith("PEER_LOST"):
                return False
            raise RuntimeError(f"remote job failed: {err}")
        return not self.dead

    async def commit(self, epoch: int, skip_jobs=None) -> None:
        frame = {"type": "commit", "epoch": epoch}
        if skip_jobs:
            frame["skip_jobs"] = sorted(skip_jobs)
        await self.send(frame)

    async def get_stats(self, timeout: float = 10.0,
                        span_ack: Optional[int] = None,
                        stage_ack: Optional[int] = None) -> dict:
        """Fetch this worker's monitor snapshot (executor trees, counters,
        queue depths, state bytes, tracing spans, barrier stage events).
        ``span_ack``/``stage_ack`` echo the last ``span_seq``/``stage_seq``
        this session processed so the worker can discard its retained
        batches (a timed-out reply is resent, not lost)."""
        req: dict = {"type": "stats"}
        if span_ack is not None:
            req["span_ack"] = span_ack
        if stage_ack is not None:
            req["stage_ack"] = stage_ack
        return await asyncio.wait_for(self.request(req, meta=True),
                                      timeout)

    async def shutdown(self) -> None:
        try:
            await asyncio.wait_for(self.request({"type": "shutdown"}), 5.0)
        except (WorkerDied, RuntimeError, asyncio.TimeoutError):
            pass


class RemoteJob:
    """StreamJob-shaped adapter for a worker-hosted job: the conduction
    loop waits on the worker's epoch acks; ``sources`` are the
    session-side queues subscribed to upstream buses (feeding the
    forwarders); the bus is empty (downstream MVs on remote MVs are not
    supported yet)."""

    def __init__(self, name: str, worker: RemoteWorker):
        self.name = name
        self.worker = worker
        self.sources: list[QueueSource] = []
        self.bus = ChangelogBus()
        self.pipeline = None
        self.table = None
        self._failure: Optional[BaseException] = None
        self._task = None

    async def wait_barrier(self, epoch: int) -> None:
        try:
            ok = await self.worker.wait_epoch(epoch, job=self.name)
        except RuntimeError:
            self._failure = self._failure or RuntimeError("remote job failed")
            raise
        if not ok:
            # worker process died: present as a killed actor so the
            # session's TTL detector + scoped recovery machinery takes over
            self._failure = asyncio.CancelledError()
            raise RuntimeError(f"worker of remote job {self.name!r} died")

    async def stop(self) -> None:
        for t in self.worker.stop_forwarders(self.name):
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass


class SpanningJob:
    """StreamJob-shaped adapter for a job whose FRAGMENT GRAPH spans
    several worker processes: an epoch completes only when EVERY
    participating worker collected it for this job (each worker's ack
    asserts all of ITS fragment actors forwarded the barrier — so the
    epoch's data crossed every remote exchange edge before the session
    may commit: exactly-once across the wire). Any participant's death —
    its socket, its deadline, or a surviving peer's PEER_LOST report —
    presents as a killed actor so the heartbeat-TTL scoped recovery
    rebuilds the job's fragments from their per-worker durable state."""

    def __init__(self, name: str, workers: list[RemoteWorker]):
        self.name = name
        self.workers = list(workers)
        self.sources: list[QueueSource] = []
        self.bus = ChangelogBus()
        self.pipeline = None
        self.table = None
        self._failure: Optional[BaseException] = None
        self._task = None

    async def wait_barrier(self, epoch: int) -> None:
        results = await asyncio.gather(
            *(w.wait_epoch(epoch, job=self.name) for w in self.workers),
            return_exceptions=True)
        hard = [r for r in results if isinstance(r, BaseException)
                and not isinstance(r, (WorkerDied,))]
        if hard:
            self._failure = self._failure or hard[0]
            raise RuntimeError(
                f"spanning job {self.name!r} failed") from hard[0]
        if not all(r is True for r in results):
            self._failure = asyncio.CancelledError()
            raise RuntimeError(
                f"a worker of spanning job {self.name!r} died")

    async def stop(self) -> None:
        return None
