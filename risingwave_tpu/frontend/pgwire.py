"""pgwire: Postgres wire-protocol (v3) server over asyncio.

Counterpart of the reference's pgwire crate
(reference: src/utils/pgwire/src/pg_server.rs:131 ``pg_serve``,
pg_protocol.rs:220-259 message loop). Implements BOTH flows:

* simple query — Query, RowDescription/DataRow/CommandComplete,
  ErrorResponse, ReadyForQuery, Terminate;
* extended query (r5) — Parse/Bind/Describe/Execute/Close/Flush/Sync with
  text-format parameters, prepared-statement + portal registries, and
  error-skip-until-Sync semantics (reference: pg_protocol.rs:220-259
  extended-mode dispatch, pg_extended.rs portals).

Parameters arrive as text; binding substitutes them into the SQL by a
quote-aware scan ($n never matches inside string literals), typed by the
Parse-declared OIDs when present and by literal shape otherwise — the
statement then flows through the same planner/binder as any other SQL
(the reference rewrites $n into bound parameters at the binder level;
this design keeps ONE front door instead).

The Session API is synchronous and owns its private event loop, so query
execution is serialized onto one worker thread; protocol IO stays on the
server's asyncio loop.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import struct
from typing import Optional

from ..common.config import MetaConfig
from ..common.types import DataType, TypeKind
from .session import Session, SqlError

# Postgres type OIDs (reference: pg_type.h; pgwire/src/types.rs)
_OIDS = {
    TypeKind.BOOL: 16,
    TypeKind.INT16: 21,
    TypeKind.INT32: 23,
    TypeKind.INT64: 20,
    TypeKind.FLOAT32: 700,
    TypeKind.FLOAT64: 701,
    TypeKind.DECIMAL: 1700,
    TypeKind.DATE: 1082,
    TypeKind.TIME: 1083,
    TypeKind.TIMESTAMP: 1114,
    TypeKind.INTERVAL: 1186,
    TypeKind.VARCHAR: 25,
    TypeKind.BYTEA: 17,
    TypeKind.SERIAL: 20,
}


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


# OIDs whose text values inline unquoted into SQL
_NUMERIC_OIDS = {16, 20, 21, 23, 700, 701, 1700}

import re as _re

_NUM_RE = _re.compile(r"-?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?")


def _render_param(value: Optional[str], oid: int) -> str:
    """Render one text-format parameter as a SQL literal. Parameters are
    DATA: a numeric-OID value that is not numeric-shaped is rejected, not
    inlined (inlining it verbatim would let a bound parameter alter the
    query's syntax)."""
    if value is None:
        return "NULL"
    if oid in _NUMERIC_OIDS:
        if oid == 16:
            low = value.strip().lower()
            if low in ("t", "true", "y", "yes", "on", "1"):
                return "TRUE"
            if low in ("f", "false", "n", "no", "off", "0"):
                return "FALSE"
            raise ValueError(
                f"invalid input syntax for type boolean: {value!r}")
        if not _NUM_RE.fullmatch(value):
            raise ValueError(
                f"invalid input for numeric parameter: {value!r}")
        return value
    if oid == 0 and _NUM_RE.fullmatch(value):  # undeclared: shape decides
        return value
    return "'" + value.replace("'", "''") + "'"


def _scan_params(sql: str, on_param) -> str:
    """Quote-aware scan: calls ``on_param(idx) -> replacement`` for every
    $n outside string literals / quoted identifiers."""
    out = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c == "'":                       # string literal ('' escapes)
            j = i + 1
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i:j + 1])
            i = j + 1
        elif c == '"':                     # quoted identifier
            j = sql.find('"', i + 1)
            j = n - 1 if j < 0 else j
            out.append(sql[i:j + 1])
            i = j + 1
        elif c == "$" and i + 1 < n and sql[i + 1].isdigit():
            j = i + 1
            while j < n and sql[j].isdigit():
                j += 1
            out.append(on_param(int(sql[i + 1:j]) - 1))
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _substitute_params(sql: str, params: list, oids: list) -> str:
    """Replace $n placeholders with rendered literals."""
    def render(idx: int) -> str:
        if idx < 0 or idx >= len(params):
            raise ValueError(f"parameter ${idx + 1} not bound")
        oid = oids[idx] if idx < len(oids) else 0
        return _render_param(params[idx], oid)

    return _scan_params(sql, render)


def _count_params(sql: str) -> int:
    """Number of distinct $n placeholders (max index), quote-aware —
    Describe(statement) must report the INFERRED parameter count even
    when Parse declared none (drivers that Describe before Bind rely on
    it)."""
    seen = [0]

    def note(idx: int) -> str:
        seen[0] = max(seen[0], idx + 1)
        return ""

    _scan_params(sql, note)
    return seen[0]


def _fmt_value(v, t: Optional[DataType]) -> str:
    import datetime as _dt
    if t is None:
        return str(v)
    if t.kind == TypeKind.BOOL:
        return "t" if v else "f"
    if t.kind == TypeKind.DATE and isinstance(v, int):
        return (_dt.date(1970, 1, 1) + _dt.timedelta(days=v)).isoformat()
    if t.kind == TypeKind.TIMESTAMP and isinstance(v, int):
        return (_dt.datetime(1970, 1, 1)
                + _dt.timedelta(microseconds=v)).isoformat(sep=" ")
    if t.kind == TypeKind.TIME and isinstance(v, int):
        us = v % 1_000_000
        sec = v // 1_000_000
        base = f"{sec // 3600:02d}:{(sec // 60) % 60:02d}:{sec % 60:02d}"
        return f"{base}.{us:06d}" if us else base
    if t.kind == TypeKind.INTERVAL and isinstance(v, int):
        sign = "-" if v < 0 else ""
        av = abs(v)
        us = av % 1_000_000
        sec = av // 1_000_000
        base = (f"{sign}{sec // 3600:02d}:"
                f"{(sec // 60) % 60:02d}:{sec % 60:02d}")
        return f"{base}.{us:06d}" if us else base
    return str(v)


class QueryShed(Exception):
    """Raised when admission control refuses to queue another query."""


class AdmissionController:
    """Admission control for query execution (the frontend-fleet overload
    story): the Session executes on ONE worker thread, so overload on a
    serving frontend shows up as an unbounded executor queue — every
    queued query pays the full backlog latency and nothing bounds p99.
    This bounds it: at most ``max_inflight`` queries are dispatched to
    the worker at once, up to ``queue_depth`` more wait on the asyncio
    side, and beyond that new queries are SHED with a retryable PG error
    (SQLSTATE 53300) instead of growing the backlog — overload degrades
    by queueing with bounded p99, not collapse. A single connection may
    hold at most ``per_conn_inflight`` slots, so one pipelining client
    cannot occupy the whole admission window."""

    def __init__(self, max_inflight: int = 8, per_conn_inflight: int = 2,
                 queue_depth: int = 64):
        self.max_inflight = max(1, int(max_inflight))
        self.per_conn_inflight = max(1, int(per_conn_inflight))
        self.queue_depth = max(0, int(queue_depth))
        # created eagerly; binds to the running loop on first await (3.10+)
        self._sem = asyncio.Semaphore(self.max_inflight)
        self._waiting = 0
        self._inflight = 0
        self.stats = {"admitted": 0, "queued": 0, "shed": 0,
                      "max_queued": 0, "max_inflight": 0}

    def conn_slot(self) -> asyncio.Semaphore:
        """Per-connection quota, one per accepted connection."""
        return asyncio.Semaphore(self.per_conn_inflight)

    async def acquire(self, conn_sem: Optional[asyncio.Semaphore]) -> None:
        would_wait = self._sem.locked() or (
            conn_sem is not None and conn_sem.locked())
        if would_wait:
            if self._waiting >= self.queue_depth:
                self.stats["shed"] += 1
                raise QueryShed(
                    f"server overloaded: {self._inflight} queries in "
                    f"flight, {self._waiting} queued "
                    f"(queue depth {self.queue_depth}); retry later")
            self._waiting += 1
            self.stats["queued"] += 1
            self.stats["max_queued"] = max(
                self.stats["max_queued"], self._waiting)
        try:
            if conn_sem is not None:
                await conn_sem.acquire()
            try:
                await self._sem.acquire()
            except BaseException:
                if conn_sem is not None:
                    conn_sem.release()
                raise
        finally:
            if would_wait:
                self._waiting -= 1
        self._inflight += 1
        self.stats["admitted"] += 1
        self.stats["max_inflight"] = max(
            self.stats["max_inflight"], self._inflight)

    def release(self, conn_sem: Optional[asyncio.Semaphore]) -> None:
        self._inflight -= 1
        self._sem.release()
        if conn_sem is not None:
            conn_sem.release()

    def snapshot(self) -> dict:
        return dict(self.stats, waiting=self._waiting,
                    inflight=self._inflight)


class PgWireServer:
    def __init__(self, session: Session, host: str = "127.0.0.1",
                 port: int = 4566, auth: Optional[dict] = None,
                 auth_method: str = "md5",
                 admission: Optional[MetaConfig] = None):
        """``auth``: user → password map enabling password authentication
        (reference: pg_protocol.rs:220-259 startup auth; SCRAM/TLS are
        not implemented — md5 and cleartext cover psql/psycopg2/JDBC
        defaults). ``auth=None`` = trust (playground default)."""
        self.session = session
        self.host = host
        self.port = port
        if auth_method not in ("md5", "cleartext"):
            raise ValueError(f"unknown auth method {auth_method!r}")
        self.auth = dict(auth) if auth else None
        self.auth_method = auth_method
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()      # live client writers (forced closed)
        # one worker thread: the Session is single-threaded by design
        self._executor = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        cfg = admission if admission is not None else MetaConfig()
        self.admission = AdmissionController(
            cfg.admission_max_inflight, cfg.admission_per_conn_inflight,
            cfg.admission_queue_depth)
        self._conn_slots: dict = {}   # writer -> per-connection semaphore

    async def _authenticate(self, reader, writer, user: str) -> bool:
        import hashlib
        import os as _os
        expected = self.auth.get(user)
        if self.auth_method == "md5":
            salt = _os.urandom(4)
            writer.write(_msg(b"R", struct.pack("!I", 5) + salt))
        else:
            writer.write(_msg(b"R", struct.pack("!I", 3)))
        await writer.drain()
        tag = await reader.readexactly(1)
        ln = struct.unpack("!I", await reader.readexactly(4))[0]
        body = await reader.readexactly(ln - 4)
        if tag != b"p":
            return False
        supplied = body.rstrip(b"\x00").decode("utf-8", "replace")
        if expected is None:
            ok = False          # unknown user: burn the exchange anyway
        elif self.auth_method == "md5":
            inner = hashlib.md5(
                (expected + user).encode()).hexdigest().encode()
            want = "md5" + hashlib.md5(inner + salt).hexdigest()
            ok = supplied == want
        else:
            ok = supplied == expected
        if not ok:
            self._send_error(
                writer, f'password authentication failed for user "{user}"')
            await writer.drain()
            return False
        return True

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            # 3.12 wait_closed() waits for connection HANDLERS too — a
            # client that never disconnects would hang shutdown, so force
            # the remaining transports closed first
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()
        self._executor.shutdown(wait=False)

    # -- protocol -------------------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # per-connection extended-protocol state (reference: pg_extended.rs)
        stmts: dict[str, tuple[str, list]] = {}     # name -> (sql, oids)
        portals: dict[str, tuple[str, Optional[list]]] = {}  # -> (sql, schema)
        skip_until_sync = False
        self._conns.add(writer)
        self._conn_slots[writer] = self.admission.conn_slot()
        try:
            if not await self._startup(reader, writer):
                return
            while True:
                hdr = await reader.readexactly(5)
                tag, ln = hdr[0:1], struct.unpack("!I", hdr[1:5])[0]
                body = await reader.readexactly(ln - 4)
                if tag == b"X":          # Terminate
                    break
                if tag == b"S":          # Sync: end of an extended batch
                    skip_until_sync = False
                    writer.write(_msg(b"Z", b"I"))
                    await writer.drain()
                    continue
                if skip_until_sync and tag in (b"P", b"B", b"D", b"E", b"C",
                                               b"H"):
                    continue             # error mode: discard until Sync
                if tag == b"Q":
                    sql = body.rstrip(b"\x00").decode()
                    await self._run_query(writer, sql)
                elif tag == b"P":
                    skip_until_sync = not await self._on_parse(
                        writer, body, stmts)
                elif tag == b"B":
                    skip_until_sync = not await self._on_bind(
                        writer, body, stmts, portals)
                elif tag == b"D":
                    skip_until_sync = not await self._on_describe(
                        writer, body, stmts, portals)
                elif tag == b"E":
                    skip_until_sync = not await self._on_execute(
                        writer, body, portals)
                elif tag == b"C":        # Close statement/portal
                    kind, name = body[0:1], body[1:].split(b"\x00")[0].decode()
                    (stmts if kind == b"S" else portals).pop(name, None)
                    writer.write(_msg(b"3", b""))    # CloseComplete
                elif tag == b"H":        # Flush
                    await writer.drain()
                else:
                    self._send_error(writer, f"unknown message {tag!r}")
                    writer.write(_msg(b"Z", b"I"))
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self._conns.discard(writer)
            self._conn_slots.pop(writer, None)
            writer.close()

    # -- extended-query flow ---------------------------------------------------

    async def _on_parse(self, writer, body: bytes, stmts) -> bool:
        try:
            name, rest = body.split(b"\x00", 1)
            sql, rest = rest.split(b"\x00", 1)
            (n_oids,) = struct.unpack_from("!H", rest, 0)
            oids = list(struct.unpack_from(f"!{n_oids}I", rest, 2))
            stmts[name.decode()] = (sql.decode(), oids)
            writer.write(_msg(b"1", b""))            # ParseComplete
            return True
        except Exception as e:  # noqa: BLE001
            self._send_error(writer, f"parse failed: {e}")
            await writer.drain()
            return False

    async def _on_bind(self, writer, body: bytes, stmts, portals) -> bool:
        try:
            portal, rest = body.split(b"\x00", 1)
            stmt_name, rest = rest.split(b"\x00", 1)
            pos = 0
            (n_fmt,) = struct.unpack_from("!H", rest, pos)
            pos += 2 + 2 * n_fmt
            fmts = list(struct.unpack_from(f"!{n_fmt}H", rest, 2))
            (n_params,) = struct.unpack_from("!H", rest, pos)
            pos += 2
            params: list = []
            for i in range(n_params):
                (plen,) = struct.unpack_from("!i", rest, pos)
                pos += 4
                if plen < 0:
                    params.append(None)
                else:
                    raw = rest[pos:pos + plen]
                    pos += plen
                    fmt = (fmts[i] if i < len(fmts)
                           else (fmts[0] if len(fmts) == 1 else 0))
                    if fmt == 1:
                        raise ValueError(
                            "binary parameter format not supported")
                    params.append(raw.decode())
            # result-column formats: text only (a client asking for
            # binary results must get an ERROR, not text bytes it will
            # misdecode as binary)
            (n_res,) = struct.unpack_from("!H", rest, pos)
            res_fmts = struct.unpack_from(f"!{n_res}H", rest, pos + 2)
            if any(f == 1 for f in res_fmts):
                raise ValueError("binary result format not supported")
            sql, oids = stmts[stmt_name.decode()]
            bound = _substitute_params(sql, params, oids)
            portals[portal.decode()] = (bound, None)
            writer.write(_msg(b"2", b""))            # BindComplete
            return True
        except KeyError:
            self._send_error(writer, "unknown prepared statement")
            await writer.drain()
            return False
        except Exception as e:  # noqa: BLE001
            self._send_error(writer, f"bind failed: {e}")
            await writer.drain()
            return False

    def _write_row_description(self, writer, schema) -> None:
        payload = struct.pack("!H", len(schema))
        for name, t in schema:
            payload += (_cstr(name) + struct.pack(
                "!IHIhih", 0, 0, _OIDS.get(t.kind, 25), -1, -1, 0))
        writer.write(_msg(b"T", payload))

    def _write_data_rows(self, writer, rows, schema) -> None:
        for row in rows:
            body = struct.pack("!H", len(row))
            for v, (_, t) in zip(row, schema):
                if v is None:
                    body += struct.pack("!i", -1)
                else:
                    s = _fmt_value(v, t).encode()
                    body += struct.pack("!i", len(s)) + s
            writer.write(_msg(b"D", body))

    async def _on_describe(self, writer, body: bytes, stmts,
                           portals) -> bool:
        kind, name = body[0:1], body[1:].split(b"\x00")[0].decode()
        try:
            if kind == b"S":
                sql, oids = stmts[name]
                n_params = max(len(oids), _count_params(sql))
                all_oids = list(oids) + [25] * (n_params - len(oids))
                writer.write(_msg(b"t", struct.pack(
                    f"!H{n_params}I", n_params, *all_oids)))
                # schema of a parameterized statement: plan with NULLs
                probe = _substitute_params(
                    sql, [None] * 64, oids or [0] * 64)
                schema = await self._admitted(writer, self._describe, probe)
            else:
                sql, schema = portals[name]
                if schema is None:
                    schema = await self._admitted(
                        writer, self._describe, sql)
                    portals[name] = (sql, schema)
            if schema is None:
                writer.write(_msg(b"n", b""))        # NoData
            else:
                self._write_row_description(writer, schema)
            return True
        except KeyError:
            self._send_error(writer, "unknown statement/portal")
            await writer.drain()
            return False
        except QueryShed as e:
            self._send_error(writer, str(e), code="53300")
            await writer.drain()
            return False
        except Exception:  # noqa: BLE001 - undescribable: NoData, not fatal
            writer.write(_msg(b"n", b""))
            return True

    async def _on_execute(self, writer, body: bytes, portals) -> bool:
        name = body.split(b"\x00")[0].decode()
        try:
            sql, _schema = portals[name]
        except KeyError:
            self._send_error(writer, "unknown portal")
            await writer.drain()
            return False
        try:
            rows, schema, command = await self._admitted(
                writer, self._execute, sql)
        except QueryShed as e:
            self._send_error(writer, str(e), code="53300")
            await writer.drain()
            return False
        except Exception as e:  # noqa: BLE001
            self._send_error(writer, str(e))
            await writer.drain()
            return False
        if schema is not None:
            self._write_data_rows(writer, rows, schema)
            command = f"SELECT {len(rows)}"
        writer.write(_msg(b"C", _cstr(command)))
        await writer.drain()
        return True

    async def _admitted(self, writer, fn, *args):
        """Run ``fn`` on the session worker thread under admission
        control. Raises QueryShed when the wait queue is full."""
        conn_sem = self._conn_slots.get(writer)
        await self.admission.acquire(conn_sem)
        try:
            return await asyncio.get_running_loop().run_in_executor(
                self._executor, fn, *args)
        finally:
            self.admission.release(conn_sem)

    def _describe(self, sql: str):
        """Worker-thread: output schema of ``sql`` WITHOUT executing it
        (None for statements that return no rows)."""
        return self.session.describe(sql)

    async def _startup(self, reader, writer) -> bool:
        while True:
            ln = struct.unpack("!I", await reader.readexactly(4))[0]
            body = await reader.readexactly(ln - 4)
            code = struct.unpack("!I", body[:4])[0]
            if code in (80877103, 80877104):   # SSLRequest / GSSENCRequest
                writer.write(b"N")             # not supported; plaintext
                await writer.drain()
                continue
            if code == 80877102:         # CancelRequest
                return False
            break                         # StartupMessage
        # startup parameters: null-separated key/value pairs
        params = {}
        parts = body[4:].split(b"\x00")
        for i in range(0, len(parts) - 1, 2):
            if parts[i]:
                params[parts[i].decode("utf-8", "replace")] = \
                    parts[i + 1].decode("utf-8", "replace")
        if self.auth:
            ok = await self._authenticate(reader, writer,
                                          params.get("user", ""))
            if not ok:
                return False
        # else trust auth (reference playground default)
        writer.write(_msg(b"R", struct.pack("!I", 0)))       # AuthenticationOk
        for k, v in (("server_version", "13.0"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8")):
            writer.write(_msg(b"S", _cstr(k) + _cstr(v)))    # ParameterStatus
        writer.write(_msg(b"K", struct.pack("!II", 0, 0)))   # BackendKeyData
        writer.write(_msg(b"Z", b"I"))                       # ReadyForQuery
        await writer.drain()
        return True

    async def _run_query(self, writer, sql: str) -> None:
        if not sql.strip():
            writer.write(_msg(b"I", b""))            # EmptyQueryResponse
            writer.write(_msg(b"Z", b"I"))
            await writer.drain()
            return
        try:
            rows, schema, command = await self._admitted(
                writer, self._execute, sql)
        except QueryShed as e:
            self._send_error(writer, str(e), code="53300")
            writer.write(_msg(b"Z", b"I"))
            await writer.drain()
            return
        except Exception as e:  # noqa: BLE001 - surfaced as ErrorResponse
            self._send_error(writer, str(e))
            writer.write(_msg(b"Z", b"I"))
            await writer.drain()
            return
        if schema is not None:
            self._write_row_description(writer, schema)
            self._write_data_rows(writer, rows, schema)
            command = f"SELECT {len(rows)}"
        writer.write(_msg(b"C", _cstr(command)))     # CommandComplete
        writer.write(_msg(b"Z", b"I"))               # ReadyForQuery
        await writer.drain()

    def _execute(self, sql: str):
        """Worker-thread entry: returns (rows, schema-or-None, command)."""
        from . import sqlast as A
        from ..common.types import VARCHAR
        from .parser import parse_sql
        stmts = parse_sql(sql)
        rows = self.session.run_sql(sql)
        schema = None
        if stmts and isinstance(stmts[-1], A.ShowStatement):
            if stmts[-1].what == "parameters":
                schema = [("Name", VARCHAR), ("Value", VARCHAR)]
            else:
                schema = [("Name", VARCHAR)]
        elif stmts and isinstance(stmts[-1], (A.Query, A.Explain)):
            # plan-derived output schema, stored by Session.query /
            # Session._explain — no second planning pass
            schema = list(self.session.last_select_schema)
        command = "OK"
        if stmts:
            command = type(stmts[-1]).__name__.replace("Statement", "").upper()
        return rows, schema, command

    def _send_error(self, writer, message: str,
                    code: str = "XX000") -> None:
        payload = (b"S" + _cstr("ERROR") + b"C" + _cstr(code)
                   + b"M" + _cstr(message) + b"\x00")
        writer.write(_msg(b"E", payload))


def serve(session: Session, host: str = "127.0.0.1", port: int = 4566):
    """Blocking entry point (reference: pg_serve, pg_server.rs:131)."""
    srv = PgWireServer(session, host, port)
    asyncio.run(srv.serve_forever())
