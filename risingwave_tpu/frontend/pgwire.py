"""pgwire: Postgres wire-protocol (v3) server over asyncio.

Counterpart of the reference's pgwire crate
(reference: src/utils/pgwire/src/pg_server.rs:131 ``pg_serve``,
pg_protocol.rs:220-259 message loop). Implements the simple-query flow —
startup (trust auth), Query, RowDescription/DataRow/CommandComplete,
ErrorResponse, ReadyForQuery, Terminate — enough for psql/BI tools and the
sqllogictest-style drivers the reference serves.

The Session API is synchronous and owns its private event loop, so query
execution is serialized onto one worker thread; protocol IO stays on the
server's asyncio loop.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import struct
from typing import Optional

from ..common.types import DataType, TypeKind
from .session import Session, SqlError

# Postgres type OIDs (reference: pg_type.h; pgwire/src/types.rs)
_OIDS = {
    TypeKind.BOOL: 16,
    TypeKind.INT16: 21,
    TypeKind.INT32: 23,
    TypeKind.INT64: 20,
    TypeKind.FLOAT32: 700,
    TypeKind.FLOAT64: 701,
    TypeKind.DECIMAL: 1700,
    TypeKind.DATE: 1082,
    TypeKind.TIME: 1083,
    TypeKind.TIMESTAMP: 1114,
    TypeKind.INTERVAL: 1186,
    TypeKind.VARCHAR: 25,
    TypeKind.BYTEA: 17,
    TypeKind.SERIAL: 20,
}


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _fmt_value(v, t: Optional[DataType]) -> str:
    import datetime as _dt
    if t is None:
        return str(v)
    if t.kind == TypeKind.BOOL:
        return "t" if v else "f"
    if t.kind == TypeKind.DATE and isinstance(v, int):
        return (_dt.date(1970, 1, 1) + _dt.timedelta(days=v)).isoformat()
    if t.kind == TypeKind.TIMESTAMP and isinstance(v, int):
        return (_dt.datetime(1970, 1, 1)
                + _dt.timedelta(microseconds=v)).isoformat(sep=" ")
    if t.kind == TypeKind.TIME and isinstance(v, int):
        us = v % 1_000_000
        sec = v // 1_000_000
        base = f"{sec // 3600:02d}:{(sec // 60) % 60:02d}:{sec % 60:02d}"
        return f"{base}.{us:06d}" if us else base
    if t.kind == TypeKind.INTERVAL and isinstance(v, int):
        sign = "-" if v < 0 else ""
        av = abs(v)
        us = av % 1_000_000
        sec = av // 1_000_000
        base = (f"{sign}{sec // 3600:02d}:"
                f"{(sec // 60) % 60:02d}:{sec % 60:02d}")
        return f"{base}.{us:06d}" if us else base
    return str(v)


class PgWireServer:
    def __init__(self, session: Session, host: str = "127.0.0.1",
                 port: int = 4566):
        self.session = session
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        # one worker thread: the Session is single-threaded by design
        self._executor = concurrent.futures.ThreadPoolExecutor(max_workers=1)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._executor.shutdown(wait=False)

    # -- protocol -------------------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            if not await self._startup(reader, writer):
                return
            while True:
                hdr = await reader.readexactly(5)
                tag, ln = hdr[0:1], struct.unpack("!I", hdr[1:5])[0]
                body = await reader.readexactly(ln - 4)
                if tag == b"X":          # Terminate
                    break
                if tag == b"Q":
                    sql = body.rstrip(b"\x00").decode()
                    await self._run_query(writer, sql)
                elif tag in (b"P", b"B", b"D", b"E", b"S", b"C"):
                    # extended protocol not supported: report cleanly once a
                    # Sync arrives (reference: pg_protocol extended mode)
                    if tag == b"S":
                        self._send_error(
                            writer, "extended query protocol not supported")
                        writer.write(_msg(b"Z", b"I"))
                        await writer.drain()
                else:
                    self._send_error(writer, f"unknown message {tag!r}")
                    writer.write(_msg(b"Z", b"I"))
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def _startup(self, reader, writer) -> bool:
        while True:
            ln = struct.unpack("!I", await reader.readexactly(4))[0]
            body = await reader.readexactly(ln - 4)
            code = struct.unpack("!I", body[:4])[0]
            if code in (80877103, 80877104):   # SSLRequest / GSSENCRequest
                writer.write(b"N")             # not supported; plaintext
                await writer.drain()
                continue
            if code == 80877102:         # CancelRequest
                return False
            break                         # StartupMessage
        # trust auth (reference playground default)
        writer.write(_msg(b"R", struct.pack("!I", 0)))       # AuthenticationOk
        for k, v in (("server_version", "13.0"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8")):
            writer.write(_msg(b"S", _cstr(k) + _cstr(v)))    # ParameterStatus
        writer.write(_msg(b"K", struct.pack("!II", 0, 0)))   # BackendKeyData
        writer.write(_msg(b"Z", b"I"))                       # ReadyForQuery
        await writer.drain()
        return True

    async def _run_query(self, writer, sql: str) -> None:
        if not sql.strip():
            writer.write(_msg(b"I", b""))            # EmptyQueryResponse
            writer.write(_msg(b"Z", b"I"))
            await writer.drain()
            return
        loop = asyncio.get_running_loop()
        try:
            rows, schema, command = await loop.run_in_executor(
                self._executor, self._execute, sql)
        except Exception as e:  # noqa: BLE001 - surfaced as ErrorResponse
            self._send_error(writer, str(e))
            writer.write(_msg(b"Z", b"I"))
            await writer.drain()
            return
        if schema is not None:
            payload = struct.pack("!H", len(schema))
            for name, t in schema:
                payload += (_cstr(name) + struct.pack(
                    "!IHIhih", 0, 0, _OIDS.get(t.kind, 25), -1, -1, 0))
            writer.write(_msg(b"T", payload))        # RowDescription
            for row in rows:
                body = struct.pack("!H", len(row))
                for v, (_, t) in zip(row, schema):
                    if v is None:
                        body += struct.pack("!i", -1)
                    else:
                        s = _fmt_value(v, t).encode()
                        body += struct.pack("!i", len(s)) + s
                writer.write(_msg(b"D", body))       # DataRow
            command = f"SELECT {len(rows)}"
        writer.write(_msg(b"C", _cstr(command)))     # CommandComplete
        writer.write(_msg(b"Z", b"I"))               # ReadyForQuery
        await writer.drain()

    def _execute(self, sql: str):
        """Worker-thread entry: returns (rows, schema-or-None, command)."""
        from . import sqlast as A
        from ..common.types import VARCHAR
        from .parser import parse_sql
        stmts = parse_sql(sql)
        rows = self.session.run_sql(sql)
        schema = None
        if stmts and isinstance(stmts[-1], A.ShowStatement):
            if stmts[-1].what == "parameters":
                schema = [("Name", VARCHAR), ("Value", VARCHAR)]
            else:
                schema = [("Name", VARCHAR)]
        elif stmts and isinstance(stmts[-1], A.Query):
            # plan-derived output schema, stored by Session.query — no
            # second planning pass
            schema = list(self.session.last_select_schema)
        command = "OK"
        if stmts:
            command = type(stmts[-1]).__name__.replace("Statement", "").upper()
        return rows, schema, command

    def _send_error(self, writer, message: str) -> None:
        payload = (b"S" + _cstr("ERROR") + b"C" + _cstr("XX000")
                   + b"M" + _cstr(message) + b"\x00")
        writer.write(_msg(b"E", payload))


def serve(session: Session, host: str = "127.0.0.1", port: int = 4566):
    """Blocking entry point (reference: pg_serve, pg_server.rs:131)."""
    srv = PgWireServer(session, host, port)
    asyncio.run(srv.serve_forever())
