"""Meta dashboard: cluster / fragment-graph / await-tree introspection
over HTTP.

Counterpart of the reference's embedded meta dashboard (reference:
src/meta/src/dashboard/ serving the Next.js UI — cluster overview,
fragment graphs, await-tree dumps; the await-tree RPC is
src/compute/src/rpc/service/monitor_service.rs:46). Scaled to this
build: one threaded endpoint over the live Session serving a small
self-contained HTML page plus the JSON APIs it fetches:

    /                    HTML overview (no external assets)
    /api/cluster         epoch, worker processes, catalog inventory
    /api/fragments       per-MV fragment graph (explain text)
    /api/metrics         Session.metrics() as JSON (federated: includes
                         worker-hosted jobs' counters)
    /api/await_tree      executor trees with counters/queue depths —
                         local AND worker-hosted jobs
    /api/trace           Chrome trace-event JSON of the span ring
                         (load in Perfetto / chrome://tracing)
    /api/slow_epochs     captured slow-epoch span trees
    /api/profiler/start  POST-only: opt-in jax.profiler.trace capture
    /api/profiler/stop   (requires serve_dashboard(..., profiler_dir=...))

Thread safety: the handlers run on HTTP server threads while the session
thread mutates catalog/metrics/jobs mid-tick; every read happens under
the session's API lock (``Session._api_lock``), the same serialization
pgwire gets from its one-worker executor."""

from __future__ import annotations

import http.server
import json
import threading

_PAGE = """<!doctype html>
<html><head><title>risingwave_tpu dashboard</title><style>
body { font-family: monospace; margin: 2em; background: #fafafa; }
h1 { font-size: 1.2em; } h2 { font-size: 1em; margin-top: 1.5em; }
pre { background: #fff; border: 1px solid #ddd; padding: 1em;
      overflow-x: auto; }
</style></head><body>
<h1>risingwave_tpu dashboard</h1>
<p><a href="/api/trace" download="trace.json">download Chrome trace</a>
(load in Perfetto / chrome://tracing)</p>
<h2>cluster</h2><pre id="cluster">loading…</pre>
<h2>leadership</h2><pre id="leadership">loading…</pre>
<h2>fragment graphs</h2><pre id="fragments">loading…</pre>
<h2>exchange edges</h2><pre id="exchange">loading…</pre>
<h2>barriers</h2><pre id="barriers">loading…</pre>
<h2>serving plane</h2><pre id="serving">loading…</pre>
<h2>scaling</h2><pre id="scaling">loading…</pre>
<h2>chaos / fault plane</h2><pre id="chaos">loading…</pre>
<h2>profiling</h2><pre id="profiling">loading…</pre>
<h2>pipeline</h2><pre id="pipeline">loading…</pre>
<h2>await tree</h2><pre id="await_tree">loading…</pre>
<h2>slow epochs</h2><pre id="slow_epochs">loading…</pre>
<h2>storage tier</h2><pre id="storage">loading…</pre>
<h2>metrics</h2><pre id="metrics">loading…</pre>
<script>
async function load(id, url, text) {
  const r = await fetch(url);
  document.getElementById(id).textContent =
    text ? await r.text() : JSON.stringify(await r.json(), null, 2);
}
async function loadStorage() {
  const r = await fetch("/api/metrics");
  const m = await r.json();
  document.getElementById("leadership").textContent =
    JSON.stringify(m.leadership || {}, null, 2);
  document.getElementById("storage").textContent =
    JSON.stringify(m.storage || {}, null, 2);
  document.getElementById("exchange").textContent =
    JSON.stringify(m.exchange || [], null, 2);
  document.getElementById("barriers").textContent =
    JSON.stringify(m.barrier || {}, null, 2);
  document.getElementById("serving").textContent =
    JSON.stringify(m.serving || {}, null, 2);
  document.getElementById("scaling").textContent =
    JSON.stringify(m.autoscaler || {}, null, 2);
  document.getElementById("chaos").textContent =
    JSON.stringify(m.chaos || {}, null, 2);
  document.getElementById("profiling").textContent =
    JSON.stringify(m.profiling || {}, null, 2);
  document.getElementById("pipeline").textContent =
    JSON.stringify(m.pipeline || {}, null, 2);
  document.getElementById("metrics").textContent =
    JSON.stringify(m, null, 2);
}
function refresh() {
  load("cluster", "/api/cluster");
  load("fragments", "/api/fragments", true);
  load("await_tree", "/api/await_tree", true);
  load("slow_epochs", "/api/slow_epochs");
  loadStorage();
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


def cluster_info(session) -> dict:
    workers = []
    for i, w in enumerate(getattr(session, "workers", []) or []):
        workers.append({
            "worker": i,
            "pid": getattr(getattr(w, "proc", None), "pid", None),
            "dead": bool(getattr(w, "dead", False)),
        })
    return {
        "epoch": session.epoch,
        "paused": bool(getattr(session, "paused", False)),
        "workers": workers,
        "catalog": {
            "tables": sorted(session.catalog.tables),
            "sources": sorted(session.catalog.sources),
            "materialized_views": sorted(
                n for n in session.catalog.mvs
                if not n.startswith("__idx_")),
            "indexes": sorted(session.catalog.indexes),
            "sinks": sorted(session.catalog.sinks),
        },
        "jobs": sorted(session.jobs),
        "remote_jobs": sorted(getattr(session, "_remote_specs", {})),
        # spanning jobs: persisted fragment→worker placement (vnode
        # ranges per actor), the deployed counterpart of the planner-side
        # fragment graphs below
        "spanning_jobs": {
            name: spec["placement"].to_json()
            for name, spec in sorted(
                getattr(session, "_spanning_specs", {}).items())
        },
    }


def fragment_text(session) -> str:
    from ..meta.fragment import fragment_plan
    out = []
    for name, mv in sorted(session.catalog.mvs.items()):
        if name.startswith("__idx_"):
            continue
        ast = getattr(mv, "query_ast", None)
        if ast is None:
            continue
        try:
            plan = session._plan(ast)
            out.append(f"-- {name}\n{fragment_plan(plan).explain()}")
        except Exception as e:  # noqa: BLE001 — a bad plan must not 500
            out.append(f"-- {name}: <{type(e).__name__}: {e}>")
    return "\n\n".join(out) or "(no materialized views)"


class DashboardServer:
    """Threaded dashboard endpoint over a live Session.

    ``profiler_dir`` opts in the ``/api/profiler/{start,stop}`` endpoints
    (reference: the compute node's CPU/heap profiling RPCs,
    monitor_service.rs profiling handlers — here a ``jax.profiler.trace``
    capture of device/host activity, viewable in TensorBoard/Perfetto).
    Left ``None``, the endpoints answer 403: profiling captures can be
    large and must be an explicit operator decision."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0,
                 profiler_dir: str | None = None):
        sess = session
        srv = self
        self.profiler_dir = profiler_dir
        self._profiling = False
        self._closed = False
        self._profiler_lock = threading.Lock()

        def profiler(action: str) -> tuple[int, dict]:
            if srv.profiler_dir is None:
                return 403, {"error": "profiler disabled; pass "
                                      "profiler_dir to serve_dashboard"}
            import jax
            # handlers run on ThreadingHTTPServer threads: the
            # check-and-set must be atomic or two concurrent /start
            # requests double-start the device trace
            with srv._profiler_lock:
                if srv._closed:
                    # a /start racing close() must not win the lock and
                    # leave a device trace nobody will ever stop
                    return 503, {"error": "dashboard is shutting down"}
                if action == "start":
                    if srv._profiling:
                        return 409, {"error": "profiler already running"}
                    try:
                        jax.profiler.start_trace(srv.profiler_dir)
                    except RuntimeError as e:
                        # the jax profiler is PROCESS-global: a capture
                        # started by another server instance (or by user
                        # code) makes start_trace raise — that is the
                        # idempotency case, not an internal error, so it
                        # must answer 409 instead of raising out of the
                        # handler thread as a 500
                        return 409, {"error": f"profiler already "
                                              f"running: {e}"}
                    srv._profiling = True
                    return 200, {"ok": True, "dir": srv.profiler_dir}
                if srv._profiling:
                    try:
                        jax.profiler.stop_trace()
                    except Exception as e:  # noqa: BLE001 - report, don't 500
                        return 500, {"error": f"stop_trace failed: {e}"}
                    finally:
                        # even a failed stop ends the capture session —
                        # a sticky True would wedge /start with 409 and
                        # /stop with the same error forever
                        srv._profiling = False
                    return 200, {"ok": True, "dir": srv.profiler_dir}
                return 409, {"error": "profiler not running"}

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, body: bytes, ctype: str,
                      status: int = 200) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):       # noqa: N802 - stdlib API
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/":
                        return self._send(_PAGE.encode(),
                                          "text/html; charset=utf-8")
                    if path == "/api/cluster":
                        with sess._api_lock:
                            info = cluster_info(sess)
                        return self._send(json.dumps(info).encode(),
                                          "application/json")
                    if path == "/api/fragments":
                        with sess._api_lock:
                            text = fragment_text(sess)
                        return self._send(text.encode(),
                                          "text/plain; charset=utf-8")
                    if path == "/api/await_tree":
                        return self._send(sess.await_tree().encode(),
                                          "text/plain; charset=utf-8")
                    if path == "/api/metrics":
                        return self._send(
                            json.dumps(sess.metrics(),
                                       default=str).encode(),
                            "application/json")
                    if path == "/api/trace":
                        return self._send(
                            json.dumps(sess.export_chrome_trace()).encode(),
                            "application/json")
                    if path == "/api/slow_epochs":
                        return self._send(
                            json.dumps(sess.slow_epochs(),
                                       default=str).encode(),
                            "application/json")
                    if path in ("/api/profiler/start",
                                "/api/profiler/stop"):
                        # state-mutating: POST only, or any web page the
                        # operator has open could start a device trace
                        # via a drive-by <img src=…> GET
                        return self._send(
                            json.dumps({"error": "use POST"}).encode(),
                            "application/json", 405)
                except Exception as e:  # session mid-shutdown
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                self.send_response(404)
                self.end_headers()

            def do_POST(self):      # noqa: N802 - stdlib API
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path in ("/api/profiler/start",
                                "/api/profiler/stop"):
                        status, obj = profiler(path.rsplit("/", 1)[1])
                        return self._send(json.dumps(obj).encode(),
                                          "application/json", status)
                except Exception as e:
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                self.send_response(404)
                self.end_headers()

            def log_message(self, *a):   # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dashboard-endpoint")
        self._thread.start()

    def close(self) -> None:
        with self._profiler_lock:   # vs a concurrent /api/profiler/start
            self._closed = True
            if self._profiling:
                # a dangling device trace would buffer forever
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    pass
                self._profiling = False
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_dashboard(session, host: str = "127.0.0.1",
                    port: int = 0,
                    profiler_dir: str | None = None) -> DashboardServer:
    return DashboardServer(session, host, port, profiler_dir=profiler_dir)
