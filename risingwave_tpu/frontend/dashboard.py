"""Meta dashboard: cluster / fragment-graph / await-tree introspection
over HTTP.

Counterpart of the reference's embedded meta dashboard (reference:
src/meta/src/dashboard/ serving the Next.js UI — cluster overview,
fragment graphs, await-tree dumps; the await-tree RPC is
src/compute/src/rpc/service/monitor_service.rs:46). Scaled to this
build: one threaded endpoint over the live Session serving a small
self-contained HTML page plus the JSON APIs it fetches:

    /                    HTML overview (no external assets)
    /api/cluster         epoch, worker processes, catalog inventory
    /api/fragments       per-MV fragment graph (explain text)
    /api/metrics         Session.metrics() as JSON
    /api/await_tree      executor-tree dump with counters/queue depths
"""

from __future__ import annotations

import http.server
import json
import threading

_PAGE = """<!doctype html>
<html><head><title>risingwave_tpu dashboard</title><style>
body { font-family: monospace; margin: 2em; background: #fafafa; }
h1 { font-size: 1.2em; } h2 { font-size: 1em; margin-top: 1.5em; }
pre { background: #fff; border: 1px solid #ddd; padding: 1em;
      overflow-x: auto; }
</style></head><body>
<h1>risingwave_tpu dashboard</h1>
<h2>cluster</h2><pre id="cluster">loading…</pre>
<h2>fragment graphs</h2><pre id="fragments">loading…</pre>
<h2>await tree</h2><pre id="await_tree">loading…</pre>
<h2>metrics</h2><pre id="metrics">loading…</pre>
<script>
async function load(id, url, text) {
  const r = await fetch(url);
  document.getElementById(id).textContent =
    text ? await r.text() : JSON.stringify(await r.json(), null, 2);
}
function refresh() {
  load("cluster", "/api/cluster");
  load("fragments", "/api/fragments", true);
  load("await_tree", "/api/await_tree", true);
  load("metrics", "/api/metrics");
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


def cluster_info(session) -> dict:
    workers = []
    for i, w in enumerate(getattr(session, "workers", []) or []):
        workers.append({
            "worker": i,
            "pid": getattr(getattr(w, "proc", None), "pid", None),
            "dead": bool(getattr(w, "dead", False)),
        })
    return {
        "epoch": session.epoch,
        "paused": bool(getattr(session, "paused", False)),
        "workers": workers,
        "catalog": {
            "tables": sorted(session.catalog.tables),
            "sources": sorted(session.catalog.sources),
            "materialized_views": sorted(
                n for n in session.catalog.mvs
                if not n.startswith("__idx_")),
            "indexes": sorted(session.catalog.indexes),
            "sinks": sorted(session.catalog.sinks),
        },
        "jobs": sorted(session.jobs),
        "remote_jobs": sorted(getattr(session, "_remote_specs", {})),
    }


def fragment_text(session) -> str:
    from ..meta.fragment import fragment_plan
    out = []
    for name, mv in sorted(session.catalog.mvs.items()):
        if name.startswith("__idx_"):
            continue
        ast = getattr(mv, "query_ast", None)
        if ast is None:
            continue
        try:
            plan = session._plan(ast)
            out.append(f"-- {name}\n{fragment_plan(plan).explain()}")
        except Exception as e:  # noqa: BLE001 — a bad plan must not 500
            out.append(f"-- {name}: <{type(e).__name__}: {e}>")
    return "\n\n".join(out) or "(no materialized views)"


class DashboardServer:
    """Threaded dashboard endpoint over a live Session."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        sess = session

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, body: bytes, ctype: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):       # noqa: N802 - stdlib API
                path = self.path.rstrip("/") or "/"
                try:
                    if path == "/":
                        return self._send(_PAGE.encode(),
                                          "text/html; charset=utf-8")
                    if path == "/api/cluster":
                        return self._send(
                            json.dumps(cluster_info(sess)).encode(),
                            "application/json")
                    if path == "/api/fragments":
                        return self._send(fragment_text(sess).encode(),
                                          "text/plain; charset=utf-8")
                    if path == "/api/await_tree":
                        from ..stream.trace import dump_session
                        return self._send(dump_session(sess).encode(),
                                          "text/plain; charset=utf-8")
                    if path == "/api/metrics":
                        return self._send(
                            json.dumps(sess.metrics(),
                                       default=str).encode(),
                            "application/json")
                except Exception as e:  # session mid-shutdown
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                self.send_response(404)
                self.end_headers()

            def log_message(self, *a):   # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dashboard-endpoint")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_dashboard(session, host: str = "127.0.0.1",
                    port: int = 0) -> DashboardServer:
    return DashboardServer(session, host, port)
