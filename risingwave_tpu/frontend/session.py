"""Session: SQL entry point + single-process cluster (playground mode).

Counterpart of the reference's Session/handler dispatch + playground runtime
(reference: src/frontend/src/handler/mod.rs:167 per-statement dispatch;
src/cmd_all/src/playground.rs one-process cluster). The Session owns the
catalog, the state store, the running stream jobs, and the epoch clock: its
``tick()`` is the GlobalBarrierManager's inject/collect cycle (SURVEY.md
§3.2) — generate source chunks, push a barrier into every root queue, await
all jobs, commit the epoch on checkpoints.

Batch ``SELECT`` runs the SAME operator pipeline over snapshot sources (two
barriers bracket the snapshot), then folds the delta stream into rows — the
streaming/batch unification the reference gets from running batch plans
over Hummock snapshots (SURVEY.md §3.5), obtained here by construction.
"""

from __future__ import annotations

import asyncio
import dataclasses
import functools
import threading
from typing import Any, Callable, Optional, Sequence

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, StreamChunk,
    chunk_to_rows, make_chunk,
)
from ..common.config import MeshUnavailableError
from ..common.types import Field, Schema
from ..connector.nexmark import (
    AUCTION_SCHEMA, BID_SCHEMA, PERSON_SCHEMA, NexmarkConfig, NexmarkGenerator,
)
from ..storage.state_store import MemoryStateStore
from ..storage.state_table import StateTable
from ..stream.eowc import WatermarkFilterExecutor
from ..stream.executor import Executor
from ..stream.materialize import MaterializeExecutor
from ..stream.message import Barrier, Message, Mutation, MutationKind
from ..stream.row_id_gen import RowIdGenExecutor
from ..stream.source import MockSource
from . import sqlast as A
from .binder import BindError, ExprBinder, Scope
from .build import BuildConfig, BuildContext, build_plan, collect_leaves
from .catalog import (
    Catalog, CatalogError, MaterializedViewDef, SinkDef, SourceDef, TableDef,
    type_from_name,
)
from .parser import parse_sql
from .planner import Planner, PMvScan, PSource, PTableScan, PValues, PlanError
from .runtime import ChangelogBus, QueueSource, StreamJob


class SqlError(ValueError):
    pass


def _udf_snapshot() -> dict:
    from ..udf.client import udf_plane
    return udf_plane().snapshot()


def _ast_uses_udf(node) -> bool:
    """True when a query AST calls a REGISTERED UDF anywhere (generic
    dataclass walk). Placement routing: such plans build session-local —
    only this process's UDF plane can resolve the name."""
    import dataclasses as _dc
    from ..expr.udf import _UDF_NAMES
    if not _UDF_NAMES:
        return False
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (list, tuple)):
            stack.extend(n)
            continue
        if not _dc.is_dataclass(n):
            continue
        if isinstance(n, A.FuncCall) and \
                str(n.name).lower() in _UDF_NAMES:
            return True
        for f in _dc.fields(n):
            stack.append(getattr(n, f.name))
    return False


def _retry_snapshot() -> dict:
    from ..common.retry import GLOBAL_RETRY_METRICS
    return GLOBAL_RETRY_METRICS.snapshot()


def _locked(fn):
    """Serialize a public Session entry point on the session's API lock.

    The Session is single-threaded by design, but observability endpoints
    (dashboard / Prometheus HTTP threads) read catalog, metrics, and the
    event loop concurrently with the driving thread — the lock makes every
    public entry a consistent snapshot boundary (pgwire gets the same
    property from its one-worker executor). Reentrant: locked entries call
    each other (run_sql → flush → tick)."""

    @functools.wraps(fn)
    def inner(self, *args, **kwargs):
        with self._api_lock:
            return fn(self, *args, **kwargs)

    return inner


from ..connector.factory import DEBEZIUM_NEEDS_PK as _DEBEZIUM_NEEDS_PK

#: state-table id range reserved per fragment of a spanning job: each
#: fragment's build allocates ids from its own deterministic window, so
#: actors of one fragment (different workers, disjoint stores) share ids
#: while fragments never collide — and recovery replays identically
_SPAN_ID_STRIDE = 256


def _values_chunk(leaf: PValues) -> StreamChunk:
    """Constant-fold VALUES expressions into one chunk (row-less exprs are
    evaluated over a dummy 1-row chunk — the frontend's eval_const)."""
    import jax.numpy as jnp
    from ..expr.expr import Literal
    dummy = StreamChunk(jnp.zeros(1, jnp.int8), jnp.ones(1, jnp.bool_), ())
    rows = []
    for r in leaf.rows:
        vals = []
        for e in r:
            if isinstance(e, Literal):
                vals.append(e.value)
            else:
                c = e.eval(dummy)
                vals.append(e.type.to_python(c.data[0])
                            if bool(c.mask[0]) else None)
        rows.append(tuple(vals))
    return make_chunk(leaf.schema, rows, capacity=max(len(rows), 1))


@dataclasses.dataclass
class _BackfillRef:
    """A live BackfillExecutor and its owning job (for teardown)."""

    bf: Any
    job: str = ""


@dataclasses.dataclass
class _SourceFeed:
    """A connector instance feeding one job's source leaf.

    ``reader`` + ``state_table`` carry the split-state checkpoint contract
    (reference: source split state,
    src/stream/src/executor/source/state_table_handler.rs): the session
    records ``reader.offsets`` per injected epoch and persists the offsets
    for each checkpoint epoch atomically with that epoch's state commit;
    recovery seeks the reader before the first tick."""

    queue: QueueSource
    generator: Callable[[], Optional[StreamChunk]]
    reader: Optional[Any] = None
    state_table: Optional[StateTable] = None
    offsets_at_epoch: dict = dataclasses.field(default_factory=dict)
    job: str = ""          # owning stream job; feed dies with it on DROP


class _RowIdAppendSource(Executor):
    """Wraps a queue of connector chunks, appending the hidden _row_id
    column (reference: source executors append the row-id column before
    RowIdGen fills it)."""

    def __init__(self, inner: QueueSource, out_schema: Schema):
        self.inner = inner
        self.schema = out_schema

    async def execute(self):
        import jax.numpy as jnp
        from ..common.chunk import Column
        async for msg in self.inner.execute():
            if isinstance(msg, StreamChunk):
                cap = msg.capacity
                rid = Column(jnp.zeros(cap, jnp.int64),
                             jnp.ones(cap, jnp.bool_))
                yield msg.append_columns((rid,))
            else:
                yield msg
            if isinstance(msg, Barrier) and msg.is_stop():
                return


def _split_sql(sql: str) -> list[str]:
    """Split a script into statement texts (';' outside string literals and
    ``--`` line comments) so DDL statements can be logged verbatim for
    recovery replay."""
    parts, buf = [], []
    in_str = in_comment = False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if in_comment:
            buf.append(ch)
            if ch == "\n":
                in_comment = False
        elif in_str:
            buf.append(ch)
            if ch == "'":
                in_str = False
        elif ch == "'":
            in_str = True
            buf.append(ch)
        elif ch == "-" and sql[i:i + 2] == "--":
            in_comment = True
            buf.append(ch)
        elif ch == ";":
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        i += 1
    parts.append("".join(buf))
    return [p for p in parts if p.strip()]


class Session:
    def __init__(self, checkpoint_frequency: int = 10,
                 chunks_per_tick: int = 1, source_chunk_capacity: int = 1024,
                 config: Optional[BuildConfig] = None, seed: int = 42,
                 data_dir: Optional[str] = None,
                 in_flight_barriers: int = 1,
                 workers: int = 0,
                 state_store: Optional[str] = None,
                 compactors: int = 0,
                 rw_config=None,
                 fault_config=None,
                 autoscaler_config=None,
                 pipeline_depth: int = 1,
                 meta_addr: Optional[str] = None,
                 role: str = "writer"):
        # layered config (common/config.py): an RwConfig overrides the
        # keyword defaults; explicit kwargs are not merged (callers pick one
        # style). Reference: load_config + SystemParams (config.rs:128).
        # API lock FIRST: _recover() below runs locked entry points, and
        # observability HTTP threads may attach before __init__ returns
        self._api_lock = threading.RLock()
        # slow-epoch detector + span-tree snapshots (common/tracing.py)
        self.slow_epoch_threshold_ms: float = 0.0
        import collections as _collections
        self._slow_epochs: _collections.deque = _collections.deque(maxlen=16)
        self._slow_epoch_total = 0
        # federation cache: last stats snapshot per worker (metrics() and
        # await_tree() refresh it; it survives a dead worker for post-hoc
        # inspection)
        self._worker_stats: dict[int, dict] = {}
        self._worker_stats_at = 0.0            # monotonic; rate-limits polls
        self._worker_span_ack: dict[int, int] = {}   # last span_seq ingested
        from ..common.config import ObservabilityConfig
        self.observability = ObservabilityConfig()
        if rw_config is not None:
            st = rw_config.streaming
            checkpoint_frequency = st.checkpoint_frequency
            in_flight_barriers = st.in_flight_barrier_nums
            source_chunk_capacity = st.chunk_capacity
            pipeline_depth = st.pipeline_depth
            data_dir = rw_config.storage.data_dir or data_dir
            if state_store is None:
                state_store = rw_config.storage.state_store
            if not compactors:
                compactors = rw_config.storage.compactors
            # span ring + slow-epoch knobs: [observability] is the
            # canonical section; the original [streaming] fields remain a
            # legacy alias — a set (non-None) observability value wins
            obs = rw_config.observability
            self.observability = obs
            self.slow_epoch_threshold_ms = float(
                obs.slow_epoch_threshold_ms
                if obs.slow_epoch_threshold_ms is not None
                else st.slow_epoch_threshold_ms)
            ring = (obs.trace_ring_capacity
                    if obs.trace_ring_capacity is not None
                    else st.trace_ring_capacity)
            from ..common.tracing import GLOBAL_TRACE
            if ring != GLOBAL_TRACE.capacity:
                GLOBAL_TRACE.set_capacity(ring)
        # barrier observatory (common/barrier_ledger.py): the per-barrier
        # waterfall history ring, and the slow-epoch capture ring resized
        # to its [observability] knob (the maxlen=16 above predates it)
        cap = max(1, int(self.observability.slow_epoch_capture_capacity))
        if cap != self._slow_epochs.maxlen:
            self._slow_epochs = _collections.deque(self._slow_epochs,
                                                   maxlen=cap)
        from ..common.barrier_ledger import BarrierLedger
        self._barrier_ledger = BarrierLedger(
            self.observability.barrier_history_capacity)
        self._worker_stage_ack: dict[int, int] = {}  # last stage_seq seen
        # device profiling plane (common/profiling.py): per-dispatch
        # telemetry + HBM ledger; pure host bookkeeping, on by default
        from ..common.profiling import GLOBAL_PROFILER
        GLOBAL_PROFILER.enabled = self.observability.profiling
        GLOBAL_PROFILER.span_min_ms = self.observability.dispatch_span_min_ms
        if rw_config is not None:
            mesh = None
            if st.mesh_shape:
                # [streaming] mesh_shape: a 1-D device mesh for the
                # sharded paths, built over the first N local devices —
                # N = 1 included, so the knob agrees with `--mesh 1`
                # (a durable job created either way recovers under the
                # other). make_mesh refuses loudly (MeshUnavailableError)
                # when the process has fewer devices than configured.
                from ..parallel.sharded_agg import make_mesh
                mesh = make_mesh(st.mesh_shape)
            config = config or BuildConfig(
                chunk_capacity=st.chunk_capacity,
                agg_table_capacity=st.agg_table_capacity,
                join_key_capacity=st.join_key_capacity,
                join_bucket_width=st.join_bucket_width,
                topn_table_capacity=st.topn_table_capacity,
                fragment_parallelism=st.fragment_parallelism,
                coschedule=st.coschedule,
                tick_compiler=st.tick_compiler,
                mesh=mesh)
        # fault-tolerance knobs for every external boundary (object-store
        # retry, sink degrade, broker reconnect, worker deadlines) —
        # common/config.py FaultConfig; explicit fault_config wins over
        # the rw_config section
        from ..common.config import FaultConfig
        self.fault = (fault_config
                      or (rw_config.fault if rw_config is not None
                          else FaultConfig()))
        # out-of-process UDF plane (ISSUE 15, docs/robustness.md): the
        # client boundary is PROCESS-global, so a session only imposes
        # its [udf] section when one was explicitly given — a plain
        # Session() must not clobber a plane another session (or a
        # test/chaos harness) already configured. Servers auto-spawn
        # lazily at the first UDF call; chaos injection traces persist
        # under the first data_dir a session offers.
        from ..udf.client import udf_plane
        if rw_config is not None:
            udf_plane().configure(rw_config.udf, trace_dir=data_dir)
        elif data_dir is not None and udf_plane().trace_dir is None:
            udf_plane().configure(udf_plane().config, trace_dir=data_dir)
        self.udf_config = udf_plane().config
        # multi-tenant attachment (docs/control-plane.md): a "writer"
        # conducts barriers and owns DDL; a "serving" session is a
        # read-only frontend sharing one meta + one Hummock dir with the
        # writer, kept current by meta notifications; a "standby" is a
        # serving session that VOLUNTEERED for election — on a
        # leader_down push it races lease.acquire and the CAS winner
        # promotes in place to writer. In-process meta (meta_addr None)
        # stays the playground default — bit-identical.
        if role not in ("writer", "serving", "standby"):
            raise ValueError(f"unknown session role {role!r} "
                             "(expected 'writer', 'serving' or 'standby')")
        if meta_addr is None and rw_config is not None \
                and getattr(rw_config, "meta", None) is not None:
            meta_addr = rw_config.meta.addr or None
        if role in ("serving", "standby") and meta_addr is None:
            raise ValueError(f"a {role} session needs a meta_addr "
                             "to attach to")
        #: election eligibility survives role flips: a promoted standby
        #: that later demotes goes back to waiting for leader_down
        self._standby = role == "standby"
        self.role = "serving" if role == "standby" else role
        role = self.role
        # failover knobs ([meta] section): the TTL itself is enforced
        # server-side (`ctl meta serve --lease-ttl`); the client keeps
        # the heartbeat cadence and the election jitter cap
        _meta_cfg = (getattr(rw_config, "meta", None)
                     if rw_config is not None else None)
        self._lease_ttl_s = (float(_meta_cfg.lease_ttl_s)
                             if _meta_cfg is not None else 2.0)
        self._lease_heartbeat_s = (float(_meta_cfg.heartbeat_s)
                                   if _meta_cfg is not None else 0.5)
        self._election_backoff_s = (
            float(_meta_cfg.election_backoff_ms) / 1000.0
            if _meta_cfg is not None else 0.1)
        # leadership telemetry (metrics()["leadership"] → Prometheus
        # rw_leader_* / rw_failover_* families)
        self._leadership: dict = {
            "promotions": 0, "demotions": 0, "elections_lost": 0,
            "lease_lost": 0, "last_failover_ms": None}
        # post-promotion vacuum grace: the runs the promoted writer's
        # adopted version referenced, protected until readers re-report
        # pins (one notification round-trip) or the deadline passes
        self._pin_grace_refs: set[str] = set()
        self._pin_grace_deadline = 0.0
        self._pin_grace_epoch = 0
        self._election_lock = threading.Lock()
        self._election_busy = False
        self.meta_addr = meta_addr
        self.catalog = Catalog()
        self.data_dir = data_dir
        if data_dir is not None:
            import os as _osp
            hummock_dir = _osp.path.exists(
                _osp.path.join(data_dir, "hummock", "version.json"))
            if state_store is None:
                # recovery auto-detect: a dir written by the Hummock tier
                # is self-describing (its version manifest exists), so a
                # plain Session(data_dir=...) reopens the right backend
                state_store = "hummock" if hummock_dir else "segment"
            elif state_store == "segment" and hummock_dir:
                raise ValueError(
                    f"{data_dir!r} was written by the hummock state "
                    "store; opening it as 'segment' would recover an "
                    "empty store (drop the explicit state_store to "
                    "auto-detect)")
            elif state_store == "hummock" and not hummock_dir \
                    and _osp.path.exists(
                        _osp.path.join(data_dir, "manifest.json")):
                raise ValueError(
                    f"{data_dir!r} was written by the segment state "
                    "store; opening it as 'hummock' would recover an "
                    "empty store (drop the explicit state_store to "
                    "auto-detect)")
            # durable-tier object store: local FS → optional seeded fault
            # injection (tests/sim chaos) → retry layer, per the fault
            # config (storage/object_store.py open_object_store)
            from ..storage.object_store import open_object_store
            _obj = open_object_store(
                data_dir, self.fault.io_retry_policy(),
                fault_transient_rate=(
                    self.fault.inject_object_store_transient_rate),
                fault_seed=self.fault.inject_object_store_seed,
                fault_torn_write_rate=(
                    self.fault.inject_object_store_torn_write_rate))
            if state_store == "hummock":
                from ..storage.hummock import HummockStateStore
                # a dedicated compactor role takes over compaction; with
                # none configured the store folds in-process (background
                # thread), mirroring the segment log
                # serving sessions never compact or vacuum: the writer
                # owns storage maintenance (a reader rewriting runs
                # would race the writer's version publishes)
                self.store: MemoryStateStore = HummockStateStore(
                    data_dir, object_store=_obj,
                    inline_compaction=(compactors == 0
                                       and role == "writer"))
            elif state_store == "segment":
                from ..storage.checkpoint import DurableStateStore
                self.store = DurableStateStore(data_dir, object_store=_obj)
            else:
                raise ValueError(
                    f"unknown state_store {state_store!r} "
                    "(expected 'segment' or 'hummock')")
        else:
            self.store = MemoryStateStore()
        self.state_store_kind = (state_store if data_dir is not None
                                 else "memory")
        # meta tier as the control plane (VERDICT r3 item 3): catalog
        # mutations write through to the MetaStore + notifications; barrier
        # conduction publishes; the heartbeat detector drives scoped job
        # recovery (reference: meta managers, src/meta/src/manager/)
        import os as _os
        from ..meta.service import MetaBackedCatalog, MetaService
        if meta_addr is not None:
            # remote control plane: the MetaClient mirrors the
            # MetaService surface, so every call site below (and the
            # catalog write-through) works unchanged over the wire
            from ..meta.client import MetaClient
            self.meta = MetaClient(meta_addr)
        else:
            self.meta = MetaService(
                data_dir=_os.path.join(data_dir, "meta")
                if data_dir is not None else None)
        self.catalog_writer = MetaBackedCatalog(self.catalog, self.meta)
        # set once this writer's lease is superseded (a newer writer
        # acquired the leader key): barrier injection and checkpoint
        # commits are refused from then on
        self._fenced = False
        # remote reader pins (meta "hummock_pins" channel): the writer's
        # vacuum treats serving sessions' pinned runs like local pins
        self._remote_pin_runs: set[str] = set()
        # session-generation fencing token (ISSUE 9): monotone across
        # session restarts (persisted in the meta store) and bumped on
        # every scoped recovery. Stamped on every session→worker frame;
        # a stale pre-recovery worker can neither ack barriers (the
        # session drops acks from older generations) nor commit
        # checkpoints (the worker refuses commit frames older than a
        # job's deployment generation).
        if role == "writer":
            self._generation = int(
                self.meta.store.get("session_generation") or "0") + 1
            self.meta.store.put("session_generation",
                                str(self._generation))
            if meta_addr is not None:
                # the same token doubles as the writer's leader-lease
                # TERM (strictly newer terms win the CAS; TTL expiry
                # triggers standby election — docs/control-plane.md)
                self.meta.acquire_leader(self._generation)
                self.meta.start_heartbeat(self._lease_heartbeat_s,
                                          on_lost=self._on_lease_lost)
        else:
            # read-only attachment: adopt (never advance) the token
            self._generation = int(
                self.meta.store.get("session_generation") or "0")
        self._jobs_to_recover: list[str] = []
        self._dead_jobs: set[str] = set()
        self.meta.on_job_failure(self._jobs_to_recover.append)
        # elastic scaling plane (meta/rescale.py + meta/autoscaler.py):
        # the autoscaler observes per-edge exchange pressure each tick
        # and issues LIVE rescale plans; stats feed metrics()/Prometheus
        from ..common.config import AutoscalerConfig
        from ..meta.autoscaler import Autoscaler
        self.autoscaler_config = (
            autoscaler_config
            or (rw_config.autoscaler if rw_config is not None
                else AutoscalerConfig()))
        self.autoscaler = Autoscaler(self.autoscaler_config)
        self._rescale_stats: dict = {"migrations": 0, "moved_vnodes": 0,
                                     "last": None, "history": []}
        self._autoscaler_pw: dict[str, int] = {}
        self._autoscaler_slow_seen = 0
        self._in_rescale = False
        self.config = config or BuildConfig()
        self.checkpoint_frequency = checkpoint_frequency
        # barrier cadence for interval-driven drivers (CLI ticker); mutable
        # via SET barrier_interval_ms
        self.barrier_interval_ms = (
            rw_config.streaming.barrier_interval_ms
            if rw_config is not None else 1000)
        # output schema of the most recent batch SELECT (pgwire reads it
        # instead of re-planning the statement)
        self.last_select_schema: list = []
        self.chunks_per_tick = chunks_per_tick
        self.source_chunk_capacity = source_chunk_capacity
        self.seed = seed
        self.epoch = max(1, self.store.committed_epoch)  # last completed epoch
        # the failure detector's clock is the epoch counter: align it with
        # the session's starting epoch or a recovered session (epoch >> 0)
        # would instantly expire every worker registered at clock 0
        # (writers only: a reader attaching on a stale store snapshot
        # must not drag the shared clock backwards)
        if role == "writer":
            self.meta.advance_epoch_clock(self.epoch)
        self.jobs: dict[str, StreamJob] = {}          # mv/table name -> job
        # epoch co-scheduler: eligible MVs' epochs batched into one
        # dispatch per tick (stream/coschedule.py; [streaming]
        # coschedule = true). Engines map job -> (flush HashAggExecutor,
        # output queue, device source cursor).
        from ..stream.coschedule import CoScheduler
        self._cosched = CoScheduler()
        self._cosched_engines: dict[str, tuple] = {}
        self._cosched_markers: set[str] = set()
        # asynchronous epoch pipeline ([streaming] pipeline_depth,
        # docs/performance.md "Pipelined tick"): depth >= 2 defers each
        # fused group's packed flush fetch to the NEXT tick, so epoch
        # N+1's dispatch launches while epoch N's stats stream back and
        # the host decodes/materializes — drained at checkpoint
        # barriers, FLUSH, DDL and recovery, so committed state is
        # bit-exact vs the synchronous path
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._pipeline_stats = {"deferred_flushes": 0, "drains": 0}
        # mesh-sharded fused MVs (ops/fused_sharded.py): with a mesh AND
        # the coschedule opt-in, eligible MVs join a signature-keyed
        # K-jobs × S-shards group (parallel/fused.ShardedCoGroup) — a
        # whole group ticks as ONE dispatch per epoch across all chips.
        # Engines map job -> (flush/persistence HashAggExecutor, output
        # queue, device source cursor, its ShardedCoGroup).
        self._shardfused = None        # lazy ShardedCoScheduler
        self._shardfused_engines: dict[str, tuple] = {}
        self._shardfused_markers: set[str] = set()
        # the heterogeneous tick compiler (stream/tick_compiler.py;
        # [streaming] tick_compiler = true): eligible MVs — even
        # DISSIMILAR ones — join a compiled dispatch schedule
        # (shape-class padded supergroups + jitted mega-epochs),
        # recompiled lazily on DDL. Engines map job -> (flush
        # HashAggExecutor, output queue, device source cursor).
        from ..stream.tick_compiler import TickCompiler
        self._hetero = TickCompiler()
        self._hetero_engines: dict[str, tuple] = {}
        self._hetero_markers: set[str] = set()
        # epochs run by fused engines this session has since dropped,
        # per dispatch qualname — the profiler's counts are cumulative,
        # so the live per_epoch invariant ratio must keep dividing by
        # these epochs after a DROP + re-CREATE
        self._dispatch_epochs_retired: dict[str, int] = {}
        self.feeds: list[_SourceFeed] = []
        self.backfills: list[_BackfillRef] = []
        # DML rendezvous (reference: DmlManager, src/source/src/
        # dml_manager.rs:44): INSERTs stage here and land in the next epoch
        from ..stream.dml import DmlManager
        self.dml = DmlManager()
        self._table_queues: dict[str, list[QueueSource]] = {}
        self._next_shard = 0
        self._recovering = False
        # barrier pipelining: up to k epochs in flight before tick() blocks
        # on the oldest (reference: in_flight_barrier_nums,
        # src/common/src/config.rs:380-381; GlobalBarrierManager pipelining,
        # src/meta/src/barrier/mod.rs:152)
        self.in_flight_barriers = max(1, in_flight_barriers)
        self._inflight: list[tuple[int, bool]] = []  # (epoch, checkpoint)
        self._injected = self.epoch                  # last injected epoch
        self.paused = False
        self._pending_mutation: Optional[Mutation] = None
        from ..stream.metrics import LatencyRecorder
        self.barrier_latency = LatencyRecorder()
        self._inject_time: dict[int, tuple] = {}   # epoch -> (perf, wall)
        # the session owns its event loop: jobs are long-lived tasks that
        # must survive across synchronous API calls, independent of any
        # ambient loop other code may create/close
        self.loop = asyncio.new_event_loop()
        # pre-warm the native row codec off the hot path: its first use
        # otherwise pays a synchronous g++ compile inside a barrier
        from ..native import codec as _native_codec
        threading.Thread(target=_native_codec, daemon=True).start()
        # remote worker processes (reference: compute nodes; the session
        # doubles as meta + frontend — playground --workers N). MV jobs are
        # placed round-robin on workers; tables/sinks/batch stay local.
        self.workers: list = []
        self._remote_specs: dict[str, dict] = {}
        # spanning jobs: one MV's fragment graph across SEVERAL worker
        # processes (meta/fragment.py scheduler + stream/remote_exchange)
        self._spanning_specs: dict[str, dict] = {}
        import itertools as _it
        # worker↔worker exchange channel ids, disjoint from the per-worker
        # session-channel space (worker_id * 100_000 + n)
        self._next_span_chan = _it.count(10_000_000)
        self._next_remote = 0
        if workers:
            import tempfile
            from .remote import RemoteWorker
            base = data_dir or tempfile.mkdtemp(prefix="rwtpu_cluster_")
            self._workers_base = base
            for k in range(workers):
                w = RemoteWorker(_os.path.join(base, f"worker_{k}"), k,
                                 self.loop,
                                 permits=self.config.exchange_permits)
                # control-frame deadlines: a wedged worker trips these
                # (and the heartbeat-TTL recovery) instead of hanging the
                # session forever
                w.request_timeout = self.fault.worker_request_timeout_s
                w.epoch_timeout = self.fault.worker_epoch_timeout_s
                w.generation = self._generation
                w.spawn()
                self._await(w.connect())
                self.workers.append(w)
                # fragment-placement target registry (reference: compute
                # nodes registering with the meta ClusterManager)
                self.meta.register_compute(w.worker_id, "127.0.0.1",
                                           w.port)
        # dedicated compactor workers (reference: standalone compactor
        # nodes, src/storage/compactor/src/server.rs:57): stateless
        # processes over the SAME object-store root; the session plays
        # the meta role, handing out version-manager tasks off the
        # barrier path (_kick_compaction)
        # serving plane (frontend/serving.py): version-pinned plan cache
        # + two-phase distributed batch aggregation + the lock-free
        # concurrent read path. The data-version seqlock: EVEN = stores
        # quiescent, ODD = a mutation (tick / commit / recovery) is in
        # flight; every mutator brackets itself with _enter_mutation /
        # _exit_mutation and optimistic readers accept a result only
        # when the same even version spans their whole scan.
        self._data_version = 0
        self._mutation_depth = 0
        from ..common.config import BatchConfig
        self.batch_config = (rw_config.batch if rw_config is not None
                             else BatchConfig())
        from .serving import ServingPlane
        self._serving = ServingPlane(self.batch_config)
        self.compactors: list = []
        self._compaction_pump: Optional[threading.Thread] = None
        if compactors and data_dir is not None \
                and self.state_store_kind == "hummock":
            from ..worker.compactor import CompactorClient
            for k in range(compactors):
                c = CompactorClient(data_dir, k)
                c.spawn()
                self.compactors.append(c)
        if role == "serving":
            # no jobs, no DDL replay, no barrier conduction: rebuild the
            # catalog read cache from the meta store and follow the
            # writer through notifications
            self._attach_serving()
        elif data_dir is not None:
            self._recover()
        if meta_addr is not None:
            self._attach_meta_observers()

    def _recover(self) -> None:
        """Crash recovery: replay the logged DDL over the recovered store.
        Executors find non-empty state tables and reload device state from
        them; MV-on-MV leaves skip the backfill snapshot (their recovered
        state already reflects the upstream through the committed epoch).
        Source connector offsets are persisted per checkpoint epoch in each
        feed's split-state table; replayed CREATEs seek their readers there
        (_stream_leaf). Reference: orchestrated recovery,
        src/meta/src/barrier/recovery.rs:110."""
        ddl = self.store.log.ddl()  # type: ignore[attr-defined]
        if not ddl:
            return
        # pre-scan for persisted rescale configs: the LAST one per job wins,
        # but a later DROP of the job voids it (a re-CREATE after the drop
        # is a NEW job that ran under the session default); its CREATE below
        # replays under that config so restarts keep their layout
        # (round-4 weak #5)
        resched_cfg: dict[str, object] = {}
        for piece in ddl:
            line = piece.strip()
            if line.startswith("-- coschedule"):
                # the job was built as a co-scheduled fused group member
                # (stream/coschedule.py); its durable layout only decodes
                # on that path — _create_mv refuses a mismatched replay
                self._cosched_markers.add(
                    line[len("-- coschedule"):].strip())
                continue
            if line.startswith("-- shardfused"):
                # mesh-sharded fused MV (ops/fused_sharded.py): replay
                # routes back down that path (re-sharding onto THIS
                # session's mesh by replaying the vnode mapping) or
                # refuses loudly — marker-directed in both directions,
                # like the coschedule marker above
                self._shardfused_markers.add(
                    line[len("-- shardfused"):].strip())
                continue
            if line.startswith("-- hetero"):
                # tick-compiled MV (stream/tick_compiler.py): replay
                # routes back into the compiled schedule or refuses
                # loudly — marker-directed in both directions, same as
                # the coschedule marker above
                self._hetero_markers.add(line[len("-- hetero"):].strip())
                continue
            if not line.startswith("-- reschedule"):
                if (resched_cfg or self._cosched_markers
                        or self._shardfused_markers
                        or self._hetero_markers) \
                        and "drop" in line.lower():
                    try:
                        for stmt in parse_sql(piece):
                            if isinstance(stmt, A.DropStatement):
                                resched_cfg.pop(stmt.name, None)
                                self._cosched_markers.discard(stmt.name)
                                self._shardfused_markers.discard(stmt.name)
                                self._hetero_markers.discard(stmt.name)
                    except Exception:  # noqa: BLE001 - replay parses below
                        pass
                continue
            rest = line[len("-- reschedule"):].strip()
            mv_name, _, cfg_json = rest.partition(" ")
            if not cfg_json:
                import warnings
                warnings.warn(
                    f"reschedule {mv_name}: legacy log entry without a "
                    "persisted config; the job recovered with the "
                    "session's default BuildConfig")
                continue
            try:
                import os as _os
                from .build import config_from_json
                # RWTPU_ALLOW_MESH_RESHARD=1 is the operator's EXPLICIT
                # consent to shrink a saved mesh to the available devices
                # (state re-shards by vnode replay on load)
                allow = _os.environ.get(
                    "RWTPU_ALLOW_MESH_RESHARD") == "1"
                resched_cfg[mv_name] = config_from_json(
                    cfg_json, allow_reshard=allow)
            except MeshUnavailableError as e:
                # the saved mesh topology needs more devices than this
                # process has. The old behavior degraded SILENTLY to the
                # session default (an 8-shard job quietly reopening
                # unsharded); refuse loudly instead — the operator either
                # restores the device count or re-shards explicitly
                raise RuntimeError(
                    f"reschedule {mv_name}: {e}. Restart with at least "
                    "that many devices (on CPU: XLA_FLAGS="
                    "--xla_force_host_platform_device_count=N), or "
                    "re-shard explicitly onto the available devices by "
                    "reopening with RWTPU_ALLOW_MESH_RESHARD=1"
                ) from e
            except Exception as e:  # noqa: BLE001 - corrupt/unportable cfg
                # a corrupt/truncated log line (JSONDecodeError/KeyError):
                # every job still recovers under the default config
                import warnings
                warnings.warn(
                    f"reschedule {mv_name}: persisted layout not "
                    f"restorable on this process ({e}); recovering with "
                    "the session's default BuildConfig")
        self._recovering = True
        try:
            for piece in ddl:
                if piece.strip().startswith(("-- reschedule",
                                             "-- coschedule",
                                             "-- hetero")):
                    continue
                for stmt in parse_sql(piece):
                    name = getattr(stmt, "name", None)
                    if (isinstance(stmt, A.CreateMaterializedView)
                            and name in resched_cfg):
                        saved = self.config
                        self.config = resched_cfg[name]  # type: ignore[assignment]
                        try:
                            self._run_statement(stmt)
                        finally:
                            self.config = saved
                    else:
                        self._run_statement(stmt)
        finally:
            self._recovering = False

    # -- multi-tenant attachment (docs/control-plane.md) -----------------------

    def _attach_serving(self) -> None:
        """Read-only attachment: the catalog read cache comes from the
        meta store's ``catalog/`` keyspace, the data comes from the
        shared Hummock dir, and both are kept current by notifications
        (no jobs, no ticks, no generation bump — the writer owns those)."""
        self._load_catalog_from_meta()
        self._report_reader_pins()

    def _load_catalog_from_meta(self) -> None:
        """Rebuild the catalog from the persisted summaries the writer's
        ``MetaBackedCatalog`` write-through maintains. Bracketed by the
        seqlock: an optimistic reader racing the swap retries."""
        import json as _json
        from ..common.types import DataType, Field, Schema, TypeKind
        from .catalog import (IndexDef, MaterializedViewDef, SinkDef,
                              SourceDef, TableDef, type_from_name)

        def _typ(name: str) -> DataType:
            try:
                return type_from_name(name)
            except ValueError:
                return DataType(TypeKind(name))

        rows = self.meta.store.list_prefix("catalog/")
        self._enter_mutation()
        try:
            cat = self.catalog
            cat.sources.clear(); cat.tables.clear(); cat.mvs.clear()
            cat.sinks.clear(); cat.indexes.clear()
            max_id = 0
            for _key, raw in rows:
                d = _json.loads(raw)
                kind, name = d["kind"], d["name"]
                tid = int(d.get("table_id", -1))
                max_id = max(max_id, tid)
                pk = tuple(d.get("pk", ()))
                if kind == "index":
                    cat.indexes[name] = IndexDef(
                        name, d.get("table", ""),
                        tuple(d.get("columns", ())),
                        d.get("mv_name", ""))
                    continue
                schema = Schema([Field(n, _typ(t))
                                 for n, t in d.get("columns", [])])
                if kind == "source":
                    cat.sources[name] = SourceDef(
                        name, schema, d.get("connector", ""), {})
                elif kind == "table":
                    cat.tables[name] = TableDef(name, schema, pk, tid)
                elif kind == "materialized_view":
                    cat.mvs[name] = MaterializedViewDef(
                        name, schema, pk, tid, d.get("definition", ""))
                elif kind == "sink":
                    cat.sinks[name] = SinkDef(
                        name, schema, d.get("connector", ""), {},
                        d.get("from_name", ""), tid)
            cat._next_table_id = max(cat._next_table_id, max_id + 1)
        finally:
            self._serving.invalidate_catalog()
            self._exit_mutation()

    def _attach_meta_observers(self) -> None:
        """Subscribe to the remote meta's push channels. Observers run
        on the MetaClient's subscription thread; every mutation they
        perform is seqlock-bracketed so concurrent lock-free reads
        retry instead of tearing."""
        notif = self.meta.notifications
        notif.subscribe("system_params", self._on_system_params_push)
        notif.subscribe("leader", self._on_leader_push)
        # every remote session hears about a dead leader; only standbys
        # (_on_leader_down checks) actually race the election
        notif.subscribe("leader_down", self._on_leader_down)
        if self.role == "serving":
            notif.subscribe("catalog", self._on_catalog_push)
            notif.subscribe("checkpoint", self._on_checkpoint_push)
        else:
            notif.subscribe("hummock_pins", self._on_pins_push)
            manager = getattr(self.store, "manager", None)
            if manager is not None:
                manager.external_refs = self._external_pin_refs
        self.meta.on_resync(self._on_meta_resync)

    def _on_catalog_push(self, _version: int, _info) -> None:
        try:
            self._load_catalog_from_meta()
        except Exception:
            pass        # next notification (or resync) retries

    def _on_checkpoint_push(self, _version: int, _info) -> None:
        refresh = getattr(self.store, "refresh", None)
        if refresh is None:
            return
        try:
            self._enter_mutation()
            try:
                refresh()
            finally:
                self._exit_mutation()
            self._report_reader_pins()
        except Exception:
            pass        # transient object-store race; next checkpoint retries

    def _on_system_params_push(self, _version: int, info) -> None:
        try:
            self._apply_system_param(info["name"], info["value"])
        except Exception:
            pass

    def _on_leader_push(self, _version: int, info) -> None:
        # only a STRICTLY newer generation fences: the subscription
        # replays the log from the start, so our own (and older
        # writers') acquisition events come past every observer
        generation = info.get("generation")
        if self.role == "writer" and generation is not None \
                and generation > self._generation:
            self._fenced = True

    def _on_pins_push(self, _version: int, info) -> None:
        self._remote_pin_runs = set(info.get("ssts", ()))
        # post-promotion grace ends after ONE notification round-trip:
        # our first checkpoint notify made readers refresh and re-report,
        # and this push is the server's updated union — from here the
        # live pin registry protects everything a reader still holds
        if self._pin_grace_refs \
                and self.store.committed_epoch > self._pin_grace_epoch:
            self._pin_grace_refs = set()

    def _on_meta_resync(self) -> None:
        """The meta process restarted (its notification log reset): the
        durable state survived in its store, so re-read everything we
        track through notifications. Writers re-check the lease but
        never re-acquire — an auto-re-acquire could steal the lease back
        from a legitimately newer writer."""
        try:
            if self.role == "writer":
                from ..meta.client import MetaFenced
                try:
                    self.meta.assert_leader()
                except MetaFenced:
                    self._fenced = True
            else:
                self._load_catalog_from_meta()
                self._on_checkpoint_push(0, None)
        except Exception:
            pass

    def _report_reader_pins(self) -> None:
        """Tell meta which SST runs this reader's current version holds
        so the writer's vacuum spares them (the remote analogue of the
        manager's local pin lease)."""
        runs = getattr(self.store, "version_runs", None)
        report = getattr(self.meta, "report_pins", None)
        if runs is None or report is None:
            return
        try:
            report(runs())
        except Exception:
            pass

    def _check_fenced(self) -> None:
        if self._fenced:
            from ..meta.client import MetaFenced
            raise MetaFenced(
                "this session's writer lease was superseded; barrier "
                "conduction and checkpoint commits are refused")

    # -- leader failover (docs/control-plane.md "Election") --------------------

    def _external_pin_refs(self) -> set:
        """What the vacuum must spare beyond local pins: the live remote
        pin registry, plus — inside the post-promotion grace window —
        every run the version adopted at promotion referenced (a reader
        that reconnected mid-failover may hold pins the registry forgot
        until it re-reports)."""
        refs = set(self._remote_pin_runs)
        if self._pin_grace_refs:
            import time as _t
            if _t.monotonic() < self._pin_grace_deadline:
                refs |= self._pin_grace_refs
            else:
                self._pin_grace_refs = set()
        return refs

    def _on_lease_lost(self, _exc) -> None:
        """Heartbeat thread: a renewal came back LeaseLost — another
        session holds a newer term. Flag only; the next conduction
        attempt raises MetaFenced and the tick path demotes us."""
        self._fenced = True
        self._leadership["lease_lost"] += 1

    def _on_leader_down(self, _version: int, info) -> None:
        """Subscription thread: the server's TTL detector declared the
        leader dead. Standbys race ``lease.acquire`` at down-term + 1 on
        a dedicated thread (promotion takes the session lock and does
        real work — it must never block notification delivery)."""
        if not self._standby or self.role == "writer":
            return
        with self._election_lock:
            if self._election_busy:
                return
            self._election_busy = True
        down_term = int(info.get("term", info.get("generation", 0)) or 0)
        threading.Thread(target=self._run_election, args=(down_term,),
                         name="leader-election", daemon=True).start()

    def _run_election(self, down_term: int) -> None:
        """One election round. Every candidate computes the SAME target
        term — down-term + 1, taken from the ``leader_down`` payload the
        server pushed once per expiry — so the server CAS admits exactly
        one; losers take the typed LeaseLost and stay serving. The term
        must NOT be re-derived from the store here: a late candidate
        reading ``session_generation`` after the winner bumped it would
        compute term + 2, be admitted as "strictly newer", and take the
        leadership right back — a split brain by term escalation. The
        winner starts heartbeating BEFORE the (possibly long) promotion
        so the lease cannot expire under it."""
        from ..meta.client import LeaseLost, MetaUnavailable
        import hashlib as _hl
        import time as _t
        try:
            if self._election_backoff_s > 0:
                # deterministic per-session jitter spreads the CAS storm
                h = int(_hl.sha256(
                    self.meta.session_id.encode()).hexdigest(), 16)
                _t.sleep((h % 1000) / 1000.0 * self._election_backoff_s)
            t0 = _t.monotonic()
            term = int(down_term) + 1
            try:
                self.meta.acquire_leader(term, reason="election")
            except (LeaseLost, MetaUnavailable):
                self._leadership["elections_lost"] += 1
                return
            self.meta.start_heartbeat(self._lease_heartbeat_s,
                                      on_lost=self._on_lease_lost)
            try:
                self.promote(term)
            except Exception:
                # a wedged half-promotion must not hold the lease: stop
                # renewing so the TTL frees it for the next candidate
                self.meta.stop_heartbeat()
                raise
            self._leadership["last_failover_ms"] = round(
                (_t.monotonic() - t0) * 1e3, 3)
        except Exception:  # noqa: BLE001 - election must not kill the relay
            pass
        finally:
            with self._election_lock:
                self._election_busy = False

    @_locked
    def promote(self, term: int) -> None:
        """In-place standby → writer takeover under ``term``: adopt the
        committed Hummock cut read-write, rebuild every streaming job by
        replaying the DDL log (the same ``_recover`` path a restarted
        writer takes — jobs land on their last committed checkpoint and
        source readers seek persisted offsets, so the takeover is
        exactly-once), then resume barrier conduction. The caller must
        already hold the lease at ``term``."""
        if self.role == "writer":
            return
        self._enter_mutation()
        try:
            self._fenced = False
            self._generation = int(term)
            self.meta.store.put("session_generation",
                                str(self._generation))
            for w in self.workers:
                w.generation = self._generation
            # adopt the committed cut (the version manifest carries the
            # DDL log, so refresh() brings that too)
            refresh = getattr(self.store, "refresh", None)
            if refresh is not None:
                refresh()
            # vacuum grace: spare every run the adopted version
            # references until readers re-report under this writer
            import time as _t
            runs = getattr(self.store, "version_runs", None)
            self._pin_grace_refs = (set(runs()) if runs is not None
                                    else set())
            self._pin_grace_deadline = (_t.monotonic()
                                        + max(self._lease_ttl_s, 1.0))
            self._pin_grace_epoch = self.store.committed_epoch
            try:
                self._remote_pin_runs = set(self.meta.pins_union())
            except Exception:
                pass
            # observer rewiring: a writer must not chase its own
            # commits through catalog/checkpoint pushes
            notif = self.meta.notifications
            notif.unsubscribe("catalog", self._on_catalog_push)
            notif.unsubscribe("checkpoint", self._on_checkpoint_push)
            notif.subscribe("hummock_pins", self._on_pins_push,
                            from_version=notif.current_version)
            manager = getattr(self.store, "manager", None)
            if manager is not None:
                manager.external_refs = self._external_pin_refs
            # rebuild jobs from the DDL log exactly like a restarted
            # writer: from an EMPTY catalog (replayed CREATEs write
            # through to meta idempotently)
            cat = self.catalog
            cat.sources.clear(); cat.tables.clear(); cat.mvs.clear()
            cat.sinks.clear(); cat.indexes.clear()
            cat._next_table_id = 1
            self.role = "writer"
            self.epoch = max(1, self.store.committed_epoch)
            self._injected = self.epoch
            self._inflight.clear()
            self._inject_time.clear()
            self._pending_mutation = None
            if self.data_dir is not None:
                self._recover()
            # the writer owns storage maintenance now (serving sessions
            # opened with compaction routed away)
            if getattr(self.store, "inline_compaction", None) is False \
                    and not self.compactors:
                self.store.inline_compaction = True
            self.meta.advance_epoch_clock(self.epoch)
            self._leadership["promotions"] += 1
        finally:
            self._serving.invalidate_catalog()
            self._exit_mutation()

    def _demote_to_serving(self) -> None:
        """A fenced ex-writer (partitioned, not dead — a successor holds
        a newer term) converts itself into a WORKING serving session
        instead of crashing: stop conducting, discard uncommitted
        in-flight epochs (the successor's recovery replays them from
        committed offsets exactly once), drop the jobs, and follow the
        new writer through notifications like any other reader."""
        self.meta.stop_heartbeat()
        self._inflight.clear()
        self._inject_time.clear()
        self._pending_mutation = None
        for job in list(self.jobs.values()):
            sink = getattr(job.pipeline, "sink", None)
            if sink is not None:
                try:
                    sink.close()
                except Exception:  # noqa: BLE001 - already dying
                    pass
        jobs = list(self.jobs.values())
        if jobs:
            async def _stop_all():
                await asyncio.gather(*(j.stop() for j in jobs),
                                     return_exceptions=True)
                for _ in range(3):
                    await asyncio.sleep(0)
            try:
                self._await(_stop_all())
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        self.jobs.clear()
        self.feeds.clear()
        self.backfills.clear()
        self._table_queues.clear()
        from ..stream.coschedule import CoScheduler
        self._cosched = CoScheduler()
        self._cosched_engines.clear()
        self._cosched_markers.clear()
        self._shardfused = None
        self._shardfused_engines.clear()
        self._shardfused_markers.clear()
        from ..stream.tick_compiler import TickCompiler
        self._hetero = TickCompiler()
        self._hetero_engines.clear()
        self._hetero_markers.clear()
        self._dead_jobs.clear()
        self._jobs_to_recover.clear()
        # discard staged-but-uncommitted state: fully discarded is the
        # demotion half of "committed exactly once or fully discarded"
        pending = getattr(self.store, "_pending", None)
        if pending is not None:
            pending.clear()
        if getattr(self.store, "inline_compaction", None) is True:
            self.store.inline_compaction = False
        self.role = "serving"
        self._fenced = False   # the serving read path is healthy
        self._leadership["demotions"] += 1
        notif = self.meta.notifications
        notif.subscribe("catalog", self._on_catalog_push,
                        from_version=notif.current_version)
        notif.subscribe("checkpoint", self._on_checkpoint_push,
                        from_version=notif.current_version)
        try:
            self._load_catalog_from_meta()
        except Exception:  # noqa: BLE001 - next push retries
            pass
        self._on_checkpoint_push(0, None)

    def _maybe_demote(self, exc: BaseException) -> None:
        """Conduction raised: if it was the fencing signal on a remote
        control plane, demote in place (swallowing demotion errors — the
        caller re-raises the original MetaFenced either way)."""
        if (type(exc).__name__ == "MetaFenced" and self._fenced
                and self.role == "writer" and self.meta_addr is not None):
            try:
                self._demote_to_serving()
            except Exception:  # noqa: BLE001 - keep the fencing signal
                pass

    # ------------------------------------------------------------------ SQL --

    @_locked
    def run_sql(self, sql: str) -> list:
        """Execute statements; returns the last statement's result rows."""
        out: list = []
        for piece in _split_sql(sql):
            for stmt in parse_sql(piece):
                out = self._run_statement(stmt)
                if (self.data_dir is not None and not self._recovering
                        and isinstance(stmt, (
                            A.CreateSource, A.CreateTable,
                            A.CreateMaterializedView, A.CreateSink,
                            A.CreateIndex, A.DropStatement))):
                    self.store.log.log_ddl(piece)  # type: ignore[attr-defined]
        return out

    def _run_statement(self, stmt: A.Statement) -> list:
        if self.role == "serving" and isinstance(stmt, (
                A.CreateSource, A.CreateTable, A.CreateMaterializedView,
                A.CreateSink, A.CreateIndex, A.DropStatement, A.Insert,
                A.Delete, A.Update, A.FlushStatement)):
            raise SqlError(
                "serving sessions are read-only: run DDL/DML on the "
                "writer session (docs/control-plane.md)")
        if isinstance(stmt, (A.CreateSource, A.CreateTable,
                             A.CreateMaterializedView, A.CreateSink,
                             A.CreateIndex)):
            # transactional table-id allocation: a failed CREATE must not
            # shift later statements' ids (recovery replays only logged —
            # successful — DDL, so id assignment must be replay-deterministic)
            saved_id = self.catalog._next_table_id
            # DDL is a data mutation for the seqlock too: a CREATE/DROP
            # rearranges store tables mid-statement, and a lock-free
            # optimistic reader racing it must see the version move and
            # retry instead of accepting a torn scan
            self._enter_mutation()
            try:
                if isinstance(stmt, A.CreateSource):
                    return self._create_source(stmt)
                if isinstance(stmt, A.CreateTable):
                    return self._create_table(stmt)
                if isinstance(stmt, A.CreateSink):
                    return self._create_sink(stmt)
                if isinstance(stmt, A.CreateIndex):
                    return self._create_index(stmt)
                return self._create_mv(stmt)
            except BaseException:
                self.catalog._next_table_id = saved_id
                raise
            finally:
                # cached serving plans may reference the (attempted)
                # relations — clear on every catalog transition, BEFORE
                # the version goes even again so no reader can re-cache
                # against the old catalog
                self._serving.invalidate_catalog()
                self._exit_mutation()
        if isinstance(stmt, A.DropStatement):
            self._enter_mutation()
            try:
                return self._drop(stmt)
            finally:
                self._serving.invalidate_catalog()
                self._exit_mutation()
        if isinstance(stmt, A.Insert):
            return self._insert(stmt)
        if isinstance(stmt, A.Delete):
            return self._delete_dml(stmt)
        if isinstance(stmt, A.Update):
            return self._update_dml(stmt)
        if isinstance(stmt, A.Query):
            return self.query(stmt.select)
        if isinstance(stmt, A.ShowStatement):
            if stmt.what == "parameters":
                return self.parameters()
            reg = {"tables": self.catalog.tables,
                   "sources": self.catalog.sources,
                   "sinks": self.catalog.sinks,
                   "indexes": self.catalog.indexes,
                   "materialized_views": self.catalog.mvs}.get(stmt.what)
            if reg is None:
                raise SqlError(f"cannot SHOW {stmt.what}")
            return [(name,) for name in sorted(reg)
                    if not name.startswith("__idx_")]
        if isinstance(stmt, A.Explain):
            return self._explain(stmt)
        if isinstance(stmt, A.FlushStatement):
            self.flush()
            return []
        if isinstance(stmt, A.SetStatement):
            return self._set_param(stmt)
        raise SqlError(f"unsupported statement {type(stmt).__name__}")

    def _set_param(self, stmt: A.SetStatement) -> list:
        """Runtime-mutable system params (reference:
        src/common/src/system_param/mod.rs — hot-propagated). ``SET``
        applies to this session; ``ALTER SYSTEM SET`` additionally
        publishes a ``system_params`` notification through meta so every
        attached session (writer and readers alike) applies it live."""
        from ..common.config import MUTABLE_SYSTEM_PARAMS
        name = stmt.name.lower()
        coerce = MUTABLE_SYSTEM_PARAMS.get(name)
        if coerce is None:
            raise SqlError(f"unknown or immutable parameter {stmt.name!r}")
        value = coerce(stmt.value)
        self._apply_system_param(name, value)
        if getattr(stmt, "system", False):
            self.meta.notifications.notify(
                "system_params", {"name": name, "value": value})
        return []

    def _apply_system_param(self, name: str, value) -> None:
        """Assign one mutable param (idempotent: a session's own ALTER
        SYSTEM comes back to it on the notification channel too)."""
        if name == "checkpoint_frequency":
            if value < 1:
                raise SqlError("checkpoint_frequency must be >= 1")
            self.checkpoint_frequency = value
        elif name == "in_flight_barrier_nums":
            self.in_flight_barriers = max(1, value)
        elif name == "barrier_interval_ms":
            self.barrier_interval_ms = value   # read live by the CLI ticker
        elif name == "slow_epoch_threshold_ms":
            self.slow_epoch_threshold_ms = max(0.0, value)

    def parameters(self) -> list:
        """SHOW PARAMETERS rows (name, value)."""
        return [
            ("barrier_interval_ms", str(self.barrier_interval_ms)),
            ("checkpoint_frequency", str(self.checkpoint_frequency)),
            ("in_flight_barrier_nums", str(self.in_flight_barriers)),
            ("slow_epoch_threshold_ms", str(self.slow_epoch_threshold_ms)),
        ]

    # ----------------------------------------------------------------- DDL --

    def _create_source(self, stmt: A.CreateSource) -> list:
        if stmt.if_not_exists and stmt.name in self.catalog.sources:
            return []
        connector = str(stmt.with_options.get("connector", ""))
        fmt = str(stmt.with_options.get("format", "")).lower()
        if fmt in ("debezium", "debezium_json"):
            # fail at DDL time, not first-MV-build time (same gate as
            # _connector_reader — see the rationale there)
            raise SqlError(_DEBEZIUM_NEEDS_PK)
        if connector == "nexmark":
            table = str(stmt.with_options.get("nexmark_table",
                                              stmt.with_options.get("table", "bid")))
            schema = {"bid": BID_SCHEMA, "auction": AUCTION_SCHEMA,
                      "person": PERSON_SCHEMA}[table.lower()]
            if stmt.columns:
                declared = {c.name for c in stmt.columns}
                missing = declared - set(schema.names)
                if missing:
                    raise SqlError(f"columns {missing} not in nexmark {table}")
        elif stmt.columns:
            schema = Schema(tuple(
                Field(c.name, type_from_name(c.type_name))
                for c in stmt.columns))
        else:
            raise SqlError("CREATE SOURCE requires columns or a known connector")
        watermark = None
        if stmt.watermark is not None:
            watermark = self._bind_watermark(stmt.watermark, schema)
        self.catalog_writer.add_source(SourceDef(
            stmt.name, schema, connector, dict(stmt.with_options),
            watermark=watermark))
        return []

    def _bind_watermark(self, wm_ast, schema: Schema):
        col_name, expr = wm_ast
        try:
            idx = list(schema.names).index(col_name)
        except ValueError:
            raise SqlError(f"watermark column {col_name!r} not found")
        # supported shape: col - INTERVAL 'x'
        if (isinstance(expr, A.BinaryOp) and expr.op == "-"
                and isinstance(expr.left, A.ColumnRef)
                and expr.left.name == col_name
                and isinstance(expr.right, A.Lit)):
            return (idx, int(expr.right.value))
        raise SqlError("watermark must be '<col> - INTERVAL ...'")

    def _create_table(self, stmt: A.CreateTable) -> list:
        if stmt.if_not_exists and stmt.name in self.catalog.tables:
            return []
        self._drain_inflight()   # job wiring happens at a quiesced boundary
        self.catalog._check_free(stmt.name)   # fail BEFORE allocating ids
        fields = tuple(Field(c.name, type_from_name(c.type_name))
                       for c in stmt.columns)
        schema = Schema(fields)
        names = list(schema.names)
        if stmt.pk:
            pk = tuple(names.index(c) for c in stmt.pk)
        else:
            # hidden _row_id pk (reference: tables without pk get one)
            from ..common.types import SERIAL
            schema = Schema(fields + (Field("_row_id", SERIAL),))
            pk = (len(fields),)
        t = TableDef(stmt.name, schema, pk,
                     table_id=self.catalog.next_table_id(),
                     append_only=stmt.append_only)
        self.catalog_writer.add_table(t)
        # the table IS a stream job: DML queue -> (row id gen) -> materialize
        q = QueueSource(Schema(fields))
        src: Executor = q
        if not stmt.pk:
            start_seq = 0
            if self._recovering:
                # continue above the recovered max row id (ids are
                # shard<<48 | seq; mask off the shard prefix)
                recovered = StateTable(self.store, t.table_id, schema, list(pk))
                seqs = [r[len(fields)] & ((1 << 48) - 1)
                        for r in recovered.scan_all()]
                start_seq = max(seqs) + 1 if seqs else 0
            src = _RowIdAppendSource(q, schema)
            src = RowIdGenExecutor(src, row_id_index=len(fields),
                                   shard_id=self._alloc_shard(),
                                   start_seq=start_seq)
        mat = MaterializeExecutor(
            src, StateTable(self.store, t.table_id, schema, list(pk)))
        job = StreamJob(stmt.name, mat, [q])
        self.jobs[stmt.name] = job
        from ..stream.dml import TableDmlHandle
        self.dml.register(t.table_id, TableDmlHandle(q.push))
        self._table_queues.setdefault(stmt.name, []).append(q)
        job.start(self.loop)
        q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))
        return []

    def _plan(self, query: A.Select, lenient: bool = False):
        """Plan + optimize one SELECT (the full frontend pipeline:
        parse → bind → plan → rule-engine passes)."""
        from .optimizer import optimize
        plan = Planner(self.catalog, lenient=lenient,
                       session=self).plan_select(query)
        return optimize(plan)

    def _explain(self, stmt: "A.Explain") -> list:
        """EXPLAIN: optimized plan as one row per line (reference:
        handler/explain.rs renders the same way)."""
        inner = stmt.stmt
        if isinstance(inner, A.Query):
            sel = inner.select
        elif isinstance(inner, (A.CreateMaterializedView, A.CreateSink)):
            sel = inner.query
            if sel is None:
                raise SqlError("EXPLAIN CREATE SINK requires AS SELECT")
        else:
            raise SqlError(
                f"cannot EXPLAIN {type(inner).__name__}")
        plan = self._plan(sel)
        from ..common.types import VARCHAR
        self.last_select_schema = [("QUERY PLAN", VARCHAR)]
        return [(line,) for line in plan.explain().split("\n")]

    def _build_query_pipeline(self, query: A.Select, plan=None):
        """Shared CREATE MV / CREATE SINK AS SELECT plumbing: plan, build
        executors via the stream-leaf factory, collect session-driven
        queues + their init feeds and (under recovery) the scan leaves
        whose backfill may need re-running. ``plan`` reuses a plan the
        caller already built (the coschedule match) instead of planning
        the same query twice."""
        if plan is None:
            plan = self._plan(query, lenient=self._recovering)
        queues: list[QueueSource] = []
        init_msgs: list[tuple[QueueSource, list[Message]]] = []
        scan_leaf_queues: list[tuple[list, StreamJob]] = []

        def factory(leaf) -> Executor:
            # scan leaves backfill concurrently through their progress
            # tables (stream/backfill.py) — no init-snapshot replay here;
            # scan_leaf_queues remains only for CREATE SINK FROM <mv>,
            # which subscribes outside this factory
            ex, q, init = self._stream_leaf(leaf)
            if q is not None:
                queues.append(q)
                init_msgs.append((q, init))
            return ex

        ctx = BuildContext(self.store, self.catalog.next_table_id, factory,
                           self.config, durable=True)
        pipeline = build_plan(plan, ctx)
        return plan, pipeline, ctx, queues, init_msgs, scan_leaf_queues

    def _maybe_rebackfill(self, state_tids, scan_leaf_queues) -> None:
        """Recovery: the DDL log records a CREATE the moment it succeeds,
        but its state first persists at the NEXT checkpoint. If we crashed
        in that window the recovered state is empty — re-run the backfill
        snapshot from the recovered upstream instead of trusting state
        that never existed."""
        if not self._recovering:
            return
        has_state = any(self.store.table_len(tid) > 0 for tid in state_tids)
        if not has_state:
            for init, up_job in scan_leaf_queues:
                init.extend(up_job.snapshot_messages(
                    Barrier.new(self.epoch), self.source_chunk_capacity))

    def _create_index(self, stmt: A.CreateIndex) -> list:
        """CREATE INDEX = a hidden MV materializing the base relation
        re-keyed by the index columns (reference: an index is a
        StreamMaterialize with order/distribution on the index columns,
        src/frontend/src/handler/create_index.rs). Batch point lookups
        prefix-scan its state table (batch/lower.py)."""
        from .catalog import IndexDef, strip_schema
        if stmt.if_not_exists and stmt.name in self.catalog.indexes:
            return []
        self.catalog._check_free(stmt.name)
        base_name = strip_schema(stmt.table)
        kind, d = self.catalog.resolve_relation(base_name)
        if kind == "source":
            raise SqlError("cannot index a source; index a table or MV")
        n_vis = getattr(d, "n_visible", len(d.schema))
        visible = [f.name for i, f in enumerate(d.schema) if i < n_vis]
        for c in stmt.columns:
            if c not in visible:
                raise SqlError(f"column {c!r} not found in {base_name!r}")
        for i in d.pk:
            if d.schema[i].name not in visible:
                raise SqlError(
                    f"cannot index {base_name!r}: its stream key has "
                    "hidden columns")
        rest = [c for c in visible if c not in stmt.columns]
        mv_name = f"__idx_{stmt.name}"
        sel = parse_sql(
            f"SELECT {', '.join(list(stmt.columns) + rest)} "
            f"FROM {base_name}")[0].select
        self._create_mv(
            A.CreateMaterializedView(mv_name, sel),
            pk_prefix=len(stmt.columns))
        self.catalog_writer.add_index(
            IndexDef(stmt.name, base_name, tuple(stmt.columns),
                     mv_name=mv_name))
        return []

    def _create_mv(self, stmt: A.CreateMaterializedView,
                   pk_prefix: int = 0) -> list:
        if stmt.if_not_exists and stmt.name in self.catalog.mvs:
            return []
        self._drain_inflight()   # subscribe at a quiesced epoch boundary
        self.catalog._check_free(stmt.name)   # fail BEFORE building executors
        if self.workers and not pk_prefix \
                and not _ast_uses_udf(stmt.query):
            # index arrangements always build session-local (they scan
            # session-owned base state); worker placement is for plain MVs.
            # UDF-projecting plans also stay LOCAL: registered UDFs live
            # behind THIS process's client plane (udf/client.py) — a
            # worker process has no registration to resolve the name
            # against, so shipping the plan would fail at build time
            # (ISSUE 15; per-worker UDF planes are future work).
            # With ≥2 workers, source-fed plans deploy as CROSS-WORKER
            # fragment graphs (vnode-mapped placement, remote exchange);
            # unsupported shapes fall back to whole-job placement.
            from ..meta.fragment import SpanUnsupported
            # a replayed MV with a persisted placement MUST re-deploy as
            # the same spanning graph: falling through to whole-job
            # placement would resume fresh=False over per-worker stores
            # laid out for FRAGMENTS — refuse loudly instead of decoding
            # another layout's tables
            was_spanning = (self._recovering
                            and self.meta.load_placement(stmt.name)
                            is not None)
            if len(self.workers) >= 2:
                try:
                    return self._create_mv_spanning(stmt)
                except SpanUnsupported as e:
                    if was_spanning:
                        raise SqlError(
                            f"MV {stmt.name!r} was deployed as a "
                            f"spanning fragment graph but cannot be "
                            f"re-deployed ({e}); restart with the same "
                            "multi-worker topology (or DROP and "
                            "re-CREATE it)") from e
            elif was_spanning:
                raise SqlError(
                    f"MV {stmt.name!r} was deployed as a spanning "
                    "fragment graph; restart with the same multi-worker "
                    "topology (or DROP and re-CREATE it)")
            return self._create_mv_remote(stmt)
        cosched_plan = None
        if not pk_prefix and getattr(self.config, "coschedule", False) \
                and self.config.mesh is not None \
                and self.config.agg_hbm_budget is None \
                and (not self._recovering
                     or stmt.name in self._shardfused_markers):
            # mesh-sharded fused path (ops/fused_sharded.py): with a mesh
            # AND the fused opt-in, an eligible MV's whole epoch runs as
            # one dispatch across all chips; ineligible shapes fall
            # through to the mesh-sharded EXECUTORS (parallel/
            # executors.py) below. Recovery is marker-directed in both
            # directions, and re-shards onto THIS session's mesh size by
            # replaying the vnode mapping over the committed rows.
            res, cosched_plan = self._try_shardfused_mv(stmt)
            if res is not None:
                return res
        if self._recovering and stmt.name in self._shardfused_markers:
            raise SqlError(
                f"MV {stmt.name!r} was created mesh-sharded fused; reopen "
                "the session with a device mesh ([streaming] mesh_shape / "
                "BuildConfig.mesh) and [streaming] coschedule = true — or "
                "DROP and re-CREATE it")
        if not pk_prefix \
                and getattr(self.config, "tick_compiler", False) \
                and self.config.mesh is None \
                and self.config.fragment_parallelism <= 1 \
                and self.config.agg_hbm_budget is None \
                and (not self._recovering
                     or stmt.name in self._hetero_markers):
            # the heterogeneous tick compiler (stream/tick_compiler.py):
            # an eligible MV joins the compiled dispatch schedule even
            # when no signature-equal sibling exists — shape-class
            # padding / mega-epoch concatenation replace the exact-
            # signature grouping rule. Wins over ``coschedule`` when
            # both are set; ineligible shapes fall through. Recovery is
            # marker-directed in both directions, like coschedule.
            res, cosched_plan = self._try_hetero_mv(stmt)
            if res is not None:
                return res
        if self._recovering and stmt.name in self._hetero_markers:
            raise SqlError(
                f"MV {stmt.name!r} was created tick-compiled; reopen the "
                "session with [streaming] tick_compiler = true and a "
                "compatible config (no mesh, fragment_parallelism 1, "
                "no agg_hbm_budget) — or DROP and re-CREATE it")
        if not pk_prefix and getattr(self.config, "coschedule", False) \
                and self.config.mesh is None \
                and self.config.fragment_parallelism <= 1 \
                and self.config.agg_hbm_budget is None \
                and (not self._recovering
                     or stmt.name in self._cosched_markers):
            # agg_hbm_budget: the co-scheduled flush has no eviction
            # path, so budgeted configs stay on the executor pipeline.
            # Recovery gate: a solo-created MV's table-id layout differs
            # from the co-scheduled one — replay it down the path that
            # wrote it, marker-directed in BOTH directions.
            res, cosched_plan = self._try_coschedule_mv(stmt)
            if res is not None:
                return res
        if self._recovering and stmt.name in self._cosched_markers:
            # the durable agg/split tables were laid out by the
            # co-scheduled builder; decoding them through the executor
            # path would shift table ids — refuse loudly
            raise SqlError(
                f"MV {stmt.name!r} was created co-scheduled; reopen the "
                "session with [streaming] coschedule = true and a "
                "co-schedulable config (no mesh, fragment_parallelism 1, "
                "no agg_hbm_budget) — or DROP and re-CREATE it")
        n_feeds0 = len(self.feeds)
        n_bf0 = len(self.backfills)
        id0 = self.catalog._next_table_id   # for reschedule id replay
        (plan, pipeline, ctx, queues, init_msgs,
         scan_leaf_queues) = self._build_query_pipeline(
            stmt.query, plan=cosched_plan)
        mv_table_id = self.catalog.next_table_id()
        mv_pk = list(plan.pk)
        if pk_prefix:
            # index arrangement: key by the index columns first, base pk
            # after (dedup keeps key order); prefix scans by index value
            # ride the sorted key encoding
            mv_pk = list(range(pk_prefix)) + [
                i for i in plan.pk if i >= pk_prefix]
        mat = MaterializeExecutor(
            pipeline,
            StateTable(self.store, mv_table_id, plan.schema, mv_pk))
        # (no _maybe_rebackfill here: scan leaves re-run their own backfill
        # from the persisted cursor — created-but-never-checkpointed
        # recovery is the empty-progress case of stream/backfill.py)
        n_visible = sum(1 for f in plan.schema if not f.name.startswith("_"))
        mv = MaterializedViewDef(
            stmt.name, plan.schema, tuple(mv_pk), table_id=mv_table_id,
            definition="")
        mv.n_visible = n_visible  # type: ignore[attr-defined]
        mv.state_table_ids = tuple(ctx.state_table_ids)  # type: ignore[attr-defined]
        # reschedule metadata: the query AST + the id range the build
        # consumed (allocation order is deterministic, so a rebuild can
        # replay the same ids over the same durable state tables)
        mv.query_ast = stmt.query  # type: ignore[attr-defined]
        mv.table_id_range = (id0, self.catalog._next_table_id)  # type: ignore[attr-defined]
        self.catalog_writer.add_mv(mv)
        for f in self.feeds[n_feeds0:]:
            f.job = stmt.name
        for b in self.backfills[n_bf0:]:
            b.job = stmt.name
        job = StreamJob(stmt.name, mat, queues, actors=ctx.actors)
        self.jobs[stmt.name] = job
        job.start(self.loop)
        # the next barrier announces the new downstream to the graph
        # (reference: Mutation::Add, executor/mod.rs:220-238)
        self._pending_mutation = Mutation(MutationKind.ADD, stmt.name)
        # init cut: every root replays up to the current epoch's barrier
        for q, init in init_msgs:
            for m in init:
                q.push(m)
            q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))
        return []

    # ------------------------------------------------- co-scheduled MV jobs --

    def _try_coschedule_mv(self, stmt: A.CreateMaterializedView):
        """Route an eligible source+agg plan into the epoch co-scheduler
        (stream/coschedule.py): the group of all such MVs ticks in ONE
        fused dispatch per epoch. Returns ``(result, plan)``; result is
        None when the shape is ineligible (the solo executor fallback —
        which reuses ``plan`` instead of planning the query twice)."""
        from ..stream.coschedule import match_coschedulable
        if not any(sd.connector == "nexmark"
                   for sd in self.catalog.sources.values()):
            # cheap gate: without an eligible source no plan can match —
            # skip the extra planning pass the match would need
            return None, None
        plan = self._plan(stmt.query, lenient=self._recovering)
        m = match_coschedulable(plan)
        if m is None:
            return None, plan
        return self._create_mv_coscheduled(stmt, plan, m), plan

    def _create_mv_coscheduled(self, stmt: A.CreateMaterializedView,
                               plan, m) -> list:
        """Build one co-scheduled fused MV job: ingest happens inside the
        group's single vmapped dispatch; a real HashAggExecutor (over a
        dummy source, never executed) is kept as the flush/persistence
        engine so state-table checkpointing and recovery load are the
        executor path's own code; the MV pipeline is a plain
        QueueSource → Materialize fed by the group's barrier flush."""
        from ..common.types import INT64, VARCHAR
        from ..connector import NexmarkConfig
        from ..connector.nexmark import DeviceBidGenerator
        from ..stream.coschedule import (
            DeviceSourceCursor, FusedJobSpec, agg_signature,
            declared_chunk_fn,
        )
        from ..stream.hash_agg import HashAggExecutor, agg_state_schema
        from ..stream.project import ProjectExecutor
        from ..stream.source import MockSource

        # group membership changes restack the job axis: resolve any
        # deferred flush first (pipeline_depth >= 2)
        self._drain_fused_pipeline()
        id0 = self.catalog._next_table_id
        proj = ProjectExecutor(MockSource(m.source.schema, []),
                               list(m.exprs), names=m.proj_names)
        key_fields = [proj.schema[i] for i in m.group_keys]
        st = StateTable(self.store, self.catalog.next_table_id(),
                        agg_state_schema(key_fields, m.agg_calls),
                        list(range(len(m.group_keys))))
        agg = HashAggExecutor(
            proj, list(m.group_keys), list(m.agg_calls), state_table=st,
            table_capacity=self.config.agg_table_capacity,
            out_capacity=self.config.chunk_capacity)
        # split-state table: the device generator's event/epoch cursor,
        # persisted per checkpoint epoch exactly like a connector reader
        split_st = StateTable(
            self.store, self.catalog.next_table_id(),
            Schema((Field("split_id", VARCHAR),
                    Field("next_offset", INT64))), [0])
        cursor = DeviceSourceCursor()
        if self._recovering:
            offsets = {VARCHAR.to_python(r[0]): int(r[1])
                       for r in split_st.scan_all()}
            if offsets:
                cursor.seek(offsets)
        mv_table_id = self.catalog.next_table_id()
        q = QueueSource(plan.schema)
        mat = MaterializeExecutor(
            q, StateTable(self.store, mv_table_id, plan.schema,
                          list(plan.pk)))
        # honor the declared source's rows_per_chunk exactly like the
        # host reader does (connector/factory.py make_reader)
        rate = (m.source.options or {}).get("rows_per_chunk")
        rows_per_chunk = int(rate) if rate else self.source_chunk_capacity
        # seed parity with the solo executor path: every nexmark reader
        # is seeded with the session seed (factory.make_reader), so the
        # same CREATE yields the same stream regardless of the flag
        src_cfg = NexmarkConfig(chunk_capacity=rows_per_chunk)
        gen = DeviceBidGenerator(src_cfg, seed=self.seed)
        source_sig = ("nexmark_bid", src_cfg.chunk_capacity,
                      src_cfg.events_per_second, src_cfg.active_people,
                      src_cfg.in_flight_auctions, src_cfg.start_time_us,
                      m.col_map,
                      tuple(sorted((m.source.options or {}).items())))
        spec = FusedJobSpec(
            kind="agg",
            signature=agg_signature(agg.core, m.exprs, rows_per_chunk,
                                    source_sig),
            chunk_fn=declared_chunk_fn(gen.chunk_fn(), m.col_map),
            exprs=tuple(m.exprs), core=agg.core,
            rows_per_chunk=rows_per_chunk, seed=self.seed)

        mv = MaterializedViewDef(stmt.name, plan.schema, tuple(plan.pk),
                                 table_id=mv_table_id, definition="")
        mv.n_visible = sum(  # type: ignore[attr-defined]
            1 for f in plan.schema if not f.name.startswith("_"))
        mv.state_table_ids = (st.table_id,)  # type: ignore[attr-defined]
        mv.query_ast = stmt.query  # type: ignore[attr-defined]
        mv.table_id_range = (  # type: ignore[attr-defined]
            id0, self.catalog._next_table_id)
        self.catalog_writer.add_mv(mv)
        job = StreamJob(stmt.name, mat, [q])
        self.jobs[stmt.name] = job
        job.start(self.loop)
        self.feeds.append(_SourceFeed(q, lambda: None, reader=cursor,
                                      state_table=split_st,
                                      job=stmt.name))
        self._cosched.add(stmt.name, spec, agg.state,
                          start=cursor.events, batch_no=cursor.epochs)
        self._cosched_engines[stmt.name] = (agg, q, cursor)
        if self.data_dir is not None and not self._recovering:
            self.store.log.log_ddl(  # type: ignore[attr-defined]
                f"-- coschedule {stmt.name}")
        self._pending_mutation = Mutation(MutationKind.ADD, stmt.name)
        q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))
        return []

    def _push_cosched_outs(self, outs: dict) -> None:
        """Feed a resolved group flush into each member MV's
        Materialize queue (they ride the next barrier)."""
        for name, chunks in outs.items():
            q = self._cosched_engines[name][1]
            for ch in chunks:
                q.push(ch)

    def _cosched_tick(self, epoch: int, checkpoint: bool,
                      generate: bool) -> None:
        """Per-tick driver: ONE fused dispatch per group covers every
        member MV's epoch; the group flush feeds each job's Materialize
        queue; checkpoint barriers reuse the HashAggExecutor's own
        state-table delta flush, then restack once.

        Pipelined cadence (docs/performance.md "Pipelined tick"): the
        LAST tick's deferred flushes resolve first (their packed fetch
        has been streaming while the host ran the previous barrier, and
        their chunks ride THIS barrier), then EVERY group's next epoch
        is enqueued before any flush decode — the device queue stays
        full while Python gathers. With ``pipeline_depth >= 2`` the new
        flush stays pending into the next tick; checkpoint barriers
        (and generate-off ticks) resolve it synchronously, so committed
        state is bit-exact vs the synchronous path."""
        k = self.chunks_per_tick
        groups = list(self._cosched.groups.values())
        # 1. resolve last tick's deferred flushes (pipeline_depth >= 2)
        for group in groups:
            if group.pending is not None:
                self._push_cosched_outs(group.finish_flush())
        # 2. enqueue every group's epoch (cross-engine overlap)
        ran = generate and k > 0
        if ran:
            for group in groups:
                group.run_epoch(k)
                for j, name in enumerate(group.names):
                    cursor = self._cosched_engines[name][2]
                    cursor.events = group.starts[j]
                    cursor.epochs = group.batch_nos[j]
        # 3. enqueue every group's probe + start its packed fetch BEFORE
        #    decoding any of them
        for group in groups:
            group.begin_flush()
        if self.pipeline_depth >= 2 and ran and not checkpoint:
            # 4a. defer resolution to the next tick / drain point: epoch
            # N+1 will dispatch before this packed fetch resolves
            self._pipeline_stats["deferred_flushes"] += len(groups)
            return
        # 4b. synchronous resolution (depth 1, checkpoint, or idle tick)
        for group in groups:
            self._push_cosched_outs(group.finish_flush())
            if checkpoint:
                ckpt_states = []
                for name in group.names:
                    agg = self._cosched_engines[name][0]
                    agg.state = group.state_of(name)
                    agg._checkpoint_to_state_table(epoch)
                    ckpt_states.append(agg.state)
                group.set_states(ckpt_states)

    # ------------------------------------------ tick-compiled fused MV jobs --

    def _try_hetero_mv(self, stmt: A.CreateMaterializedView):
        """Route an eligible source+agg plan into the tick compiler
        (stream/tick_compiler.py): UNEQUAL jobs are fused into minimal
        dispatches — shape-class supergroups (padded + vmapped) plus
        jitted mega-epochs for the singletons. Returns ``(result,
        plan)``; result is None when the shape is ineligible (the solo
        executor fallback, which reuses ``plan``)."""
        from ..stream.coschedule import match_coschedulable
        if not any(sd.connector == "nexmark"
                   for sd in self.catalog.sources.values()):
            return None, None
        plan = self._plan(stmt.query, lenient=self._recovering)
        m = match_coschedulable(plan)
        if m is None:
            return None, plan
        return self._create_mv_hetero(stmt, plan, m), plan

    def _create_mv_hetero(self, stmt: A.CreateMaterializedView,
                          plan, m) -> list:
        """Build one tick-compiled fused MV job. Mirrors
        ``_create_mv_coscheduled`` — a real HashAggExecutor (never
        executed) remains the flush/persistence engine so state-table
        checkpointing and recovery load are the executor path's own
        code — but registration goes to the TickCompiler, which
        skeletonizes the plan and re-buckets the whole job set into
        shape-class supergroups + mega-epochs on the next tick."""
        from ..common.types import INT64, VARCHAR
        from ..connector import NexmarkConfig
        from ..connector.nexmark import DeviceBidGenerator
        from ..stream.coschedule import (
            DeviceSourceCursor, FusedJobSpec, agg_signature,
            declared_chunk_fn,
        )
        from ..stream.hash_agg import HashAggExecutor, agg_state_schema
        from ..stream.project import ProjectExecutor
        from ..stream.source import MockSource

        # registration dissolves every group (schedule recompile):
        # resolve any deferred flush first (pipeline_depth >= 2)
        self._drain_fused_pipeline()
        id0 = self.catalog._next_table_id
        proj = ProjectExecutor(MockSource(m.source.schema, []),
                               list(m.exprs), names=m.proj_names)
        key_fields = [proj.schema[i] for i in m.group_keys]
        st = StateTable(self.store, self.catalog.next_table_id(),
                        agg_state_schema(key_fields, m.agg_calls),
                        list(range(len(m.group_keys))))
        agg = HashAggExecutor(
            proj, list(m.group_keys), list(m.agg_calls), state_table=st,
            table_capacity=self.config.agg_table_capacity,
            out_capacity=self.config.chunk_capacity)
        split_st = StateTable(
            self.store, self.catalog.next_table_id(),
            Schema((Field("split_id", VARCHAR),
                    Field("next_offset", INT64))), [0])
        cursor = DeviceSourceCursor()
        if self._recovering:
            offsets = {VARCHAR.to_python(r[0]): int(r[1])
                       for r in split_st.scan_all()}
            if offsets:
                cursor.seek(offsets)
        mv_table_id = self.catalog.next_table_id()
        q = QueueSource(plan.schema)
        mat = MaterializeExecutor(
            q, StateTable(self.store, mv_table_id, plan.schema,
                          list(plan.pk)))
        rate = (m.source.options or {}).get("rows_per_chunk")
        rows_per_chunk = int(rate) if rate else self.source_chunk_capacity
        src_cfg = NexmarkConfig(chunk_capacity=rows_per_chunk)
        gen = DeviceBidGenerator(src_cfg, seed=self.seed)
        source_sig = ("nexmark_bid", src_cfg.chunk_capacity,
                      src_cfg.events_per_second, src_cfg.active_people,
                      src_cfg.in_flight_auctions, src_cfg.start_time_us,
                      m.col_map,
                      tuple(sorted((m.source.options or {}).items())))
        spec = FusedJobSpec(
            kind="agg",
            signature=agg_signature(agg.core, m.exprs, rows_per_chunk,
                                    source_sig),
            chunk_fn=declared_chunk_fn(gen.chunk_fn(), m.col_map),
            exprs=tuple(m.exprs), core=agg.core,
            rows_per_chunk=rows_per_chunk, seed=self.seed)

        mv = MaterializedViewDef(stmt.name, plan.schema, tuple(plan.pk),
                                 table_id=mv_table_id, definition="")
        mv.n_visible = sum(  # type: ignore[attr-defined]
            1 for f in plan.schema if not f.name.startswith("_"))
        mv.state_table_ids = (st.table_id,)  # type: ignore[attr-defined]
        mv.query_ast = stmt.query  # type: ignore[attr-defined]
        mv.table_id_range = (  # type: ignore[attr-defined]
            id0, self.catalog._next_table_id)
        self.catalog_writer.add_mv(mv)
        job = StreamJob(stmt.name, mat, [q])
        self.jobs[stmt.name] = job
        job.start(self.loop)
        self.feeds.append(_SourceFeed(q, lambda: None, reader=cursor,
                                      state_table=split_st,
                                      job=stmt.name))
        self._hetero.add(stmt.name, spec, agg.state,
                         n_source_cols=len(m.col_map),
                         start=cursor.events, batch_no=cursor.epochs)
        self._fold_hetero_retired()
        self._hetero_engines[stmt.name] = (agg, q, cursor)
        if self.data_dir is not None and not self._recovering:
            self.store.log.log_ddl(  # type: ignore[attr-defined]
                f"-- hetero {stmt.name}")
        self._pending_mutation = Mutation(MutationKind.ADD, stmt.name)
        q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))
        return []

    def _fold_hetero_retired(self) -> None:
        """Fold dissolved groups' epochs-run into the retirement ledger
        so the dispatch/epoch invariant (``per_epoch == 1.0``) survives
        schedule recompilation: the counts a dead group accumulated
        still back the dispatches it issued."""
        for qn, n in self._hetero.take_retired().items():
            self._dispatch_epochs_retired[qn] = (
                self._dispatch_epochs_retired.get(qn, 0) + n)

    def _push_hetero_outs(self, outs: dict) -> None:
        for name, chunks in outs.items():
            q = self._hetero_engines[name][1]
            for ch in chunks:
                q.push(ch)

    def _hetero_tick(self, epoch: int, checkpoint: bool,
                     generate: bool) -> None:
        """Per-tick driver for the tick compiler: one dispatch per
        compiled group (shape-class supergroup or mega-epoch) covers
        every member MV's epoch. Mirrors ``_cosched_tick`` — pipelined
        cadence, deferred flush at ``pipeline_depth >= 2``, checkpoint
        write-back through each job's own HashAggExecutor — but the
        schedule is (re)compiled lazily here, only when DDL has marked
        it dirty since the last tick."""
        self._hetero.ensure_compiled()
        k = self.chunks_per_tick
        groups = list(self._hetero.groups)
        # 1. resolve last tick's deferred flushes (pipeline_depth >= 2)
        for group in groups:
            if group.pending is not None:
                self._push_hetero_outs(group.finish_flush())
        # 2. enqueue every group's epoch (cross-group overlap)
        ran = generate and k > 0
        if ran:
            for group in groups:
                group.run_epoch(k)
                for j, name in enumerate(group.names):
                    cursor = self._hetero_engines[name][2]
                    cursor.events = group.starts[j]
                    cursor.epochs = group.batch_nos[j]
        # 3. enqueue every group's probe + packed fetch before decoding
        for group in groups:
            group.begin_flush()
        if self.pipeline_depth >= 2 and ran and not checkpoint:
            self._pipeline_stats["deferred_flushes"] += len(groups)
            return
        # 4. synchronous resolution (depth 1, checkpoint, or idle tick)
        for group in groups:
            self._push_hetero_outs(group.finish_flush())
            if checkpoint:
                ckpt_states = []
                for name in group.names:
                    agg = self._hetero_engines[name][0]
                    agg.state = group.state_of(name)
                    agg._checkpoint_to_state_table(epoch)
                    ckpt_states.append(agg.state)
                group.set_states(ckpt_states)

    # ------------------------------------------- mesh-sharded fused MV jobs --

    def _try_shardfused_mv(self, stmt: A.CreateMaterializedView):
        """Route an eligible source+agg plan onto the mesh-sharded fused
        path (ops/fused_sharded.py + parallel/fused.py): the MV's whole
        epoch — generation, projection, the in-dispatch all_to_all vnode
        shuffle, aggregation — is ONE dispatch across every chip of
        ``config.mesh``. Eligibility is exactly the co-scheduler's shape
        match; anything else returns ``(None, plan)`` and builds the
        mesh-sharded executor pipeline instead."""
        from ..stream.coschedule import match_coschedulable
        if not any(sd.connector == "nexmark"
                   for sd in self.catalog.sources.values()):
            return None, None
        plan = self._plan(stmt.query, lenient=self._recovering)
        m = match_coschedulable(plan)
        if m is None:
            return None, plan
        return self._create_mv_sharded_fused(stmt, plan, m), plan

    def _create_mv_sharded_fused(self, stmt: A.CreateMaterializedView,
                                 plan, m) -> list:
        """Build one mesh-sharded fused MV job. Mirrors
        ``_create_mv_coscheduled``: a real HashAggExecutor (never
        executed) is the flush/persistence engine, so the state-table
        checkpoint delta and the durable layout are the executor path's
        own code; the MV pipeline is QueueSource → Materialize fed by
        the sharded group flush. TWO differences: state placement —
        per-shard AggCore states live stacked under ``P('shard')`` and
        recovery re-shards the committed rows onto THIS session's mesh
        by replaying the vnode mapping (parallel/fused.py
        ``load_shard_states``), so an 8-shard checkpoint reopens cleanly
        on a 4-shard mesh — and multiplexing: signature-equal MVs join
        ONE K-jobs × S-shards group (ShardedCoGroup, fusion surface 6),
        so the whole group is one dispatch per tick, not one per MV."""
        from ..common.types import INT64, VARCHAR
        from ..connector import NexmarkConfig
        from ..connector.nexmark import DeviceBidGenerator
        from ..parallel.fused import ShardedCoScheduler, load_shard_states
        from ..stream.coschedule import (
            DeviceSourceCursor, FusedJobSpec, agg_signature,
            declared_chunk_fn,
        )
        from ..stream.hash_agg import HashAggExecutor, agg_state_schema
        from ..stream.project import ProjectExecutor
        from ..stream.source import MockSource

        # group membership changes restack the job axis: resolve any
        # deferred flush first (pipeline_depth >= 2)
        self._drain_fused_pipeline()
        id0 = self.catalog._next_table_id
        proj = ProjectExecutor(MockSource(m.source.schema, []),
                               list(m.exprs), names=m.proj_names)
        key_fields = [proj.schema[i] for i in m.group_keys]
        st = StateTable(self.store, self.catalog.next_table_id(),
                        agg_state_schema(key_fields, m.agg_calls),
                        list(range(len(m.group_keys))))
        # state_table attached AFTER construction: the executor's own
        # recovery load would pull EVERY shard's rows into one solo
        # table — the sharded load below re-partitions them instead
        agg = HashAggExecutor(
            proj, list(m.group_keys), list(m.agg_calls), state_table=None,
            table_capacity=self.config.agg_table_capacity,
            out_capacity=self.config.chunk_capacity)
        agg.state_table = st
        mesh = self.config.mesh
        n_shards = mesh.devices.size
        states = None
        if self._recovering:
            rows = list(st.scan_all())
            if rows:
                states = load_shard_states(agg.core, rows, n_shards)
        split_st = StateTable(
            self.store, self.catalog.next_table_id(),
            Schema((Field("split_id", VARCHAR),
                    Field("next_offset", INT64))), [0])
        cursor = DeviceSourceCursor()
        if self._recovering:
            offsets = {VARCHAR.to_python(r[0]): int(r[1])
                       for r in split_st.scan_all()}
            if offsets:
                cursor.seek(offsets)
        mv_table_id = self.catalog.next_table_id()
        q = QueueSource(plan.schema)
        mat = MaterializeExecutor(
            q, StateTable(self.store, mv_table_id, plan.schema,
                          list(plan.pk)))
        rate = (m.source.options or {}).get("rows_per_chunk")
        rows_per_chunk = int(rate) if rate else self.source_chunk_capacity
        src_cfg = NexmarkConfig(chunk_capacity=rows_per_chunk)
        gen = DeviceBidGenerator(src_cfg, seed=self.seed)
        source_sig = ("nexmark_bid", src_cfg.chunk_capacity,
                      src_cfg.events_per_second, src_cfg.active_people,
                      src_cfg.in_flight_auctions, src_cfg.start_time_us,
                      m.col_map,
                      tuple(sorted((m.source.options or {}).items())))
        spec = FusedJobSpec(
            kind="agg",
            signature=agg_signature(agg.core, m.exprs, rows_per_chunk,
                                    source_sig),
            chunk_fn=declared_chunk_fn(gen.chunk_fn(), m.col_map),
            exprs=tuple(m.exprs), core=agg.core,
            rows_per_chunk=rows_per_chunk, seed=self.seed)
        if self._shardfused is None or self._shardfused.mesh is not mesh:
            self._shardfused = ShardedCoScheduler(mesh)
        group = self._shardfused.add(
            stmt.name, spec, shard_states=states, start=cursor.events,
            batch_no=cursor.epochs)

        mv = MaterializedViewDef(stmt.name, plan.schema, tuple(plan.pk),
                                 table_id=mv_table_id, definition="")
        mv.n_visible = sum(  # type: ignore[attr-defined]
            1 for f in plan.schema if not f.name.startswith("_"))
        mv.state_table_ids = (st.table_id,)  # type: ignore[attr-defined]
        mv.query_ast = stmt.query  # type: ignore[attr-defined]
        mv.table_id_range = (  # type: ignore[attr-defined]
            id0, self.catalog._next_table_id)
        self.catalog_writer.add_mv(mv)
        job = StreamJob(stmt.name, mat, [q])
        self.jobs[stmt.name] = job
        job.start(self.loop)
        self.feeds.append(_SourceFeed(q, lambda: None, reader=cursor,
                                      state_table=split_st,
                                      job=stmt.name))
        self._shardfused_engines[stmt.name] = (agg, q, cursor, group)
        self._shardfused_markers.add(stmt.name)
        if self.data_dir is not None and not self._recovering:
            self.store.log.log_ddl(  # type: ignore[attr-defined]
                f"-- shardfused {stmt.name}")
        self._pending_mutation = Mutation(MutationKind.ADD, stmt.name)
        q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))
        return []

    def _push_shardfused_outs(self, outs: dict) -> None:
        for name, chunks in outs.items():
            q = self._shardfused_engines[name][1]
            for ch in chunks:
                q.push(ch)

    def _shardfused_tick(self, epoch: int, checkpoint: bool,
                         generate: bool) -> None:
        """Per-tick driver: ONE dispatch per K×S group covers every
        member MV's whole epoch across all chips; the group flush (one
        packed [n, J, 3] fetch) feeds each job's Materialize queue;
        checkpoint barriers write every (job, shard) delta through each
        job's own state-table flush, then restack once per group.
        Pipelined cadence exactly as ``_cosched_tick``; the sharded
        grow-retry drains inside ``finish_flush`` before anything else
        dispatches, and sharded epochs never donate, so the deferred
        handle's pre-finish state stays valid for the gathers."""
        k = self.chunks_per_tick
        groups = list(self._shardfused.groups.values())
        for group in groups:
            if group.pending is not None:
                self._push_shardfused_outs(group.finish_flush())
        ran = generate and k > 0
        if ran:
            for group in groups:
                group.run_epoch(k)
                for j, name in enumerate(group.names):
                    cursor = self._shardfused_engines[name][2]
                    cursor.events = group.starts[j]
                    cursor.epochs = group.batch_nos[j]
        for group in groups:
            group.begin_flush()
        if self.pipeline_depth >= 2 and ran and not checkpoint:
            self._pipeline_stats["deferred_flushes"] += len(groups)
            return
        for group in groups:
            self._push_shardfused_outs(group.finish_flush())
            if checkpoint:
                group.checkpoint(
                    {name: self._shardfused_engines[name][0]
                     for name in group.names}, epoch)

    def _drain_fused_pipeline(self) -> None:
        """Resolve every deferred fused flush and feed its chunks to the
        job queues (they ride the next barrier). The pipeline's drain
        points — DDL, DROP, scoped recovery, checkpoint ticks — call
        this so membership changes and durable cuts never race an
        in-flight packed fetch. No-op when nothing is pending (always,
        at pipeline_depth = 1)."""
        for group in list(self._cosched.groups.values()):
            if group.pending is not None:
                self._push_cosched_outs(group.finish_flush())
                self._pipeline_stats["drains"] += 1
        for group in list(self._hetero.groups):
            if group.pending is not None:
                self._push_hetero_outs(group.finish_flush())
                self._pipeline_stats["drains"] += 1
        if self._shardfused is not None:
            for group in list(self._shardfused.groups.values()):
                if group.pending is not None:
                    self._push_shardfused_outs(group.finish_flush())
                    self._pipeline_stats["drains"] += 1

    # ------------------------------------------------------ remote MV jobs --

    def _plan_remote_mv(self, query: A.Select, worker):
        """Plan + classify leaves for a worker-hosted MV: connector
        sources run worker-side; table/MV scans become remote exchange
        channels fed by the session (the upstream jobs are local)."""
        plan = self._plan(query, lenient=self._recovering)
        leaves = collect_leaves(plan)
        defs, channels, ups = [], {}, {}
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, PSource):
                defs.append(leaf.source)
            elif isinstance(leaf, PTableScan):
                defs.append(leaf.table)
                channels[i] = worker.alloc_chan()
                ups[i] = (leaf.table.name, leaf.schema)
            elif isinstance(leaf, PMvScan):
                if self._mv_worker(leaf.mv.name) is not None:
                    raise SqlError(
                        "an MV over a worker-hosted MV is not supported "
                        "yet; chain MVs in-process or via a table")
                defs.append(leaf.mv)
                channels[i] = worker.alloc_chan()
                ups[i] = (leaf.mv.name, leaf.schema)
            else:
                raise SqlError(
                    f"cannot place {type(leaf).__name__} on a worker")
        return plan, defs, channels, ups

    def _create_mv_remote(self, stmt: A.CreateMaterializedView) -> list:
        """CREATE MATERIALIZED VIEW on a worker process (reference: the
        meta DdlController building actors on compute nodes,
        src/meta/src/rpc/ddl_controller.rs + stream_service.rs:46-233)."""
        from .plan_json import defs_to_json, plan_to_json
        from .remote import RemoteJob
        worker = self.workers[self._next_remote % len(self.workers)]
        self._next_remote += 1
        plan, defs, channels, ups = self._plan_remote_mv(stmt.query, worker)
        # id allocation must stay replay-deterministic: a FAILED create
        # must roll the counter back, or every later object shifts ids
        # relative to the DDL replay that skips the failure
        id_rollback = self.catalog._next_table_id
        mv_table_id = self.catalog.next_table_id()
        id_start = self.catalog._next_table_id
        cfg = self.config
        req = {
            "type": "create_job", "name": stmt.name,
            "plan": plan_to_json(plan), "defs": defs_to_json(defs),
            "mv_table_id": mv_table_id, "id_start": id_start,
            "channels": {str(i): c for i, c in channels.items()},
            "config": {
                "chunk_capacity": cfg.chunk_capacity,
                "agg_table_capacity": cfg.agg_table_capacity,
                "join_key_capacity": cfg.join_key_capacity,
                "join_bucket_width": cfg.join_bucket_width,
                "topn_table_capacity": cfg.topn_table_capacity,
                "agg_hbm_budget": cfg.agg_hbm_budget,
            },
            "chunks_per_tick": self.chunks_per_tick,
            "chunk_capacity": self.source_chunk_capacity,
            "seed": self.seed,
            # fault knobs travel with the job: worker-hosted broker
            # readers honor the same reconnect budget as local ones
            "fault": dataclasses.asdict(self.fault),
            # session-restart replay of a channel-fed job rebuilds fresh
            # from the upstream snapshot (the changelog between the
            # worker's and the session's last commits is unrecoverable);
            # source-fed jobs resume from worker-durable state + offsets
            "fresh": bool(channels) and self._recovering,
        }
        try:
            resp = self._await(worker.request(req))
        except BaseException:
            self.catalog._next_table_id = id_rollback
            raise
        self.catalog._next_table_id = max(self.catalog._next_table_id,
                                          resp["ids_end"])
        n_visible = sum(1 for f in plan.schema
                        if not f.name.startswith("_"))
        mv = MaterializedViewDef(stmt.name, plan.schema, tuple(plan.pk),
                                 table_id=mv_table_id, definition="")
        mv.n_visible = n_visible  # type: ignore[attr-defined]
        mv.state_table_ids = tuple(resp["state_table_ids"])  # type: ignore[attr-defined]
        mv.query_ast = stmt.query  # type: ignore[attr-defined]
        mv.table_id_range = (id_start, resp["ids_end"])  # type: ignore[attr-defined]
        mv.remote_worker = worker.worker_id  # type: ignore[attr-defined]
        self.catalog_writer.add_mv(mv)
        job = RemoteJob(stmt.name, worker)
        self.jobs[stmt.name] = job
        self._remote_specs[stmt.name] = {
            "worker": worker, "channels": channels, "ups": ups, "req": req}
        self._wire_remote_channels(stmt.name)
        self._pending_mutation = Mutation(MutationKind.ADD, stmt.name)
        self._await(worker.init_barrier(stmt.name, self.epoch))
        return []

    def _wire_remote_channels(self, name: str) -> None:
        """Build the session side of each remote exchange edge: subscribe
        to the upstream bus, ship the backfill snapshot, start the
        permit-metered forwarder (reference: exchange_service.rs:74-133 +
        backfill snapshot-then-deltas)."""
        spec = self._remote_specs[name]
        worker = spec["worker"]
        job = self.jobs[name]
        for i, chan in spec["channels"].items():
            up_name, leaf_schema = spec["ups"][i]
            up_job = self.jobs[up_name]
            snap = up_job.snapshot_messages(Barrier.new(self.epoch),
                                            self.source_chunk_capacity)
            q = QueueSource(leaf_schema)
            up_job.bus.subscribe(q)
            job.sources.append(q)

            async def _ship(snap=snap, chan=chan, schema=leaf_schema):
                for m in snap:
                    await worker.send_data(chan, m, schema)

            self._await(_ship())
            worker.start_forwarder(name, q, chan, leaf_schema)

    def _recover_remote_job(self, name: str) -> list[str]:
        """Scoped recovery of a worker-hosted job across the process
        boundary: respawn the worker if its process died, re-create the
        job (fresh-from-snapshot for channel-fed, durable-resume for
        source-fed), re-wire exchange edges (reference: recovery.rs:110
        rebuilding actors on a replacement worker)."""
        self._drain_inflight()
        self._bump_generation()
        spec = self._remote_specs[name]
        worker = spec["worker"]
        job = self.jobs.pop(name, None)
        if job is not None:
            self._await(job.stop())
            self._unsubscribe_job(job)
            self.meta.deregister_job(name)
            self._dead_jobs.discard(name)
        if worker.dead:
            worker.respawn(self._await)
            # the replacement process numbers its span batches from 0 —
            # a stale ack could match the fresh counter and make the
            # worker discard a never-delivered span outbox
            self._worker_span_ack.pop(worker.worker_id, None)
        from .remote import RemoteJob
        req = dict(spec["req"])
        if spec["channels"]:
            # fresh rebuild from the upstream's CURRENT state: the deltas
            # the dead worker consumed past its last commit are gone with
            # its bus subscription, so resuming from worker state would
            # fork history — snapshot-rebuild is the consistent cut
            req["fresh"] = True
            new_channels = {i: worker.alloc_chan()
                            for i in spec["channels"]}
            spec["channels"] = new_channels
            req["channels"] = {str(i): c for i, c in new_channels.items()}
        else:
            req["fresh"] = False
        spec["req"] = req
        self._await(worker.request(req))
        self.jobs[name] = RemoteJob(name, worker)
        self._wire_remote_channels(name)
        self._await(worker.init_barrier(name, self.epoch))
        self.meta.notifications.notify(
            "recovery", {"jobs": [name], "epoch": self.epoch})
        return [name]

    # ------------------------------------------ spanning fragment-graph jobs --

    def _create_mv_spanning(self, stmt: A.CreateMaterializedView) -> list:
        """CREATE MATERIALIZED VIEW as a fragment graph SPANNING worker
        processes: the meta scheduler places fragments by vnode mapping,
        each worker builds only its fragments, and the edges between them
        cross the wire protocol with permit-based credit (reference: the
        DdlController + scheduler splitting one streaming job's fragment
        graph over compute nodes, src/meta/src/stream/stream_graph/ +
        scale.rs vnode mappings)."""
        from ..meta.fragment import (
            FragmentScheduler, SpanUnsupported, span_plan,
        )
        from .plan_json import defs_to_json
        from .remote import SpanningJob
        plan = self._plan(stmt.query, lenient=self._recovering)
        graph = span_plan(plan)              # raises SpanUnsupported
        # placement targets come from the meta compute-node registry,
        # reconciled with the live process handles (reference: the
        # scheduler reads the ClusterManager's worker set)
        for w in self.workers:
            self.meta.cluster.set_compute_state(
                w.worker_id, "DOWN" if w.dead else "RUNNING")
        worker_ids = [n.worker_id
                      for n in self.meta.cluster.live_compute_nodes()]
        if len(worker_ids) < 2:
            raise SpanUnsupported("fewer than two live workers")
        placement = None
        fresh = not self._recovering
        if self._recovering:
            # a restarted session MUST re-place fragments where their
            # per-worker durable state lives: the persisted mapping wins
            prev = self.meta.load_placement(stmt.name)
            if prev is not None:
                if set(prev.actors) == set(graph.fragments) \
                        and set(prev.workers()) <= set(worker_ids):
                    placement = prev
                else:
                    # re-placing over stale per-worker stores with
                    # fresh=False would reload other shards' state —
                    # refuse loudly instead of corrupting silently
                    raise RuntimeError(
                        f"spanning MV {stmt.name!r} was deployed on "
                        f"workers {prev.workers()} "
                        f"({len(prev.actors)} fragments) but this "
                        f"session has workers {worker_ids}; restart "
                        "with the same --workers topology (or DROP and "
                        "re-CREATE the MV)")
            else:
                # no persisted placement (pre-spanning data dir or a
                # wiped meta store): rebuild from scratch — wiping is
                # consistent, resuming over unknown layouts is not
                fresh = True
        if placement is None:
            placement = FragmentScheduler().place(
                stmt.name, graph, worker_ids,
                parallelism=self.config.fragment_parallelism)
        defs, seen = [], set()
        for frag in graph.fragments.values():
            for leaf in collect_leaves(frag.plan):
                if isinstance(leaf, PSource) \
                        and leaf.source.name not in seen:
                    seen.add(leaf.source.name)
                    defs.append(leaf.source)
        id_rollback = self.catalog._next_table_id
        mv_table_id = self.catalog.next_table_id()
        id_start = self.catalog._next_table_id
        id_end = id_start + len(graph.fragments) * _SPAN_ID_STRIDE
        self.catalog._next_table_id = id_end
        by_id = {w.worker_id: w for w in self.workers}
        involved = [by_id[wid] for wid in placement.workers()]
        spec = {"graph": graph, "placement": placement,
                "workers": involved,
                "root_worker": by_id[placement.root_worker],
                "mv_table_id": mv_table_id, "id_start": id_start,
                "defs": defs_to_json(defs)}
        recover_at = None
        if not fresh:
            # session-restart replay: participants may sit one phase-2
            # frame apart (a worker killed between prepare and commit) —
            # settle every store on the cluster-decided cut first
            recover_at = self._span_decided_epoch(stmt.name, involved)
        reqs = self._span_requests(stmt.name, spec, fresh=fresh,
                                   recover_at=recover_at)
        created, state_table_ids = [], []
        try:
            for w in involved:
                resp = self._await(w.request(reqs[w.worker_id]))
                created.append(w)
                state_table_ids.extend(resp.get("state_table_ids", ()))
        except BaseException:
            # id-replay determinism + no half-deployed graph: roll the
            # counter back and tear down what was already built
            self.catalog._next_table_id = id_rollback
            for w in created:
                try:
                    self._await(w.request(
                        {"type": "drop_job", "name": stmt.name,
                         "epoch": self._injected + 1}))
                except Exception:  # noqa: BLE001 - best-effort undo
                    pass
            raise
        n_visible = sum(1 for f in plan.schema
                        if not f.name.startswith("_"))
        mv = MaterializedViewDef(stmt.name, plan.schema, tuple(plan.pk),
                                 table_id=mv_table_id, definition="")
        mv.n_visible = n_visible  # type: ignore[attr-defined]
        mv.state_table_ids = tuple(state_table_ids)  # type: ignore[attr-defined]
        mv.query_ast = stmt.query  # type: ignore[attr-defined]
        mv.table_id_range = (id_start, id_end)  # type: ignore[attr-defined]
        mv.span_workers = placement.workers()  # type: ignore[attr-defined]
        self.catalog_writer.add_mv(mv)
        from ..meta.rescale import commit_placement
        commit_placement(self.meta, placement)
        self.jobs[stmt.name] = SpanningJob(stmt.name, involved)
        self._spanning_specs[stmt.name] = spec
        self._pending_mutation = Mutation(MutationKind.ADD, stmt.name)

        async def _init_all() -> None:
            # every participant acks once ITS actors saw the init cut —
            # the barrier reaches non-source fragments over the wire, so
            # the waits must run concurrently
            await asyncio.gather(*(w.init_barrier(stmt.name, self.epoch)
                                   for w in involved))

        self._await(_init_all())
        return []

    def _span_requests(self, name: str, spec: dict, fresh: bool,
                       recover_at: Optional[int] = None,
                       import_refs: Optional[dict] = None) -> dict[int, dict]:
        """Per-worker ``create_fragments`` requests for one spanning job.
        Re-run at recovery with FRESH channel ids and the workers'
        CURRENT ports (a respawned worker listens on a new ephemeral
        port), so edge specs always name live peers. ``import_refs``
        ((fragment, actor) → handoff segment paths) rides a LIVE RESCALE
        deployment: the receiving worker imports those refs' rows before
        building (meta/rescale.py, docs/scaling.md)."""
        from .plan_json import plan_to_json
        graph, placement = spec["graph"], spec["placement"]
        by_id = {w.worker_id: w for w in self.workers}
        consumers: dict[int, int] = {}            # u_fid -> d_fid
        for d_fid, frag in graph.fragments.items():
            for u_fid in frag.upstream:
                consumers[u_fid] = d_fid
        chan_of: dict[tuple, int] = {}
        for u_fid, d_fid in consumers.items():
            for ua in range(len(placement.actors[u_fid])):
                for da in range(len(placement.actors[d_fid])):
                    chan_of[(u_fid, ua, d_fid, da)] = \
                        next(self._next_span_chan)

        def edge(u_fid, ua, d_fid, da) -> str:
            return f"{name}:f{u_fid}.{ua}->f{d_fid}.{da}"

        cfg = self.config
        frag_specs: dict[int, list] = {w.worker_id: []
                                       for w in spec["workers"]}
        for fid in sorted(graph.fragments):
            frag = graph.fragments[fid]
            plan_json = plan_to_json(frag.plan)   # same for every actor
            for ap in placement.actors[fid]:
                inputs = []
                for u_fid in frag.upstream:
                    chans = []
                    for up in placement.actors[u_fid]:
                        chans.append({
                            "chan": chan_of[(u_fid, up.actor, fid,
                                             ap.actor)],
                            "from_worker": up.worker,
                            "edge": edge(u_fid, up.actor, fid, ap.actor),
                        })
                    inputs.append({"up_fid": u_fid, "chans": chans})
                out = None
                if not frag.is_root:
                    d_fid = consumers[fid]
                    downs = placement.actors[d_fid]
                    if len(downs) > 1 and not frag.dist_keys:
                        raise RuntimeError(
                            f"fragment {fid} has {len(downs)} downstream "
                            "actors but no distribution keys")
                    out = {
                        "kind": "hash" if frag.dist_keys else "simple",
                        "keys": list(frag.dist_keys),
                        "targets": [{
                            "chan": chan_of[(fid, ap.actor, d_fid,
                                             dp.actor)],
                            "worker": dp.worker,
                            "host": "127.0.0.1",
                            "port": by_id[dp.worker].port,
                            "edge": edge(fid, ap.actor, d_fid, dp.actor),
                        } for dp in downs],
                    }
                fspec = {
                    "fid": fid, "actor": ap.actor,
                    "plan": plan_json,
                    "id_start": spec["id_start"] + fid * _SPAN_ID_STRIDE,
                    "shard_base": fid * 16,
                    "is_root": frag.is_root,
                    # owned vnode range: stateful executors reload (and
                    # the root MV serves scans for) ONLY this range, so
                    # placement == routing survives live migrations
                    "vnodes": [ap.vnode_start, ap.vnode_end],
                    "inputs": inputs, "output": out,
                }
                if import_refs:
                    refs = import_refs.get((fid, ap.actor))
                    if refs:
                        fspec["import_refs"] = list(refs)
                frag_specs[ap.worker].append(fspec)
        reqs = {}
        for w in spec["workers"]:
            reqs[w.worker_id] = {
                "type": "create_fragments", "name": name,
                "defs": spec["defs"],
                "mv_table_id": spec["mv_table_id"],
                "id_stride": _SPAN_ID_STRIDE,
                "permits": cfg.exchange_permits,
                "config": {
                    "chunk_capacity": cfg.chunk_capacity,
                    "agg_table_capacity": cfg.agg_table_capacity,
                    "join_key_capacity": cfg.join_key_capacity,
                    "join_bucket_width": cfg.join_bucket_width,
                    "topn_table_capacity": cfg.topn_table_capacity,
                    "agg_hbm_budget": cfg.agg_hbm_budget,
                },
                "chunks_per_tick": self.chunks_per_tick,
                "chunk_capacity": self.source_chunk_capacity,
                "seed": self.seed,
                "fault": dataclasses.asdict(self.fault),
                "fresh": fresh,
                "fragments": frag_specs[w.worker_id],
            }
            if recover_at is not None:
                reqs[w.worker_id]["recover_at"] = recover_at
        return reqs

    def _span_decided_epoch(self, name: str, workers) -> int:
        """The cluster-decided checkpoint cut for a spanning job: the MAX
        committed epoch across its participants. A commit frame is only
        sent after EVERY participant durably prepared the epoch, so any
        participant behind the max still holds that epoch prepared and
        rolls forward — all stores settle on one consistent cut
        (phase-2 asymmetry healed; reference: meta-owned atomic Hummock
        versions make this a non-problem in the reference)."""
        committed = []
        for w in workers:
            resp = self._await(w.request({"type": "job_epochs",
                                          "name": name}))
            committed.append(int(resp.get("committed", 0)))
        return max(committed) if committed else 0

    def _recover_spanning_job(self, name: str) -> list[str]:
        """Scoped recovery of a SPANNING job: respawn dead participants,
        drop the surviving fragments WITHOUT touching durable state, and
        re-deploy the same placement — every fragment reloads from its
        own worker's store at the last committed checkpoint and the
        deterministic sources replay the gap (reference: recovery.rs:110
        scoped to one job's actor set; unrelated jobs on the same workers
        keep running untouched)."""
        from .remote import SpanningJob, WorkerDied
        self._drain_inflight()
        # fence the dead incarnation FIRST: frames the rebuilt graph
        # sends carry the new generation, and anything still in flight
        # from the old one (delayed acks, stale commits) is refused
        self._bump_generation()
        spec = self._spanning_specs[name]
        job = self.jobs.pop(name, None)
        if job is not None:
            self._await(job.stop())
            self._unsubscribe_job(job)
            self.meta.deregister_job(name)
            self._dead_jobs.discard(name)
        for w in spec["workers"]:
            if w.dead:
                w.respawn(self._await)
                self._worker_span_ack.pop(w.worker_id, None)
                self.meta.register_compute(w.worker_id, "127.0.0.1",
                                           w.port)
        for w in spec["workers"]:
            try:
                self._await(w.request(
                    {"type": "drop_job", "name": name,
                     "epoch": self._injected + 1, "drop_state": False}))
            except (WorkerDied, RuntimeError):
                pass                 # fresh respawn or wedged: no-op
        decided = self._span_decided_epoch(name, spec["workers"])
        reqs = self._span_requests(name, spec, fresh=False,
                                   recover_at=decided)
        for w in spec["workers"]:
            self._await(w.request(reqs[w.worker_id]))
        self.jobs[name] = SpanningJob(name, spec["workers"])

        async def _init_all() -> None:
            await asyncio.gather(*(w.init_barrier(name, self.epoch)
                                   for w in spec["workers"]))

        self._await(_init_all())
        self.meta.notifications.notify(
            "recovery", {"jobs": [name], "epoch": self.epoch})
        return [name]

    # ------------------------------------- elastic scaling (live rescale) --

    @_locked
    def rescale(self, name: str, parallelism: int) -> dict:
        """Change one MV job's fragment parallelism (docs/scaling.md).

        * **spanning jobs** — LIVE vnode migration: pause the graph at an
          aligned checkpoint barrier, hand off only the vnode ranges
          whose owner changes as state refs (handoff segments on shared
          storage), fence the old incarnation by generation, redeploy
          with rewired exchange edges — no full-session restart, worker
          processes stay up (reference: scale.rs:657).
        * **session-local jobs** — no vnode-mapped placement exists;
          delegates to ``reschedule`` (quiesce + rebuild from durable
          state under the new ``fragment_parallelism``).
        * **whole-job remote placements** — refused loudly
          (``RescaleUnsupported``): a round-robined whole job has no
          fragments to migrate (VERDICT #78 made this failure explicit
          instead of silent).
        """
        from ..meta.rescale import RescaleUnsupported
        if name in self._spanning_specs:
            return self._rescale_spanning(name, parallelism)
        if name in self._remote_specs:
            raise RescaleUnsupported(
                f"MV {name!r} is placed WHOLE-JOB on worker "
                f"{self._remote_specs[name]['worker'].worker_id}: "
                "round-robined whole-job placements carry no vnode-mapped "
                "fragments, so there is nothing to migrate. DROP and "
                "re-CREATE it under a span-capable shape (sourced plan, "
                ">= 2 workers, fragment_parallelism >= 2) to make it "
                "rescalable — see docs/scaling.md")
        if name not in self.catalog.mvs:
            raise SqlError(f"materialized view {name!r} not found "
                           "(only MV jobs rescale)")
        cfg = dataclasses.replace(self.config,
                                  fragment_parallelism=max(1, parallelism))
        self.reschedule(name, config=cfg)
        return {"job": name, "mode": "local-rebuild",
                "parallelism": max(1, parallelism), "moved_vnodes": 0}

    def _rescale_spanning(self, name: str, parallelism: int) -> dict:
        """Diff-based live vnode migration of one spanning job.

        Protocol (every step under the API lock, the session being the
        barrier conductor — "paused" means no barrier can be injected
        while this runs):

        1. **aligned barrier**: drain in-flight epochs + checkpoint
           flush — every fragment's state durably committed at one cut
           ``E`` on its own worker;
        2. **plan**: ``meta.rescale.plan_rescale`` computes the new
           placement (ranges == the ``vnode_to_shard`` routing) and the
           minimal ``VnodeMove`` set;
        3. **fence**: bump the session generation — the pre-rescale
           incarnation can neither ack barriers nor commit;
        4. **hand off**: each moving range's committed rows are exported
           by the (still-live) source actors as handoff segments on
           shared storage; only REFS travel to the destinations;
        5. **pause actors**: stop + drop the job's actors on every old
           worker (``drop_state=False`` — processes stay up, durable
           state stays put);
        6. **redeploy**: ``create_fragments`` under the new placement
           with fresh exchange channels; destinations import their refs
           before building, every actor reloads only its owned range;
        7. **commit**: persist the placement (``commit_placement``) —
           the rollback/roll-forward watershed — then init barriers.

        A failure before step 7 ROLLS BACK (redeploy the old placement
        from the untouched durable cut); after it, failures ROLL FORWARD
        through the ordinary scoped recovery under the new placement.
        """
        import time as _time

        from ..meta.rescale import RescaleUnsupported, plan_rescale
        if self._in_rescale:
            raise RuntimeError("a rescale is already in flight")
        spec = self._spanning_specs[name]
        graph, old_placement = spec["graph"], spec["placement"]
        for w in self.workers:
            self.meta.cluster.set_compute_state(
                w.worker_id, "DOWN" if w.dead else "RUNNING")
        worker_ids = [n.worker_id
                      for n in self.meta.cluster.live_compute_nodes()]
        plan = plan_rescale(name, graph, old_placement, worker_ids,
                            parallelism)
        new_par = max(len(a) for a in plan.new.actors.values())
        if not plan.moves and plan.new.to_json() == old_placement.to_json():
            return {"job": name, "mode": "noop", "parallelism": new_par,
                    "moved_vnodes": 0, "pause_ms": 0.0}
        by_id = {w.worker_id: w for w in self.workers}
        missing = [wid for wid in plan.new.workers() if wid not in by_id]
        if missing:
            raise RescaleUnsupported(
                f"rescale of {name!r} needs workers {missing} which this "
                "session does not run")
        # 1. aligned barrier: quiesce + checkpoint-commit the cut
        t0 = _time.perf_counter()
        self._in_rescale = True
        try:
            return self._rescale_spanning_locked(name, spec, plan,
                                                 old_placement, by_id,
                                                 new_par, t0)
        finally:
            self._in_rescale = False

    def _rescale_spanning_locked(self, name: str, spec: dict, plan,
                                 old_placement, by_id: dict,
                                 new_par: int, t0: float) -> dict:
        import os as _os
        import time as _time

        from ..common.failpoint import fail_point
        from ..meta.rescale import commit_placement
        from .remote import SpanningJob, WorkerDied
        self._drain_inflight()
        self.flush()
        decided = self._span_decided_epoch(name, spec["workers"])
        # 3. fence the pre-rescale incarnation
        self._bump_generation()
        old_workers = list(spec["workers"])
        try:
            # 4. export the moving ranges as state refs on shared storage
            handoff_dir = _os.path.join(self._workers_base, "handoff",
                                        name, f"g{self._generation}")
            import_refs: dict[tuple, list] = {}
            for (src_wid, fid), moves in sorted(
                    plan.moves_by_source().items()):
                resp = self._await(by_id[src_wid].request({
                    "type": "rescale_export", "name": name,
                    "fragment": fid,
                    "ranges": [[m.vnode_start, m.vnode_end]
                               for m in moves],
                    "dir": handoff_dir}))
                for ref, m in zip(resp["refs"], moves):
                    import_refs.setdefault(
                        (fid, m.to_actor), []).append(ref["path"])
            fail_point("rescale.migrate")
            # 5. pause: tear the actors down in place (no process restart)
            job = self.jobs.pop(name, None)
            if job is not None:
                self._await(job.stop())
                self._unsubscribe_job(job)
                self.meta.deregister_job(name)
                self._dead_jobs.discard(name)
            for w in old_workers:
                self._await(w.request(
                    {"type": "drop_job", "name": name,
                     "epoch": self._injected + 1, "drop_state": False}))
            # 6. redeploy under the new placement, refs riding along
            spec["placement"] = plan.new
            spec["workers"] = [by_id[wid] for wid in plan.new.workers()]
            spec["root_worker"] = by_id[plan.new.root_worker]
            reqs = self._span_requests(name, spec, fresh=False,
                                       recover_at=decided,
                                       import_refs=import_refs)
            for w in spec["workers"]:
                self._await(w.request(reqs[w.worker_id]))
        except (WorkerDied, RuntimeError, OSError) as e:
            self._rollback_rescale(name, spec, old_placement, old_workers,
                                   by_id)
            raise RuntimeError(
                f"rescale of {name!r} failed mid-migration; the job was "
                f"rolled back to its previous placement") from e
        # 7. COMMIT: the new placement becomes authoritative — failures
        # from here roll FORWARD via scoped recovery under it
        commit_placement(self.meta, plan.new)
        # cached serving runners are bound to the PRE-rescale host set
        # (remote two-phase tasks name workers + vnode slices): drop
        # them — re-planning against the new placement is the only
        # correct re-execution (frontend/serving.py)
        self._serving.invalidate_catalog()
        mv = self.catalog.mvs.get(name)
        if mv is not None:
            mv.span_workers = plan.new.workers()  # type: ignore[attr-defined]
        self.jobs[name] = SpanningJob(name, spec["workers"])
        self._pending_mutation = Mutation(MutationKind.UPDATE, name)
        fail_point("rescale.commit")

        async def _init_all() -> None:
            await asyncio.gather(*(w.init_barrier(name, self.epoch)
                                   for w in spec["workers"]))

        try:
            self._await(_init_all())
        except (WorkerDied, RuntimeError):
            # committed: the new placement is truth — roll forward
            self._recover_spanning_job(name)
        pause_ms = round((_time.perf_counter() - t0) * 1e3, 3)
        out = {
            "job": name, "mode": "live-migration",
            "parallelism": new_par, "epoch": decided,
            "moved_vnodes": plan.moved_vnodes,
            "moved_ranges": [
                {"fragment": m.fragment_id, "vnodes":
                 [m.vnode_start, m.vnode_end],
                 "from_worker": m.from_worker, "to_worker": m.to_worker}
                for m in plan.moves],
            "workers": plan.new.workers(),
            "pause_ms": pause_ms,
        }
        self._rescale_stats["migrations"] += 1
        self._rescale_stats["moved_vnodes"] += plan.moved_vnodes
        self._rescale_stats["last"] = out
        self._rescale_stats["history"].append(
            {k: out[k] for k in ("job", "parallelism", "moved_vnodes",
                                 "pause_ms", "epoch")})
        del self._rescale_stats["history"][:-16]
        self.meta.notifications.notify(
            "rescale", {"job": name, "parallelism": new_par,
                        "moved_vnodes": plan.moved_vnodes})
        return out

    def _rollback_rescale(self, name: str, spec: dict, old_placement,
                          old_workers: list, by_id: dict) -> None:
        """Migration failed before the placement commit: the OLD
        placement is still authoritative. Drop whatever the attempt
        half-deployed on ANY worker (a new worker's orphan fragments
        would otherwise wedge its barrier collection forever), restore
        the spec, and redeploy the old layout from the untouched durable
        cut via the scoped-recovery machinery. Imported handoff rows a
        destination already committed are benign leftovers: every reload
        and scan filters to the actor's OWNED vnode range."""
        from .remote import WorkerDied
        spec["placement"] = old_placement
        spec["workers"] = old_workers
        spec["root_worker"] = by_id[old_placement.root_worker]
        for w in self.workers:
            if w.dead:
                continue
            try:
                self._await(w.request(
                    {"type": "drop_job", "name": name,
                     "epoch": self._injected + 1, "drop_state": False}))
            except (WorkerDied, RuntimeError):
                pass
        self._serving.invalidate_catalog()
        try:
            self._recover_spanning_job(name)
        except Exception as e2:
            raise RuntimeError(
                f"rescale of {name!r} failed AND the rollback redeploy "
                "failed; durable state is intact — restart the session "
                "to restore the job") from e2

    def _create_sink(self, stmt: A.CreateSink) -> list:
        """CREATE SINK: a stream job whose terminal is a SinkExecutor over
        a log store instead of a MaterializeExecutor (reference:
        src/stream/src/executor/sink.rs:38; log store
        common/log_store/mod.rs:57-168)."""
        if stmt.if_not_exists and stmt.name in self.catalog.sinks:
            return []
        self._drain_inflight()
        self.catalog._check_free(stmt.name)
        from ..connector.sinks import build_sink
        from ..stream.sink import PROGRESS_SCHEMA, SinkExecutor, log_table_schema
        connector = str(stmt.with_options.get("connector", "blackhole"))
        n_feeds0 = len(self.feeds)
        n_bf0 = len(self.backfills)
        scan_leaf_queues: list[tuple[list, StreamJob]] = []
        ctx_tids: tuple = ()
        actors: list = []
        if stmt.from_name is not None:
            kind, obj = self.catalog.resolve_relation(stmt.from_name)
            if kind == "source":
                raise SqlError("CREATE SINK FROM a source is not supported; "
                               "use CREATE SINK ... AS SELECT")
            if self._mv_worker(stmt.from_name) is not None:
                raise SqlError(
                    f"CREATE SINK FROM worker-hosted MV "
                    f"{stmt.from_name!r} is not supported yet")
            up_job = self.jobs[stmt.from_name]
            q = QueueSource(obj.schema)
            up_job.bus.subscribe(q)
            pipeline: Executor = q
            schema = obj.schema
            # visible = non-hidden columns (pk-less tables carry _row_id)
            n_visible = getattr(
                obj, "n_visible",
                sum(1 for f in schema if not f.name.startswith("_")))
            queues = [q]
            init_msgs = [(q, [])]   # snapshot decided after tid allocation
            scan_leaf_queues.append((init_msgs[0][1], up_job))
        else:
            (plan, pipeline, ctx, queues, init_msgs,
             scan_leaf_queues) = self._build_query_pipeline(stmt.query)
            ctx_tids = tuple(ctx.state_table_ids)
            actors = ctx.actors
            schema = plan.schema
            n_visible = sum(1 for f in schema if not f.name.startswith("_"))
        log_tid = self.catalog.next_table_id()
        prog_tid = self.catalog.next_table_id()
        if stmt.from_name is not None and not self._recovering:
            init_msgs[0][1].extend(up_job.snapshot_messages(
                Barrier.new(self.epoch), self.source_chunk_capacity))
        # recovery in the created-but-never-checkpointed window: state
        # tables (incl. the sink's own log/progress) are all empty — re-run
        # the backfill snapshot (same rule as MVs)
        self._maybe_rebackfill(ctx_tids + (log_tid, prog_tid),
                               scan_leaf_queues)
        visible_schema = Schema(tuple(schema)[:n_visible])
        sink = build_sink(connector, dict(stmt.with_options), visible_schema,
                          fault=self.fault)
        # delivery decoupling knobs: per-sink WITH options override the
        # session fault config (reference: sink decouple + retry params)
        opts = stmt.with_options
        ex = SinkExecutor(
            pipeline, sink,
            StateTable(self.store, log_tid, log_table_schema(schema), [0, 1]),
            StateTable(self.store, prog_tid, PROGRESS_SCHEMA, [0]),
            n_visible=n_visible, recovering=self._recovering,
            retry_policy=self.fault.sink_retry_policy(),
            degrade_after=int(opts.get("sink.degrade_after",
                                       self.fault.sink_degrade_after)),
            log_cap_rows=int(opts.get("sink.log_cap_rows",
                                      self.fault.sink_log_cap_rows)))
        sdef = SinkDef(stmt.name, schema, connector, dict(stmt.with_options),
                       from_name=stmt.from_name or "", table_id=log_tid,
                       progress_table_id=prog_tid)
        sdef.state_table_ids = ctx_tids + (prog_tid,)  # type: ignore[attr-defined]
        self.catalog_writer.add_sink(sdef)
        for f in self.feeds[n_feeds0:]:
            f.job = stmt.name
        for b in self.backfills[n_bf0:]:
            b.job = stmt.name
        job = StreamJob(stmt.name, ex, queues, actors=actors)
        self.jobs[stmt.name] = job
        job.start(self.loop)
        self._pending_mutation = Mutation(MutationKind.ADD, stmt.name)
        for q, init in init_msgs:
            for m in init:
                q.push(m)
            q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))
        return []

    @_locked
    def reschedule(self, name: str, config: Optional[BuildConfig] = None):
        """Online rescale of one MV job: rebuild its executors under a new
        BuildConfig (typically a different ``mesh``) from durable state at
        a quiesced checkpoint boundary, without losing a row.

        Reference: the scale controller's Reschedule command
        (src/meta/src/stream/scale.rs:657, barrier/command.rs:48-60) —
        actors are rebuilt with new vnode mappings and state re-read from
        shared storage; here the "vnode mapping" is the mesh sharding of
        the rebuilt executors and the shared storage is the state store.
        """
        mv = self.catalog.mvs.get(name)
        if mv is None:
            raise SqlError(f"materialized view {name!r} not found "
                           "(only MV jobs reschedule)")
        if self._mv_worker(name) is not None:
            raise SqlError(
                "reschedule of a worker-hosted MV is not supported; "
                "spanning jobs rescale LIVE via Session.rescale / "
                "`ctl cluster rescale` (docs/scaling.md), whole-job "
                "placements must be dropped and re-created")
        self.flush()                       # all state durable + quiesced
        old_job = self.jobs[name]
        self._await(old_job.stop())
        self._unsubscribe_job(old_job)     # upstreams stop feeding dead queues
        # this job's source feeds are recreated (sought to their offsets)
        live = [f for f in self.feeds if f.job != name]
        self.feeds = live
        self.backfills = [b for b in self.backfills if b.job != name]
        id0, id1 = mv.table_id_range  # type: ignore[attr-defined]
        ids = iter(range(id0, id1))
        saved_alloc = self.catalog.next_table_id
        saved_recovering = self._recovering
        saved_config = self.config

        def replay_id() -> int:
            try:
                return next(ids)
            except StopIteration:
                raise RuntimeError(
                    "reschedule id replay diverged from the original build")

        self.catalog.next_table_id = replay_id  # type: ignore[assignment]
        self._recovering = True      # reload state, seek sources, no snapshot
        if config is not None:
            self.config = config
        n_feeds0 = len(self.feeds)
        n_bf0 = len(self.backfills)
        bus_subs0 = {n: list(j.bus.subscribers)
                     for n, j in self.jobs.items()}
        rollback_error: Optional[BaseException] = None
        try:
            try:
                (plan, pipeline, ctx, queues, init_msgs,
                 _slq) = self._build_query_pipeline(mv.query_ast)  # type: ignore[attr-defined]
                mv_table_id = self.catalog.next_table_id()
            except BaseException as e1:
                # the new config failed to build (incl. interrupts —
                # rollback is fast): roll back to the original config over
                # the same durable state. A stopped job left in self.jobs
                # would hang every later barrier. Undo the failed build's
                # feed/subscription side effects first.
                rollback_error = e1
                self.feeds = self.feeds[:n_feeds0]
                self.backfills = self.backfills[:n_bf0]
                for n, subs in bus_subs0.items():
                    self.jobs[n].bus.subscribers = list(subs)
                self.config = saved_config
                ids = iter(range(id0, id1))
                try:
                    (plan, pipeline, ctx, queues, init_msgs,
                     _slq) = self._build_query_pipeline(mv.query_ast)  # type: ignore[attr-defined]
                    mv_table_id = self.catalog.next_table_id()
                except BaseException as e2:
                    # config-independent failure: even the original config
                    # no longer builds. Deregister the job AND everything
                    # transitively fed by it (barrier-starved otherwise);
                    # durable state + catalog remain — a restart's
                    # recovery replay restores the jobs.
                    self.feeds = self.feeds[:n_feeds0]
                    self.backfills = self.backfills[:n_bf0]
                    for n, subs in bus_subs0.items():
                        self.jobs[n].bus.subscribers = list(subs)
                    self.jobs.pop(name, None)
                    self._pop_downstreams_of(old_job)
                    raise RuntimeError(
                        f"reschedule of {name!r} failed and the rollback "
                        "rebuild failed too; the job (and its downstream "
                        "MVs) are stopped — state is durable, restart the "
                        "session to restore them") from e2
            mat = MaterializeExecutor(
                pipeline,
                StateTable(self.store, mv_table_id, plan.schema,
                           list(plan.pk)))
        finally:
            self.catalog.next_table_id = saved_alloc  # type: ignore[assignment]
            self._recovering = saved_recovering
            self.config = saved_config
        for f in self.feeds[n_feeds0:]:
            f.job = name
        for b in self.backfills[n_bf0:]:
            b.job = name
        job = StreamJob(name, mat, queues, actors=ctx.actors)
        job.bus.subscribers = old_job.bus.subscribers   # downstreams keep
        self.jobs[name] = job
        job.start(self.loop)
        # the next barrier announces the config change (reference:
        # Mutation::Update on the reschedule barrier)
        self._pending_mutation = Mutation(MutationKind.UPDATE, name)
        for q, init in init_msgs:
            for m in init:
                q.push(m)
            q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))
        if rollback_error is not None:
            # the job is healthy again under the SESSION DEFAULT config,
            # but the requested reschedule did NOT happen — persist the
            # layout the job actually runs now (an earlier successful
            # rescale's log entry would otherwise resurrect on restart a
            # layout the live session no longer has), then surface it
            if self.data_dir is not None:
                from .build import config_to_json
                self.store.log.log_ddl(  # type: ignore[attr-defined]
                    f"-- reschedule {name} {config_to_json(saved_config)}")
            raise RuntimeError(
                f"reschedule of {name!r} failed; the job was restored "
                "with its original config") from rollback_error
        # persist the rescale only once the rebuild SUCCEEDED: the config's
        # durable form (mesh topology, not live device handles) goes in the
        # DDL log; recovery replays the CREATE under this config so a
        # restart keeps its layout (reference: persisted vnode mappings,
        # stream/scale.rs:657)
        if self.data_dir is not None:
            from .build import config_to_json
            cfg_json = config_to_json(config if config is not None
                                      else saved_config)
            self.store.log.log_ddl(  # type: ignore[attr-defined]
                f"-- reschedule {name} {cfg_json}")

    def _pop_downstreams_of(self, job: StreamJob) -> None:
        """Remove jobs transitively fed by ``job``'s bus (they would wait
        forever for barriers a stopped upstream can never send). Full
        teardown per job: stop the task, unsubscribe its queues from live
        buses, drop its feeds and barrier queues."""
        sub_queues = set(map(id, job.bus.subscribers))
        for n, j in list(self.jobs.items()):
            if any(id(q) in sub_queues for q in j.sources):
                self.jobs.pop(n, None)
                self._teardown_job(n, j)
                self._pop_downstreams_of(j)

    def _teardown_job(self, name: str, j: StreamJob) -> None:
        """Full per-job teardown shared by drop-downstreams and scoped
        recovery: stop the task (and fragment actors), unsubscribe its
        queues from live buses, drop feeds/backfills/barrier queues, close
        its sink, deregister its worker."""
        sink = getattr(j.pipeline, "sink", None)
        if sink is not None:
            sink.close()
        self._await(j.stop())
        self._unsubscribe_job(j)
        self.feeds = [f for f in self.feeds if f.job != name]
        self.backfills = [b for b in self.backfills if b.job != name]
        self._table_queues.pop(name, None)
        self.meta.deregister_job(name)
        self._dead_jobs.discard(name)

    def sink_of(self, name: str):
        """The live Sink instance of a sink job (inspection/testing)."""
        job = self.jobs.get(name)
        return getattr(job.pipeline, "sink", None) if job else None

    @_locked
    def resume_sink(self, name: str) -> None:
        """Re-arm delivery on a DEGRADED sink job (the ALTER SINK ...
        RESUME shape): the logged backlog drains at the next barrier.
        No-op on a healthy sink."""
        if name not in self.catalog.sinks:
            raise SqlError(f"sink {name!r} not found")
        job = self.jobs.get(name)
        resume = getattr(job.pipeline, "resume", None) if job else None
        if resume is None:
            raise SqlError(f"sink {name!r} has no live delivery loop")
        resume()

    # ------------------------------------------------- scoped job recovery --

    def kill_job(self, name: str) -> None:
        """Chaos/test hook: hard-kill a job's actor task mid-flight (the
        madsim node-kill analogue). Nothing is cleaned up here — detection
        is the heartbeat detector's duty and restoration is
        ``_recover_job``'s (reference: madsim kill,
        src/tests/simulation/src/cluster.rs:498-510)."""
        job = self.jobs[name]
        if job._task is not None:
            job._task.cancel()

    def _job_state_ids(self, name: str) -> list[int]:
        """Every state-table id a job (MV / table / sink) writes."""
        mv = self.catalog.mvs.get(name)
        if mv is not None:
            rng = getattr(mv, "table_id_range", None)
            if rng is not None:
                return list(range(*rng))
        obj = (self.catalog.tables.get(name)
               or self.catalog.sinks.get(name))
        if obj is None:
            return []
        ids = [obj.table_id]
        ids += [tid for tid in getattr(obj, "state_table_ids", ())
                if tid >= 0]
        prog = getattr(obj, "progress_table_id", -1)
        if prog >= 0:
            ids.append(prog)
        return ids

    def _downstream_names(self, job: StreamJob) -> list[str]:
        """Names of jobs transitively fed by ``job``'s bus."""
        sub_queues = set(map(id, job.bus.subscribers))
        out: list[str] = []
        for n, j in self.jobs.items():
            if any(id(q) in sub_queues for q in j.sources):
                if n not in out:
                    out.append(n)
                    for m in self._downstream_names(j):
                        if m not in out:
                            out.append(m)
        return out

    def _recover_job(self, name: str) -> list[str]:
        """Scoped recovery: rebuild a dead job (and its transitive
        downstream MVs) from durable state at the last committed epoch,
        WITHOUT restarting the session or touching unrelated jobs.

        Mirrors the reference's recovery sequence
        (src/meta/src/barrier/recovery.rs:110 — clean dirty state, rebuild
        actors, re-seek sources) scoped to one job subtree: torn staged
        writes are discarded, executors reload state tables at the last
        commit, and source readers seek their checkpointed offsets, so the
        rebuilt subtree replays exactly the rows lost since that commit.
        Only MV jobs are scoped-recoverable; a subtree containing a table
        or sink job falls back to requiring a session restart (state is
        durable). Returns the recovered subtree's job names (the caller
        dedups overlapping recovery requests with it)."""
        if name in self._spanning_specs:
            return self._recover_spanning_job(name)
        if name in self._remote_specs:
            return self._recover_remote_job(name)
        job = self.jobs.get(name)
        if job is None:
            return [name]
        # drain pipelined epochs first: the rebuilt jobs will only see
        # barriers from the NEXT injection on, so nothing may stay in
        # flight across the rebuild (dead jobs are tolerated by collect)
        self._drain_fused_pipeline()
        self._drain_inflight()
        subtree = [name] + self._downstream_names(job)
        non_mv = [n for n in subtree if n not in self.catalog.mvs]
        if non_mv:
            raise RuntimeError(
                f"job {name!r} died and its subtree {subtree} contains "
                f"non-MV jobs {non_mv}; scoped recovery covers MV jobs — "
                "restart the session to restore from durable state")
        for n in subtree:
            j = self.jobs.pop(n, None)
            if j is None:
                continue
            self._teardown_job(n, j)
            mv = self.catalog.mvs[n]
            rng = getattr(mv, "table_id_range", None)
            if rng is not None:
                self.store.discard_pending_tables(range(*rng))
        # rebuild in creation order (upstream MVs before their readers)
        for n in [m for m in self.catalog.mvs if m in subtree]:
            self._rebuild_mv_job(n)
        self.meta.notifications.notify(
            "recovery", {"jobs": subtree, "epoch": self.epoch})
        return subtree

    def _rebuild_mv_job(self, name: str) -> None:
        """Rebuild one MV job from its catalog definition over existing
        durable state (the reschedule rebuild core, without a config
        change): table ids replay deterministically, ``_recovering`` makes
        executors reload state instead of snapshotting upstreams, and
        source readers seek their checkpointed offsets."""
        mv = self.catalog.mvs[name]
        id0, id1 = mv.table_id_range  # type: ignore[attr-defined]
        ids = iter(range(id0, id1))
        saved_alloc = self.catalog.next_table_id
        saved_recovering = self._recovering

        def replay_id() -> int:
            try:
                return next(ids)
            except StopIteration:
                raise RuntimeError(
                    "recovery id replay diverged from the original build")

        self.catalog.next_table_id = replay_id  # type: ignore[assignment]
        self._recovering = True
        n_feeds0 = len(self.feeds)
        n_bf0 = len(self.backfills)
        try:
            (plan, pipeline, ctx, queues, init_msgs,
             _slq) = self._build_query_pipeline(mv.query_ast)  # type: ignore[attr-defined]
            mv_table_id = self.catalog.next_table_id()
            mat = MaterializeExecutor(
                pipeline,
                StateTable(self.store, mv_table_id, plan.schema,
                           list(plan.pk)))
        finally:
            self.catalog.next_table_id = saved_alloc  # type: ignore[assignment]
            self._recovering = saved_recovering
        for f in self.feeds[n_feeds0:]:
            f.job = name
        for b in self.backfills[n_bf0:]:
            b.job = name
        job = StreamJob(name, mat, queues, actors=ctx.actors)
        self.jobs[name] = job
        job.start(self.loop)
        for q, init in init_msgs:
            for m in init:
                q.push(m)
            q.push(Barrier.new(self.epoch))
        self._await(job.wait_barrier(self.epoch))

    def _stream_leaf(self, leaf):
        """-> (executor, session_driven_queue_or_None, init_messages)"""
        if isinstance(leaf, PSource):
            src_def = leaf.source
            q = QueueSource(src_def.schema)
            reader = self._connector_reader(src_def)
            start_seq = 0
            if reader is None:
                self.feeds.append(_SourceFeed(q, lambda: None))
            else:
                # split-state table: (split_id, next_offset), persisted on
                # checkpoint epochs, sought on recovery
                from ..common.types import INT64, VARCHAR
                st = StateTable(
                    self.store, self.catalog.next_table_id(),
                    Schema((Field("split_id", VARCHAR),
                            Field("next_offset", INT64))), [0])
                if self._recovering:
                    offsets = {
                        VARCHAR.to_python(r[0]): int(r[1])
                        for r in st.scan_all()}
                    if offsets:
                        reader.seek(offsets)
                        # row ids must continue above any id assigned
                        # before the crash (pk collisions in downstream
                        # materialized state otherwise)
                        start_seq = reader.rows_emitted()
                self.feeds.append(_SourceFeed(
                    q, reader.next_chunk, reader=reader, state_table=st))
            ex: Executor = _RowIdAppendSource(q, leaf.schema)
            ex = RowIdGenExecutor(ex, row_id_index=leaf.row_id_index,
                                  shard_id=self._alloc_shard(),
                                  start_seq=start_seq)
            if src_def.watermark is not None:
                col, delay = src_def.watermark
                ex = WatermarkFilterExecutor(ex, time_col=col, delay=delay)
            return ex, q, []
        if isinstance(leaf, (PTableScan, PMvScan)):
            name = leaf.table.name if isinstance(leaf, PTableScan) else leaf.mv.name
            if self._mv_worker(name) is not None:
                raise SqlError(
                    f"{name!r} is a worker-hosted MV; jobs consuming it "
                    "must also be worker-hosted (not supported yet)")
            up_job = self.jobs[name]
            q = QueueSource(leaf.schema)
            up_job.bus.subscribe(q)
            # CONCURRENT backfill (reference: executor/backfill.rs:48-69):
            # the upstream's durable table is snapshot-read in bounded
            # batches across barriers while live deltas keep flowing —
            # creating an MV over a huge upstream never stalls an epoch.
            # The progress table makes it crash-resumable; on recovery the
            # persisted cursor/done flag decides (done => pass-through,
            # matching the old recovered-state semantics; empty progress
            # after a create-but-never-checkpointed crash => fresh
            # backfill, subsuming _maybe_rebackfill for scan leaves).
            from ..stream.backfill import BackfillExecutor
            from ..stream.backfill import PROGRESS_SCHEMA as BF_PROGRESS
            prog = StateTable(self.store, self.catalog.next_table_id(),
                              BF_PROGRESS, [0])
            meta = self.meta

            def report(p, _name=name):
                meta.notifications.notify(
                    "backfill", {"job": _name, **p})

            batch_rows = (self.config.backfill_batch_rows
                          or max(self.source_chunk_capacity * 4, 4096))
            bf = BackfillExecutor(
                q, up_job.table, batch_rows=batch_rows,
                chunk_capacity=self.source_chunk_capacity,
                progress_table=prog, on_progress=report)
            self.backfills.append(_BackfillRef(bf))
            # session does NOT drive this queue; upstream bus does. The
            # init barrier is pushed at creation (empty init list).
            return bf, q, []
        if isinstance(leaf, PValues):
            q = QueueSource(leaf.schema)
            chunk = _values_chunk(leaf)
            return q, q, [chunk]
        raise PlanError(f"cannot stream {type(leaf).__name__}")

    def _connector_reader(self, src: SourceDef):
        """Instantiate the connector's SplitReader via the shared factory
        (connector/factory.py); None for declared-schema sources fed only
        by tests."""
        from ..connector.factory import ConnectorError, make_reader
        try:
            return make_reader(src.connector, src.options, src.schema,
                               self.source_chunk_capacity, self.seed,
                               fault=self.fault)
        except ConnectorError as e:
            raise SqlError(str(e)) from None

    def _unsubscribe_job(self, job: StreamJob) -> None:
        """Remove a stopped job's input queues from every upstream bus —
        otherwise upstreams keep pushing into dead queues forever."""
        for other in self.jobs.values():
            if other is job:
                continue
            for q in job.sources:
                other.bus.unsubscribe(q)

    def _drop(self, stmt: A.DropStatement) -> list:
        if stmt.kind == "index":
            ix = self.catalog.indexes.get(stmt.name)
            if ix is None:
                if stmt.if_exists:
                    return []
                raise SqlError(f"index {stmt.name!r} not found")
            self.catalog_writer.drop("index", stmt.name, False)
            # the arrangement MV goes with it
            return self._drop(dataclasses.replace(
                stmt, kind="materialized_view", name=ix.mv_name,
                if_exists=True))
        # dropping a base relation cascades to its indexes — a dangling
        # index would keep serving the DROPPED table's rows to lookups
        for ix_name in [n for n, ix in self.catalog.indexes.items()
                        if ix.table == stmt.name]:
            self._drop(dataclasses.replace(
                stmt, kind="index", name=ix_name, if_exists=True))
        # a deferred fused flush must resolve BEFORE membership changes
        # restack the job axis (and before its chunks would be lost)
        self._drain_fused_pipeline()
        self._drain_inflight()
        # free the object's durable state (tombstoned in the manifest so
        # recovery and compaction skip it)
        obj = (self.catalog.tables.get(stmt.name)
               or self.catalog.mvs.get(stmt.name)
               or self.catalog.sinks.get(stmt.name))
        existed = self.catalog_writer.drop(stmt.kind, stmt.name, stmt.if_exists)
        if existed:
            # the job's source feeds die with it: free their split-state
            # tables (collect BEFORE teardown filters them away)
            dead_feeds = [f for f in self.feeds if f.job == stmt.name]
            group = self._cosched.jobs.get(stmt.name)
            self._cosched.remove(stmt.name)
            if group is not None and group.n_jobs == 0 and group.epochs_run:
                # the job emptied its group: its epochs leave the live
                # registry, so retire them for the per_epoch ratio
                qn = "build_group_epoch.<locals>.coscheduled_epoch"
                self._dispatch_epochs_retired[qn] = \
                    self._dispatch_epochs_retired.get(qn, 0) \
                    + group.epochs_run
            self._cosched_engines.pop(stmt.name, None)
            self._cosched_markers.discard(stmt.name)
            if stmt.name in self._hetero.jobs:
                # dissolve-then-recompile: the member's groups retire
                # their epochs into the compiler ledger; fold it so the
                # per_epoch invariant ratio survives the DROP
                self._hetero.remove(stmt.name)
                self._fold_hetero_retired()
            self._hetero_engines.pop(stmt.name, None)
            self._hetero_markers.discard(stmt.name)
            dead_sf = self._shardfused_engines.pop(stmt.name, None)
            if dead_sf is not None and self._shardfused is not None:
                _states, sf_group = self._shardfused.remove(stmt.name)
                if sf_group is not None and sf_group.n_jobs == 0 \
                        and sf_group.epochs_run:
                    # the job emptied its K×S group: retire its epochs
                    # for the per_epoch invariant ratio, like coschedule
                    qn = ("build_sharded_group_epoch.<locals>"
                          ".sharded_coscheduled_epoch")
                    self._dispatch_epochs_retired[qn] = \
                        self._dispatch_epochs_retired.get(qn, 0) \
                        + sf_group.epochs_run
            self._shardfused_markers.discard(stmt.name)
            if stmt.name in self.jobs:
                job = self.jobs.pop(stmt.name)
                # full shared teardown: also clears _dead_jobs / worker
                # registry — a dropped dead job's name must not poison a
                # future job of the same name
                self._teardown_job(stmt.name, job)
            for f in dead_feeds:
                if f.state_table is not None:
                    self.store.drop_table(f.state_table.table_id)
            spec = self._remote_specs.pop(stmt.name, None)
            if spec is not None and not spec["worker"].dead:
                from .remote import WorkerDied
                try:
                    self._await(spec["worker"].request(
                        {"type": "drop_job", "name": stmt.name,
                         "epoch": self._injected + 1}))
                except (WorkerDied, RuntimeError):
                    pass             # worker gone; its state dir is stale
            span = self._spanning_specs.pop(stmt.name, None)
            if span is not None:
                from .remote import WorkerDied
                self.meta.drop_placement(stmt.name)
                for w in span["workers"]:
                    if w.dead:
                        continue     # its state dir is stale; respawn wipes
                    try:
                        self._await(w.request(
                            {"type": "drop_job", "name": stmt.name,
                             "epoch": self._injected + 1}))
                    except (WorkerDied, RuntimeError):
                        pass
        if existed and obj is not None:
            self.dml.unregister_table(obj.table_id)
            for tid in ((obj.table_id,)
                        + tuple(getattr(obj, "state_table_ids", ()))):
                if tid >= 0:
                    self.store.drop_table(tid)
        return []

    # ----------------------------------------------------------------- DML --

    def _insert(self, stmt: A.Insert) -> list:
        from .catalog import strip_schema
        t = self.catalog.tables.get(strip_schema(stmt.table))
        if t is None:
            raise SqlError(f"table {stmt.table!r} not found")
        binder = ExprBinder(Scope([]))
        data_fields = [f for f in t.schema if f.name != "_row_id"]
        names = [f.name for f in data_fields]
        cols = list(stmt.columns) or names
        rows = []
        for vrow in stmt.rows:
            if len(vrow) != len(cols):
                raise SqlError("INSERT arity mismatch")
            by_name = {}
            for cname, vexpr in zip(cols, vrow):
                lit = binder.bind(vexpr)
                from ..expr.expr import Literal
                if not isinstance(lit, Literal):
                    raise SqlError("INSERT values must be literals")
                by_name[cname] = lit.value
            rows.append(tuple(by_name.get(n) for n in names))
        chunk = make_chunk(Schema(tuple(data_fields)), rows,
                           capacity=max(len(rows), 1))
        self.dml.stage(t.table_id, chunk)
        return []

    def _dml_target(self, name: str):
        """Resolve + preconditions shared by DELETE/UPDATE (reference:
        batch Delete/Update executors via DmlManager)."""
        from .catalog import strip_schema
        t = self.catalog.tables.get(strip_schema(name))
        if t is None:
            raise SqlError(f"table {name!r} not found")
        if t.append_only:
            raise SqlError(f"table {name!r} is APPEND ONLY")
        if len(t.pk) == 1 and t.schema[t.pk[0]].name == "_row_id":
            raise SqlError(
                "DELETE/UPDATE require a declared PRIMARY KEY "
                "(hidden row-id tables are insert-only)")
        # read-your-writes: staged DML must be visible to the match. A
        # plain (non-checkpoint) epoch suffices — materialize ingests into
        # the store's pending view; no durable commit per statement
        if self.dml.has_staged():
            self.tick(generate=False, checkpoint=False)
        self._drain_inflight()
        return t

    def _match_rows(self, t, where) -> list:
        """Physical rows of ``t`` matching ``where`` (vectorized eval)."""
        import numpy as np
        from ..common.chunk import physical_chunk
        table = StateTable(self.store, t.table_id, t.schema, list(t.pk))
        rows = list(table.scan_all())
        if where is None or not rows:
            return rows
        pred = ExprBinder(Scope.of_schema(t.schema)).bind(where)
        chunk = physical_chunk(t.schema, rows, len(rows))
        cond = pred.eval(chunk)
        keep = np.asarray(cond.data & cond.mask)[:len(rows)]
        return [r for r, k in zip(rows, keep) if k]

    def _delete_dml(self, stmt: A.Delete) -> list:
        from ..common.chunk import OP_DELETE, make_chunk
        t = self._dml_target(stmt.table)
        rows = self._match_rows(t, stmt.where)
        if rows:
            chunk = make_chunk(t.schema, rows, ops=[OP_DELETE] * len(rows),
                               capacity=len(rows), physical=True)
            self.dml.stage(t.table_id, chunk)
        return [("DELETE", len(rows))]

    def _update_dml(self, stmt: A.Update) -> list:
        import numpy as np
        from ..common.chunk import (
            OP_UPDATE_DELETE, OP_UPDATE_INSERT, make_chunk, physical_chunk,
        )
        t = self._dml_target(stmt.table)
        names = list(t.schema.names)
        assigns = []
        for col, e in stmt.assignments:
            if col not in names:
                raise SqlError(f"column {col!r} not found")
            assigns.append((names.index(col),
                            ExprBinder(Scope.of_schema(t.schema)).bind(e)))
        rows = self._match_rows(t, stmt.where)
        if rows:
            from ..expr.expr import cast as _cast
            chunk = physical_chunk(t.schema, rows, len(rows))
            new_cols = {}
            for idx, e in assigns:
                e2 = (e if e.type == t.schema[idx].type
                      else _cast(e, t.schema[idx].type))
                c = e2.eval(chunk)
                new_cols[idx] = (np.asarray(c.data), np.asarray(c.mask))
            new_rows = []
            for r, old in enumerate(rows):
                new = list(old)
                for idx, _ in assigns:
                    d, m = new_cols[idx]
                    new[idx] = d[r].item() if m[r] else None
                new_rows.append(tuple(new))
            pk_cols = set(t.pk)
            pk_changed = any(idx in pk_cols for idx, _ in assigns)
            if not pk_changed:
                # same-pk updates: adjacent U-/U+ pairs (order-safe — pks
                # are unique within the statement)
                pairs, ops = [], []
                for old, new in zip(rows, new_rows):
                    pairs.extend((tuple(old), new))
                    ops.extend((OP_UPDATE_DELETE, OP_UPDATE_INSERT))
            else:
                # pk-moving updates: sequential pair application could
                # delete a freshly-moved row (SET k = k + 1 over k=1,2).
                # Emit ALL deletes before ALL inserts, and reject
                # duplicate-key outcomes the way a database must.
                from ..common.chunk import OP_DELETE, OP_INSERT
                def pk_of(row):
                    return tuple(row[i] for i in t.pk)
                old_pks = {pk_of(r) for r in rows}
                seen = set()
                table = StateTable(self.store, t.table_id, t.schema,
                                   list(t.pk))
                for nr in new_rows:
                    npk = pk_of(nr)
                    if npk in seen:
                        raise SqlError(
                            f"UPDATE produces duplicate key {npk}")
                    seen.add(npk)
                    if npk not in old_pks and \
                            table.get_row(list(npk)) is not None:
                        raise SqlError(
                            f"UPDATE key {npk} collides with an "
                            "existing row")
                pairs = [tuple(r) for r in rows] + new_rows
                ops = [OP_DELETE] * len(rows) + [OP_INSERT] * len(new_rows)
            out = make_chunk(t.schema, pairs, ops=ops,
                             capacity=len(pairs), physical=True)
            self.dml.stage(t.table_id, out)
        return [("UPDATE", len(rows))]

    # --------------------------------------------------------------- epochs --

    # -- data-version seqlock (frontend/serving.py reads it) ------------------
    # State-store mutation sections (tick / barrier completion / recovery):
    # the data version goes ODD on entry of the outermost section and EVEN
    # again on exit. Optimistic serving readers accept a scan only when
    # the same even version spans it; mutators always hold the API lock,
    # so the depth counter needs no extra lock. Plain enter/exit methods —
    # these sit on the hot path of every tick.

    def _enter_mutation(self) -> None:
        self._mutation_depth += 1
        if self._mutation_depth == 1:
            self._data_version += 1              # odd: in progress

    def _exit_mutation(self) -> None:
        self._mutation_depth -= 1
        if self._mutation_depth == 0:
            self._data_version += 1              # even: quiescent

    @_locked
    def tick(self, generate: bool = True, checkpoint: Optional[bool] = None,
             mutation: Optional[Mutation] = None) -> int:
        """One barrier cycle: feed sources, inject the barrier, and await
        completion of the oldest in-flight epoch once more than
        ``in_flight_barriers`` are outstanding — the reference's pipelined
        inject/collect loop (src/meta/src/barrier/mod.rs:152,
        in_flight_barrier_nums config.rs:380-381). With the default of 1
        this is the classic synchronous cycle. Returns the last COMPLETED
        epoch."""
        self._enter_mutation()
        try:
            return self._tick_impl(generate, checkpoint, mutation)
        except Exception as exc:
            # a fenced ex-writer on a remote control plane demotes to a
            # working serving session instead of wedging (the original
            # MetaFenced still surfaces so the driver knows)
            self._maybe_demote(exc)
            raise
        finally:
            self._exit_mutation()

    def _tick_impl(self, generate: bool, checkpoint: Optional[bool],
                   mutation: Optional[Mutation]) -> int:
        if self.role == "serving":
            raise RuntimeError(
                "serving sessions do not conduct barriers: only the "
                "writer session ticks (docs/control-plane.md)")
        # a fenced ex-writer must not inject another barrier: a newer
        # writer owns conduction now (lease loss arrives either on the
        # leader notification channel or as a refused publish/commit)
        self._check_fenced()
        epoch = self._injected + 1
        # tag this tick's dispatch spans (common/profiling.py) so a slow
        # epoch's span-tree capture includes the dispatches that caused it
        from ..common.profiling import GLOBAL_PROFILER
        GLOBAL_PROFILER.epoch = epoch
        if checkpoint is None:
            checkpoint = epoch % self.checkpoint_frequency == 0
        # keep the worker registry in sync with the live job set (workers
        # register with last_heartbeat = the current epoch clock). With a
        # remote meta, re-anchor the epoch clock FIRST: a restarted meta
        # process comes back with clock 0, and letting sync_jobs register
        # at 0 before completion advances to `epoch` would expire every
        # job in one jump (in-process meta: clock already equals
        # self.epoch, so this is a no-op kept off that path)
        if self.meta_addr is not None:
            self.meta.advance_epoch_clock(self.epoch)
        self.meta.sync_jobs(self.jobs.keys())
        if mutation is None and self._pending_mutation is not None:
            mutation = self._pending_mutation
            self._pending_mutation = None
        barrier = Barrier.new(epoch, checkpoint=checkpoint, mutation=mutation)
        if generate and not self.paused:
            for feed in self.feeds:
                if feed.job in self._dead_jobs:
                    # a dead job consumes nothing: advancing its reader
                    # would move offsets past rows it never processed
                    continue
                for _ in range(self.chunks_per_tick):
                    chunk = feed.generator()
                    if chunk is not None:
                        feed.queue.push(chunk)
        if self._cosched.jobs:
            # co-scheduled groups: one fused dispatch per group covers
            # every member MV's epoch; flush chunks land on the job
            # queues BEFORE the barrier below
            self._cosched_tick(epoch, checkpoint,
                               generate and not self.paused)
        if self._hetero.jobs:
            # tick-compiled groups: the compiler's minimal dispatch
            # schedule (shape-class supergroups + mega-epochs) covers
            # every registered MV's epoch in a handful of dispatches
            self._hetero_tick(epoch, checkpoint,
                              generate and not self.paused)
        if self._shardfused_engines:
            # mesh-sharded fused MVs: one dispatch per MV per epoch
            # across ALL chips (ops/fused_sharded.py)
            self._shardfused_tick(epoch, checkpoint,
                                  generate and not self.paused)
        from ..common.tracing import CAT_EPOCH, trace_span
        import time as _time
        # barrier observatory: open this epoch's waterfall record and
        # time the inject stage (host-side perf_counter only — zero
        # added dispatches, nothing on the device path)
        self._barrier_ledger.begin(epoch, checkpoint, _time.time())
        _inj0 = _time.perf_counter()
        with trace_span("barrier.inject", CAT_EPOCH, epoch=epoch,
                        tid="conductor", checkpoint=checkpoint):
            self.dml.drain_into_epoch()
            for feed in self.feeds:
                if feed.reader is not None:
                    feed.offsets_at_epoch[epoch] = feed.reader.offsets
                feed.queue.push(barrier)
            for queues in self._table_queues.values():
                for q in queues:
                    q.push(barrier)
            if self.workers:
                from .remote import WorkerDied
                dead_jobs = sorted(self._dead_jobs)

                async def _inject_remote() -> None:
                    for w in self.workers:
                        if w.dead:
                            continue
                        try:
                            # jobs already declared dead (a spanning job
                            # with a killed peer) are excluded: feeding
                            # them would advance readers past rows the
                            # job never processed, and waiting on them
                            # would wedge the worker's healthy jobs
                            await w.inject_barrier(
                                epoch, checkpoint,
                                generate and not self.paused, mutation,
                                exclude=dead_jobs)
                        except WorkerDied:
                            pass        # collect marks its jobs dead
                self._await(_inject_remote())
        self._injected = epoch
        self._inflight.append((epoch, checkpoint))
        self._barrier_ledger.stage(
            epoch, "inject", (_time.perf_counter() - _inj0) * 1e3)
        # (perf_counter for latency precision, wall clock for span export)
        self._inject_time[epoch] = (_time.perf_counter(), _time.time())
        # pipelined barriers would let an upstream run AHEAD of an active
        # backfill's snapshot reads (the scan would see a later epoch's
        # staged rows and the same update would also arrive as a delta —
        # double-apply). While any backfill is in flight, barriers
        # complete synchronously; completed backfills free pipelining.
        self.backfills = [b for b in self.backfills if not b.bf.done]
        limit = 1 if self.backfills else self.in_flight_barriers
        while len(self._inflight) >= limit:
            self._complete_oldest()
        # failure detection + scoped recovery (reference: heartbeat expiry
        # manager/cluster.rs:320-344 → recovery barrier/recovery.rs:110):
        # the TTL detector declares jobs that stopped heartbeating DOWN;
        # its listeners queue them and recovery runs here, outside the
        # collect path
        if not self._recovering:
            self.meta.check_job_failures()
            if self._jobs_to_recover:
                # a dead job's downstreams expire with it (barrier
                # starvation). Recover only subtree ROOTS — each root's
                # recovery rebuilds its whole downstream subtree, and
                # expiry order is not topological (the detector iterates a
                # registry), so covered names must be dropped, not just
                # deduped after the fact.
                pending = list(dict.fromkeys(self._jobs_to_recover))
                self._jobs_to_recover.clear()
                covered: set[str] = set()
                for m in pending:
                    j = self.jobs.get(m)
                    if j is not None:
                        covered.update(self._downstream_names(j))
                recovered: set[str] = set()
                for n in pending:
                    if n in covered or n in recovered:
                        continue
                    from .remote import WorkerDied
                    try:
                        recovered.update(self._recover_job(n))
                    except WorkerDied:
                        # the fabric is STILL faulty (an ongoing
                        # partition ate the rebuilt graph's init cut, or
                        # the respawned worker died again): a recovery
                        # attempt must not crash the session — requeue
                        # and retry on a later tick, when the fault
                        # window may have passed
                        if n in self.jobs:
                            self._dead_jobs.add(n)
                        self._jobs_to_recover.append(n)
            if (self.autoscaler_config.enabled and self.workers
                    and not self._in_rescale
                    and not self._dead_jobs and not self._jobs_to_recover):
                # backlog-driven autoscaling, AFTER failure handling: a
                # cluster mid-recovery must heal, not rescale — and a
                # rescale's own quiesce flush (a nested tick) must not
                # re-enter the policy mid-migration
                self._autoscaler_step()
        return self.epoch

    def _autoscaler_step(self) -> None:
        """One autoscaler observation per spanning job: fold this job's
        per-edge exchange counters (backlog, permits_waited growth) and
        the slow-epoch detector into the policy core
        (meta/autoscaler.py); execute any decision as a live rescale.
        A failed migration rolls back, notes the error, and holds the
        cooldown — the autoscaler can never crash a tick."""
        if not self._spanning_specs:
            return          # nothing rescalable: skip the stats fan-out
        stats = self._federate_worker_stats(force=True, timeout=0.5)
        slow_delta = self._slow_epoch_total - self._autoscaler_slow_seen
        self._autoscaler_slow_seen = self._slow_epoch_total
        if len(self._spanning_specs) > 1:
            # the slow-epoch detector times the WHOLE barrier tick, so
            # with several spanning jobs it cannot name a culprit — one
            # heavy job would scale out every idle sibling. Per-edge
            # backlog/permit counters stay per-job; only they decide.
            slow_delta = 0
        live_workers = sum(1 for w in self.workers if not w.dead)
        for name in list(self._spanning_specs):
            placement = self._spanning_specs[name]["placement"]
            par = max(len(a) for a in placement.actors.values())
            backlog = pw = 0
            for _wid, st in sorted(stats.items()):
                for e in st.get("exchange", ()) or ():
                    if str(e.get("edge", "")).startswith(f"{name}:"):
                        backlog += int(e.get("backlog", 0) or 0)
                        pw += int(e.get("permits_waited", 0) or 0)
            pw_delta = max(0, pw - self._autoscaler_pw.get(name, 0))
            self._autoscaler_pw[name] = pw
            target = self.autoscaler.observe(
                name, par, backlog=backlog, permits_waited=pw_delta,
                slow_epochs=slow_delta, live_workers=live_workers)
            if target is None or target == par:
                continue
            try:
                self.rescale(name, target)
            except Exception as e:  # noqa: BLE001 - rolled back + held
                self.autoscaler.note_failed(name, repr(e))

    @_locked
    def set_source_rate(self, chunks_per_tick: int) -> None:
        """Adjust the per-tick source generation rate LIVE, session-side
        and on every worker (``set_rate`` frames) — the traffic-spike
        lever the sim's autoscaler scenario drives (sim.py
        run_traffic_spike)."""
        self.chunks_per_tick = max(0, int(chunks_per_tick))
        if not self.workers:
            return
        from .remote import WorkerDied

        async def _all() -> None:
            for w in self.workers:
                if w.dead:
                    continue
                try:
                    await w.request({"type": "set_rate",
                                     "chunks_per_tick":
                                     self.chunks_per_tick})
                except WorkerDied:
                    pass          # recovery re-ships chunks_per_tick

        self._await(_all())

    def _complete_oldest(self) -> None:
        self._enter_mutation()
        try:
            self._complete_oldest_impl()
        finally:
            self._exit_mutation()

    def _complete_oldest_impl(self) -> None:
        from ..common.barrier_ledger import GLOBAL_STAGES
        from ..common.tracing import CAT_EPOCH, GLOBAL_TRACE, Span, trace_span
        import time as _time
        e, ckpt = self._inflight.pop(0)
        ledger = self._barrier_ledger
        t_entry = _time.perf_counter()
        _pend = self._inject_time.get(e)
        if _pend is not None:
            # pending: injected, parked in _inflight behind older epochs
            # (pipelining) — with depth 1 this is ~0 and the waterfall
            # stage sum reconciles with the barrier latency recorder
            ledger.stage(e, "pending", (t_entry - _pend[0]) * 1e3)
        dead_before = len(self._dead_jobs)
        result = "ok"
        try:
            with trace_span("barrier.collect", CAT_EPOCH, epoch=e,
                            tid="conductor"):
                self._await(self._collect_barrier(e))
        except BaseException:
            ledger.stage(e, "collect",
                         (_time.perf_counter() - t_entry) * 1e3)
            ledger.ingest_events(GLOBAL_STAGES.drain())
            ledger.finish(e, (_time.perf_counter() - t_entry) * 1e3,
                          "failed")
            self._inject_time.pop(e, None)
            raise
        ledger.stage(e, "collect", (_time.perf_counter() - t_entry) * 1e3)
        if len(self._dead_jobs) > dead_before:
            result = "failed"        # collect declared a job dead
        if ckpt and self._dead_jobs:
            # a dead job may have staged a torn subset of its tables for an
            # epoch whose checkpoint it never finished — keep those buffers
            # out of this commit (recovery reloads from the last good one).
            # Covers EVERY job kind: a killed table/sink job's torn epoch
            # must not become durable either.
            for n in self._dead_jobs:
                self.store.discard_pending_tables(self._job_state_ids(n))
        if ckpt:
            t_commit = _time.perf_counter()
            with trace_span("checkpoint.commit", CAT_EPOCH, epoch=e,
                            tid="conductor"):
                self._commit_checkpoint(e)
            ledger.stage(e, "commit",
                         (_time.perf_counter() - t_commit) * 1e3)
        # session-process storage/sink stage events (recorded at the 2PC
        # sites in storage/checkpoint.py and stream/sink.py) fold into
        # their records here, off the device path. Worker-side events
        # arrive later over stats federation and attach to the sealed
        # ring record by epoch.
        ledger.ingest_events(GLOBAL_STAGES.drain())
        t0 = self._inject_time.pop(e, None)
        if t0 is not None:
            perf0, wall0 = t0
            lat = _time.perf_counter() - perf0
            self.barrier_latency.record(lat)
            record = ledger.finish(e, lat * 1e3, result)
            # the whole-epoch span (inject → collect/commit): parent of
            # this epoch's executor spans in the trace export
            GLOBAL_TRACE.record(Span(
                f"epoch {e}", CAT_EPOCH, wall0, lat, epoch=e,
                tid="conductor", args={"checkpoint": ckpt}))
            lat_ms = lat * 1e3
            if (self.slow_epoch_threshold_ms
                    and lat_ms >= self.slow_epoch_threshold_ms):
                # slow-epoch detector: freeze the offending epoch's span
                # tree for post-hoc inspection (the ring may overwrite it
                # long before anyone looks). Pull workers' spans FIRST —
                # without the forced poll a worker-hosted job's capture
                # would hold only conductor-side spans. Short fuse: this
                # runs INSIDE barrier completion, and a 2s stall here
                # would itself keep every following epoch over threshold
                self._federate_worker_stats(force=True, timeout=0.25)
                self._slow_epoch_total += 1
                self._slow_epochs.append({
                    "epoch": e, "latency_ms": round(lat_ms, 3),
                    "checkpoint": ckpt,
                    # the offending barrier's waterfall record, refreshed
                    # post-federation so worker stages are attached
                    "barrier": ledger.get(e) or record,
                    "spans": [s.to_dict()
                              for s in GLOBAL_TRACE.snapshot(epoch=e)],
                })
        else:
            ledger.finish(e, (_time.perf_counter() - t_entry) * 1e3,
                          result)
        self.epoch = e
        # control-plane publication (reference: barrier_complete responses +
        # hummock version notifications, SURVEY.md §3.2 tail)
        self.meta.advance_epoch_clock(e)
        try:
            self.meta.publish_barrier(e, ckpt)
            if ckpt:
                self.meta.publish_checkpoint(e)
        except Exception as exc:
            # a refused publish is how a stale writer learns it lost the
            # lease when the leader notification hasn't landed yet
            if type(exc).__name__ == "MetaFenced":
                self._fenced = True
            raise
        if ckpt and self.compactors:
            self._kick_compaction()

    def _commit_checkpoint(self, e: int) -> None:
        """Phase 2 of the cluster checkpoint for epoch ``e``: split
        offsets + the session store tier, then the workers' staged
        epochs."""
        # lease check BEFORE anything becomes durable: a stale ex-writer
        # (remote meta, lease superseded) must not commit. One host-side
        # RPC per checkpoint — nothing on the device path.
        self._check_fenced()
        assert_leader = getattr(self.meta, "assert_leader", None)
        if assert_leader is not None and self.role == "writer":
            from ..meta.client import MetaFenced
            try:
                assert_leader()
            except MetaFenced:
                self._fenced = True
                raise
        # persist source split offsets atomically with the epoch commit
        # (reference: split state committed with the checkpoint barrier)
        from ..common.types import VARCHAR
        for feed in self.feeds:
            if feed.state_table is None:
                continue
            if feed.job in self._dead_jobs:
                # freeze the dead job's offsets at its last completed
                # checkpoint: its state did not advance, so persisting
                # newer offsets would silently skip the rows in between
                continue
            latest = None
            for oe in sorted(list(feed.offsets_at_epoch)):
                if oe <= e:
                    latest = feed.offsets_at_epoch.pop(oe)
            if latest is not None:
                for sid, off in latest.items():
                    feed.state_table.insert(
                        (VARCHAR.to_physical(sid), int(off)))
                feed.state_table.commit(e)
        if self.pipeline_depth >= 2:
            # off-critical-path checkpoint encode: the committed-delta
            # serialization + segment write runs on a worker thread and
            # overlaps the next epoch's device compute; it is JOINED
            # before any 2PC phase-2 frame below (and on FLUSH/close),
            # so exactly-once semantics are untouched
            self.store.commit_async(e)
        else:
            self.store.commit(e)
        if self.workers:
            # the session tier must be durable before phase 2: a worker
            # committing ahead of a crashed session write would fork
            # history against the recovery rebuild
            self.store.join_commits()
            # phase 2 of the cluster checkpoint: workers sealed and
            # acked; only now may their staged epochs become durable
            # (a worker killed before this frame recovers one
            # checkpoint back and its deterministic sources replay).
            # Dead jobs are excluded: a spanning job with a killed peer
            # may have staged a TORN epoch on its surviving workers —
            # committing it would fork history against the recovery
            # rebuild (the session-store analogue is
            # discard_pending_tables above)
            from .remote import WorkerDied
            dead_jobs = sorted(self._dead_jobs)

            async def _commit_remote() -> None:
                for w in self.workers:
                    if w.dead:
                        continue
                    try:
                        await w.commit(e, skip_jobs=dead_jobs)
                    except WorkerDied:
                        pass
            self._await(_commit_remote())

    def _drain_inflight(self) -> None:
        while self._inflight:
            self._complete_oldest()

    # -- storage-tier compaction (dedicated compactor role) -------------------

    def _kick_compaction(self) -> None:
        """Hand the version manager's next merge task to a compactor
        worker — on a pump thread, never the barrier path (reference:
        compaction runs concurrently with checkpoints,
        src/storage/compactor/src/server.rs:57)."""
        t = self._compaction_pump
        if t is not None and t.is_alive():
            return
        task = self.store.manager.get_compact_task()  # type: ignore[attr-defined]
        if task is None:
            return
        t = threading.Thread(target=self._drive_compactor, args=(task,),
                             daemon=True, name="compaction-pump")
        self._compaction_pump = t
        t.start()

    def _drive_compactor(self, task) -> None:
        from ..common.tracing import CAT_STORAGE, trace_span
        from ..worker.compactor import CompactorDied
        mgr = self.store.manager  # type: ignore[attr-defined]
        for c in self.compactors:
            if c.dead:
                try:
                    c.respawn()   # stateless role: nothing to recover
                except Exception:  # noqa: BLE001 - try the next worker
                    continue
            try:
                with trace_span("compaction.dispatch", CAT_STORAGE,
                                tid="conductor", task_id=task.task_id,
                                compactor=c.worker_id):
                    outputs = c.compact(task)
                mgr.report_compact_task(task.task_id, outputs)
                mgr.vacuum()
                return
            except (CompactorDied, RuntimeError) as e:
                import sys as _sys
                _sys.stderr.write(
                    f"compactor {c.worker_id} failed task "
                    f"{task.task_id}: {e!r}\n")
        # no worker finished it: forget the task; a later checkpoint
        # reschedules and converges (inputs are untouched)
        mgr.cancel_compact_task(task.task_id)

    def wait_compaction(self) -> None:
        """Join in-flight compaction work (tests / orderly shutdown)."""
        t = self._compaction_pump
        if t is not None and t.is_alive():
            t.join()
        wait = getattr(self.store, "wait_compaction", None)
        if wait is not None:
            wait()

    def pin_version(self):
        """Pin the current storage version for consistent snapshot reads
        (Hummock tier only): the returned snapshot's SSTs survive any
        concurrent compaction until ``unpin()``/context exit — the read
        contract batch nodes and backup rely on (reference:
        pin_version leases, src/meta/src/hummock/manager/versioning.rs)."""
        pin = getattr(self.store, "pin", None)
        if pin is None:
            raise SqlError(
                "version pinning requires the hummock state store "
                "(Session(state_store='hummock'))")
        return pin()

    async def _collect_barrier(self, epoch: int) -> None:
        # gather must be created inside the session loop (it binds futures
        # to the running loop). Each job that reports the barrier heartbeats
        # its worker entry; a job whose actor task was KILLED (cancelled —
        # the madsim node-kill analogue) stops heartbeating and is left to
        # the TTL detector + scoped recovery, while executor logic errors
        # keep propagating to the caller as before.
        #
        # Downstreams of a dead job are BARRIER-STARVED (nothing upstream
        # will ever forward this epoch's barrier): waiting on them would
        # deadlock the conductor, so they are skipped — and since skipping
        # also withholds their heartbeat, the TTL detector declares the
        # whole subtree DOWN and scoped recovery rebuilds it together.
        dead = {n for n, j in self.jobs.items()
                if isinstance(j._failure, asyncio.CancelledError)}
        self._dead_jobs |= dead
        starved: set[str] = set()
        for n in dead:
            starved.update(self._downstream_names(self.jobs[n]))
        starved -= dead

        async def one(name: str, job: StreamJob) -> None:
            if name in starved:
                return
            try:
                await job.wait_barrier(epoch)
            except BaseException:
                if isinstance(job._failure, asyncio.CancelledError):
                    self._dead_jobs.add(name)
                    return
                raise
            self.meta.job_heartbeat(name)

        await asyncio.gather(
            *(one(n, j) for n, j in self.jobs.items()))

    @_locked
    def flush(self) -> None:
        """FLUSH: complete a checkpoint epoch (DML + state made durable).
        Joins any deferred checkpoint encode — FLUSH is the durability
        promise, so it may not return while an async commit is in
        flight."""
        self.tick(generate=False, checkpoint=True)
        try:
            self._drain_inflight()
        except Exception as exc:
            self._maybe_demote(exc)
            raise
        self.store.join_commits()

    # ----------------------------------------------------------- mutations --

    @_locked
    def pause(self) -> None:
        """Stop source data flow; barriers keep flowing (reference:
        Mutation::Pause, executor/mod.rs:241-251 — used during config
        changes and recovery)."""
        if not self.paused:
            self.paused = True
            self.tick(generate=False, mutation=Mutation(MutationKind.PAUSE))

    @_locked
    def resume(self) -> None:
        if self.paused:
            self.paused = False
            self.tick(generate=False, mutation=Mutation(MutationKind.RESUME))

    # ---------------------------------------------------------------- query --

    @_locked
    def describe(self, sql: str):
        """Output schema of ``sql``'s LAST statement WITHOUT executing it
        — the extended-protocol Describe contract (reference: pgwire
        Describe → frontend infer_return_type,
        src/utils/pgwire/src/pg_protocol.rs:220-259). None = no rows."""
        stmts = parse_sql(sql)
        if not stmts:
            return None
        last = stmts[-1]
        from ..common.types import VARCHAR
        if isinstance(last, A.ShowStatement):
            if last.what == "parameters":
                return [("Name", VARCHAR), ("Value", VARCHAR)]
            return [("Name", VARCHAR)]
        if isinstance(last, A.Explain):
            return [("QUERY PLAN", VARCHAR)]
        if isinstance(last, A.Query):
            # raw plan suffices: every optimizer pass preserves the root
            # schema by contract, so skip the rewrite work here
            plan = Planner(self.catalog).plan_select(last.select)
            return [(f.name, f.type) for f in plan.schema
                    if not f.name.startswith("_")]
        return None

    def _push_remote_fragments(self, plan):
        """Cut maximal Filter/Project chains over worker-hosted MV scans
        into PRemoteFragment stages: the scan+filter+project runs ON the
        worker owning the state and only result rows cross the socket
        (reference: distributed batch stages,
        scheduler/distributed/query.rs:69,115)."""
        from .planner import (
            PFilter as _PF, PProject as _PP, PRemoteFragment,
        )

        def chain_base(node):
            cur = node
            while isinstance(cur, (_PF, _PP)):
                cur = cur.input
            return cur

        def make_fragment(node):
            base = chain_base(node)
            name = base.mv.name
            from .plan_json import defs_to_json, plan_to_json
            plan_json = plan_to_json(node)
            defs_json = defs_to_json([base.mv])
            hosts = self._mv_hosts(name)
            types = [f.type for f in node.schema]

            def fetch():
                import base64 as _b64

                from ..common.row import decode_value_row

                # data-plane requests: a big batch stage may legitimately
                # outlive the control-frame deadline — unbounded here;
                # wedge detection stays the barrier deadline's job. A
                # sharded-root MV's stage runs on EVERY slice-holding
                # worker, each restricted to ITS placed vnode range — a
                # live migration (meta/rescale.py) can leave handed-off
                # rows behind in a store, and an unrestricted scan would
                # union them twice against the range's current owner.
                async def _all():
                    def req(rng):
                        frame = {"type": "batch_task", "job": name,
                                 "plan": plan_json, "defs": defs_json}
                        if rng is not None:
                            frame["vnodes"] = list(range(rng[0], rng[1]))
                        return frame
                    return await asyncio.gather(*(
                        w.request(req(rng), timeout=0)
                        for w, rng in hosts))

                rows = []
                for resp in self._await(_all()):
                    if not resp.get("ok", True):
                        raise RuntimeError(
                            f"batch stage on {name!r}: {resp.get('error')}")
                    rows.extend(decode_value_row(_b64.b64decode(b), types)
                                for b in resp["rows"])
                return rows

            return PRemoteFragment(schema=node.schema, pk=node.pk,
                                   job=name, fetch=fetch)

        def rewrite(node):
            base = chain_base(node)
            if (isinstance(base, PMvScan)
                    and self._mv_worker(base.mv.name) is not None):
                return make_fragment(node)
            kids = list(node.children)
            if not kids:
                return node
            new_kids = [rewrite(k) for k in kids]
            if all(a is b for a, b in zip(new_kids, kids)):
                return node
            from .optimizer import _with_children
            return _with_children(node, new_kids)

        return rewrite(plan)

    def query(self, sel: A.Select) -> list:
        """Batch SELECT through the serving plane (frontend/serving.py):
        version-pinned plan cache (a repeated SELECT skips replan /
        relower / re-jit entirely), two-phase distributed aggregation
        for grouped-agg shapes, and a concurrent read path — cache hits
        and local re-executions never take the session API lock, so
        readers do not serialize behind each other or block barrier
        ticks. Batch-unservable shapes (windows, EOWC, DISTINCT aggs,
        fallback joins) run the stream-fold path below, exactly as
        before. NOTE: do not call ``lower_plan`` here directly — the
        serving cache is the only lowering entry (scripts/check.sh
        lints this)."""
        return self._serving.query(self, sel)

    def _query_stream_fold(self, sel: A.Select, plan) -> list:
        """Stream-only SELECT shapes: run the SAME operator pipeline over
        snapshot sources and fold the delta stream into rows (the
        streaming/batch unification path). Called by the serving plane
        WITH the API lock held."""
        if self._remote_specs or self._spanning_specs:
            plan = self._push_remote_fragments(plan)

        def factory(leaf) -> Executor:
            from .planner import PRemoteFragment
            if isinstance(leaf, (PTableScan, PMvScan, PRemoteFragment)):
                if isinstance(leaf, PTableScan):
                    tid, schema = leaf.table.table_id, leaf.table.schema
                elif isinstance(leaf, PMvScan):
                    tid, schema = leaf.mv.table_id, leaf.mv.schema
                else:
                    schema = leaf.schema
                if isinstance(leaf, PRemoteFragment):
                    rows = leaf.fetch()       # stage ran on the worker
                elif (isinstance(leaf, PMvScan)
                        and self._mv_worker(leaf.mv.name) is not None):
                    rows = self._remote_scan(leaf.mv.name, schema,
                                             physical=True)
                else:
                    table = StateTable(self.store, tid, schema, [])
                    rows = list(table.scan_all())
                msgs: list[Message] = [Barrier.new(1)]
                from ..common.chunk import physical_chunk
                cap = self.source_chunk_capacity
                for i in range(0, len(rows), cap):
                    msgs.append(physical_chunk(schema, rows[i:i + cap], cap))
                msgs.append(Barrier.new(2))
                return MockSource(schema, msgs)
            if isinstance(leaf, PValues):
                chunk = _values_chunk(leaf)
                return MockSource(leaf.schema,
                                  [Barrier.new(1), chunk, Barrier.new(2)])
            raise SqlError(
                "batch SELECT over an unbounded source is not supported; "
                "create a materialized view instead")

        ctx = BuildContext(self.store, self.catalog.next_table_id, factory,
                           self.config, durable=False)
        pipeline = build_plan(plan, ctx)
        rows = self._await(self._run_batch(pipeline))
        # fold the change stream into final rows
        acc: dict = {}
        for op, row in rows:
            if op in (OP_INSERT, OP_UPDATE_INSERT):
                acc[row] = acc.get(row, 0) + 1
            else:
                acc[row] = acc.get(row, 0) - 1
                if acc[row] == 0:
                    del acc[row]
        out = []
        for row, n in acc.items():
            out.extend([row] * n)
        out = self._present(out, sel, plan)
        return out

    async def _run_batch(self, pipeline: Executor) -> list:
        rows = []
        async for msg in pipeline.execute():
            if isinstance(msg, StreamChunk):
                rows.extend(chunk_to_rows(msg, pipeline.schema, with_ops=True))
        return rows

    def _present(self, rows: list, sel: A.Select, plan) -> list:
        """Presentation: ORDER BY sort, then strip hidden columns."""
        schema = plan.schema
        if sel.order_by:
            scope = Scope.of_schema(schema)
            keys = []
            for oi in sel.order_by:
                b = ExprBinder(scope).bind(oi.expr)
                from ..expr.expr import InputRef
                if isinstance(b, InputRef):
                    keys.append((b.index, oi.desc))
            for idx, desc in reversed(keys):
                rows = sorted(
                    rows,
                    key=lambda r: (r[idx] is None, r[idx] if r[idx] is not None else 0),
                    reverse=desc)
        visible = [i for i, f in enumerate(schema) if not f.name.startswith("_")]
        if len(visible) != len(schema):
            rows = [tuple(r[i] for i in visible) for r in rows]
        return rows

    # -------------------------------------------------------------- helpers --

    @_locked
    def mv_rows(self, name: str) -> list:
        """Current contents of an MV (visible columns, decoded)."""
        self._drain_inflight()   # read-your-writes
        mv = self.catalog.mvs.get(name)
        if mv is None:
            raise SqlError(f"materialized view {name!r} not found")
        n_vis = getattr(mv, "n_visible", len(mv.schema))
        if self._mv_worker(name) is not None:
            return [tuple(r[:n_vis])
                    for r in self._remote_scan(name, mv.schema)]
        job = self.jobs[name]
        rows = []
        for phys in job.table.scan_all():
            rows.append(tuple(
                None if v is None else mv.schema[i].type.to_python(v)
                for i, v in enumerate(phys[:n_vis])))
        return rows

    def _mv_worker(self, name: str):
        """The PRIMARY worker process holding an MV's materialized table
        (first root actor for a spanning job); None for session-local
        MVs. Scan-shaped consumers must use ``_mv_hosts`` — a sharded
        root distributes the table over SEVERAL workers."""
        hosts = self._mv_hosts(name)
        return hosts[0][0] if hosts else None

    def _mv_hosts(self, name: str) -> list:
        """Every worker holding a slice of an MV's materialized table,
        as ``(worker, (vnode_start, vnode_end) | None)`` pairs: the one
        hosting worker for whole-job placement (owning the full ring),
        one entry per ROOT-FRAGMENT ACTOR for a spanning job — with a
        sharded root (meta/fragment.py ``shardable``) the MV table is
        vnode-distributed across ≥2 workers, each owning the contiguous
        range its actor was placed with. Empty for session-local MVs."""
        spec = self._remote_specs.get(name)
        if spec is not None:
            return [(spec["worker"], None)]
        span = self._spanning_specs.get(name)
        if span is not None:
            placement = span["placement"]
            graph = span["graph"]
            by_id = {w.worker_id: w for w in span["workers"]}
            return [(by_id[a.worker], (a.vnode_start, a.vnode_end))
                    for a in placement.actors[graph.root_id]]
        return []

    def _remote_scan(self, name: str, schema: Schema,
                     physical: bool = False) -> list:
        """Fetch a worker-hosted MV's rows over the scan RPC — the UNION
        over every worker holding a slice of its table (one worker for
        whole-job placement; every root actor of a sharded-root spanning
        job, whose slices are disjoint by vnode range)."""
        import base64

        from ..common.row import decode_value_row

        async def _scan_all() -> list:
            # data-plane requests: scanning a huge MV may exceed the
            # control deadline without the worker being wedged — unbounded
            return await asyncio.gather(*(
                w.request({"type": "scan", "name": name}, timeout=0)
                for w, _rng in self._mv_hosts(name)))

        types = [f.type for f in schema]
        out = []
        for resp in self._await(_scan_all()):
            for b in resp["rows"]:
                phys = decode_value_row(base64.b64decode(b), types)
                if physical:
                    out.append(phys)
                else:
                    out.append(tuple(
                        None if v is None else schema[i].type.to_python(v)
                        for i, v in enumerate(phys)))
        return out

    @_locked
    def metrics(self) -> dict:
        """Observability dump: per-job per-executor counters + session
        barrier latency percentiles (reference:
        src/stream/src/executor/monitor/streaming_stats.rs:27-88),
        FEDERATED across worker processes — a worker-hosted job's
        counters and state bytes appear exactly like a local job's
        (reference: per-compute-node exporters scraped into one
        Prometheus; here the session is the scraper)."""
        from ..common.memory import pipeline_state_bytes
        from ..stream.metrics import pipeline_metrics
        out = {
            "barrier_latency": self.barrier_latency.snapshot(),
            # barrier observatory (common/barrier_ledger.py): in-flight
            # count + per-stage p50/p99 over the waterfall history ring
            "barrier": {
                "inflight": len(self._inflight),
                **self._barrier_ledger.summary(),
            },
            "epoch": self.epoch,
            "jobs": {
                name: pipeline_metrics(job.pipeline)
                for name, job in self.jobs.items()
                if job.pipeline is not None
            },
            "state_bytes": {
                name: pipeline_state_bytes(job.pipeline)
                for name, job in self.jobs.items()
                if job.pipeline is not None
            },
            "slow_epoch_total": self._slow_epoch_total,
            "slow_epochs": [
                {k: v for k, v in se.items() if k != "spans"}
                for se in self._slow_epochs
            ],
            "storage": self._storage_metrics(),
            # epoch co-scheduler: group membership + epochs run
            # (stream/coschedule.py)
            "coschedule": self._cosched.stats(),
            # heterogeneous tick compiler: dispatch schedule shape +
            # per-job cost attribution (stream/tick_compiler.py)
            "hetero": {**self._hetero.stats(),
                       "attribution": self._hetero.attribution()},
            # mesh-sharded fused MVs: shard count + group size + epochs
            # + grow-retry events per job (ops/fused_sharded.py,
            # parallel/fused.ShardedCoGroup — signature-equal MVs share
            # one K×S group, so their stats coincide by design)
            "shardfused": {
                name: {"shards": g.n, "epochs_run": g.epochs_run,
                       "recv_width": g.recv_width,
                       "route_grows": g.route_grows,
                       "group_jobs": g.n_jobs}
                for name, (_, _, _, g) in
                self._shardfused_engines.items()
            },
            # serving plane (frontend/serving.py): plan-cache hit/miss,
            # two-phase task counts, partials merged, read latency p50/p99
            "serving": self._serving.metrics(),
            # leader failover plane (docs/control-plane.md "Election"):
            # current role/term, fencing state, promotion/demotion
            # counters → rw_leader_* / rw_failover_* Prometheus families
            "leadership": {
                "role": self.role,
                "standby": self._standby,
                "term": self._generation,
                "is_writer": int(self.role == "writer"
                                 and not self._fenced),
                "fenced": self._fenced,
                **self._leadership,
            },
            # asynchronous epoch pipeline ([streaming] pipeline_depth):
            # configured depth, deferred-flush/drain counters, how many
            # group flushes are pending right now, and the profiler's
            # completion/occupancy stats (common/profiling.py)
            "pipeline": self._pipeline_metrics(),
            # per-site retry counters from every boundary (object store,
            # broker, sink delivery) — common/retry.py global registry
            "retry": _retry_snapshot(),
            # out-of-process UDF plane (udf/client.py): server
            # generation, call/retry/respawn/timeout counters, fencing
            # drops, backpressure peaks
            "udf": _udf_snapshot(),
            # sink-decouple health: degraded flag, undelivered backlog,
            # delivery failure counters per sink job
            "sinks": {
                name: job.pipeline.sink_health()
                for name, job in self.jobs.items()
                if hasattr(job.pipeline, "sink_health")
            },
        }
        # network fault plane (rpc/faults.py): the session process's
        # installed schedule + injection counters, the fencing/dedup
        # counters injection forced, and every worker's plane snapshot
        from ..rpc.faults import chaos_snapshot
        out["chaos"] = {
            **chaos_snapshot(),
            "generation": self._generation,
            "stale_acks_dropped": sum(
                getattr(w, "stale_acks_dropped", 0) for w in self.workers),
            "dup_replies_dropped": sum(
                getattr(w, "dup_replies_dropped", 0) for w in self.workers),
            "dup_acks_dropped": sum(
                getattr(w, "dup_acks_dropped", 0) for w in self.workers),
        }
        worker_stats = self._federate_worker_stats()
        out["chaos"]["workers"] = {
            wid: st["chaos"] for wid, st in sorted(worker_stats.items())
            if st.get("chaos")}
        # elastic scaling plane (meta/rescale.py + meta/autoscaler.py):
        # policy state + executed migrations + per-worker handoff rows
        out["autoscaler"] = {
            "enabled": self.autoscaler_config.enabled,
            **self.autoscaler.status(),
            "migrations": self._rescale_stats["migrations"],
            "moved_vnodes": self._rescale_stats["moved_vnodes"],
            "last_rescale": self._rescale_stats["last"],
            "rescale_history": list(self._rescale_stats["history"]),
            "handoff_rows": {
                wid: st["rescale"]
                for wid, st in sorted(worker_stats.items())
                if st.get("rescale")},
        }
        exchange: list = []
        for wid, st in sorted(worker_stats.items()):
            # live local jobs win over cached worker snapshots of the
            # same name (an MV recreated in-process after worker death)
            for name, jm in st.get("jobs", {}).items():
                out["jobs"].setdefault(name, jm)
            for name, nb in st.get("state_bytes", {}).items():
                out["state_bytes"].setdefault(name, nb)
            # per-exchange-edge counters (permits waited, chunks/bytes
            # forwarded, backlog) from every worker hosting an endpoint
            for e in st.get("exchange", ()) or ():
                exchange.append({"worker": wid, **e})
        out["exchange"] = exchange
        out["workers"] = [
            {"worker": w.worker_id,
             "pid": getattr(getattr(w, "proc", None), "pid", None),
             "dead": bool(w.dead),
             "jobs": sorted(worker_stats.get(w.worker_id, {})
                            .get("jobs", {}))}
            for w in self.workers
        ]
        # device profiling plane (common/profiling.py): per-qualname
        # dispatch telemetry + the cluster-wide HBM ledger. The ledger
        # consumes the ALREADY-federated per-job state-bytes snapshot
        # above (session-local jobs + every worker's), attributing each
        # job to the process that hosts its state.
        from ..common.profiling import GLOBAL_PROFILER, hbm_ledger
        obs = self.observability
        job_owner: dict = {name: None for name, job in self.jobs.items()
                           if job.pipeline is not None}
        for wid, st in sorted(worker_stats.items()):
            for name in st.get("state_bytes", {}):
                job_owner.setdefault(name, wid)
        ledger_jobs = {}
        for name, nb in out["state_bytes"].items():
            if isinstance(nb, dict):
                total = nb.get("_total", 0)
                executors = {k: v for k, v in nb.items() if k != "_total"}
            else:
                total, executors = int(nb), {}
            ledger_jobs[name] = {"bytes": int(total),
                                 "executors": executors,
                                 "worker": job_owner.get(name)}
        out["profiling"] = {
            "enabled": GLOBAL_PROFILER.enabled,
            "dispatch": GLOBAL_PROFILER.snapshot(),
            "hbm": hbm_ledger(ledger_jobs, obs.hbm_capacity_bytes,
                              GLOBAL_PROFILER.peak_temp_bytes(),
                              obs.hbm_warn_fraction),
            "workers": {wid: st["profiling"]
                        for wid, st in sorted(worker_stats.items())
                        if st.get("profiling")},
        }
        # live twin of common/dispatch_count.py: per-qualname dispatch
        # counts, with the one-dispatch-per-epoch invariants readable
        # (fused engines report dispatches ÷ epochs_run)
        dispatch = {"counts": GLOBAL_PROFILER.counts(), "per_epoch": {}}
        counts = dispatch["counts"]
        epochs_by_name: dict = dict(self._dispatch_epochs_retired)
        for g in self._cosched.groups.values():
            if g.epochs_run:
                epochs_by_name[
                    "build_group_epoch.<locals>.coscheduled_epoch"] = \
                    epochs_by_name.get(
                        "build_group_epoch.<locals>.coscheduled_epoch", 0) \
                    + g.epochs_run
        if self._shardfused is not None:
            qn = "build_sharded_group_epoch.<locals>.sharded_coscheduled_epoch"
            for g in self._shardfused.groups.values():
                if g.epochs_run:
                    epochs_by_name[qn] = epochs_by_name.get(qn, 0) \
                        + g.epochs_run
        for g in self._hetero.groups:
            if g.epochs_run:
                epochs_by_name[g.epoch_qualname] = \
                    epochs_by_name.get(g.epoch_qualname, 0) + g.epochs_run
        for qn, epochs in epochs_by_name.items():
            if qn in counts and epochs:
                dispatch["per_epoch"][qn] = round(counts[qn] / epochs, 4)
        out["dispatch"] = dispatch
        return out

    def _pipeline_metrics(self) -> dict:
        from ..common.profiling import GLOBAL_PROFILER
        pending = sum(1 for g in self._cosched.groups.values()
                      if g.pending is not None)
        pending += sum(1 for g in self._hetero.groups
                       if g.pending is not None)
        if self._shardfused is not None:
            pending += sum(1 for g in self._shardfused.groups.values()
                           if g.pending is not None)
        return {
            "depth": self.pipeline_depth,
            "pending_flushes": pending,
            **self._pipeline_stats,
            **GLOBAL_PROFILER.pipeline_stats(),
        }

    def _storage_metrics(self) -> dict:
        """Storage-tier counters for metrics()/Prometheus/dashboard:
        version id, level shape, compaction + vacuum progress (reference:
        hummock manager metrics scraped from the meta node)."""
        mgr = getattr(self.store, "manager", None)
        if mgr is not None:             # hummock tier
            out = {"tier": "hummock", **mgr.stats,
                   "pinned_versions": len(mgr.pinned_versions()),
                   "inflight_compact_tasks": len(mgr.inflight_tasks())}
            if self.compactors:
                out["compactors"] = [
                    {"worker": c.worker_id, "dead": bool(c.dead)}
                    for c in self.compactors]
            return out
        log = getattr(self.store, "log", None)
        if log is not None:             # segment tier
            try:
                m = log._read_manifest()
                return {"tier": "segment",
                        "segments": len(m.get("segments", ())),
                        "committed_epoch": m.get("committed_epoch", 0)}
            except Exception:  # noqa: BLE001 - stats must never fail
                return {"tier": "segment"}
        return {"tier": "memory"}

    def _federate_worker_stats(self, force: bool = False,
                               timeout: float = 0.5) -> dict[int, dict]:
        """Poll every live worker's ``stats`` frame. Worker spans merge
        into the session's trace ring (tagged pid = worker_id + 1) and the
        per-worker snapshot refreshes ``self._worker_stats`` — a dead
        worker keeps its last snapshot for post-hoc inspection.

        Polls are rate-limited and short-fused: the caller holds the API
        lock, so a scrape storm (dashboard auto-refresh + Prometheus) or
        a hung-but-connected worker must not stall tick()/run_sql() on
        the driving thread for long."""
        if not self.workers or self.loop.is_running():
            return self._worker_stats
        import time as _time
        now = _time.monotonic()
        if not force and now - self._worker_stats_at < 0.5:
            return self._worker_stats
        from ..common.tracing import GLOBAL_TRACE

        async def _one(w):
            try:
                return (w.worker_id, await w.get_stats(
                    timeout=timeout,
                    span_ack=self._worker_span_ack.get(w.worker_id),
                    stage_ack=self._worker_stage_ack.get(w.worker_id)))
            except Exception:  # noqa: BLE001 - stats are best-effort
                return None

        async def _fetch() -> list:
            # concurrent: a hung worker costs one timeout, not one per
            # worker, while the caller holds the API lock
            got = await asyncio.gather(
                *(_one(w) for w in self.workers if not w.dead))
            return [g for g in got if g is not None]

        for wid, resp in self._await(_fetch()):
            GLOBAL_TRACE.ingest(resp.pop("spans", []) or [], pid=wid + 1)
            seq = resp.pop("span_seq", None)
            if seq is not None:
                self._worker_span_ack[wid] = seq
            # barrier observatory: the worker's epoch-stamped stage
            # events (storage prepare/settle/commit, worker collect)
            # attach to their waterfall records in the history ring —
            # re-ingesting a resent batch only re-sums an epoch already
            # evicted from the ring, so ack discipline keeps it exact
            stage_seq = resp.pop("stage_seq", None)
            events = resp.pop("barrier_stages", []) or []
            if stage_seq is not None \
                    and stage_seq != self._worker_stage_ack.get(wid):
                self._barrier_ledger.ingest_events(events, worker=wid)
            if stage_seq is not None:
                self._worker_stage_ack[wid] = stage_seq
            self._worker_stats[wid] = resp
        self._worker_stats_at = _time.monotonic()
        return self._worker_stats

    @_locked
    def await_tree(self) -> str:
        """Federated await-tree dump: local jobs walked in-process plus
        every worker-hosted job's tree over the stats RPC — "the
        await-tree of a worker-hosted job, visible over HTTP while it
        runs" (reference: risectl trace / dashboard await-tree,
        monitor_service.rs:46)."""
        from ..stream.trace import dump_session
        self._federate_worker_stats()
        return dump_session(self)

    @_locked
    def export_chrome_trace(self, path: Optional[str] = None) -> dict:
        """Chrome trace-event JSON of the span ring (Perfetto-loadable):
        epochs on the conductor track, executors on their own tracks,
        workers as separate processes. Optionally written to ``path``."""
        from ..common.tracing import GLOBAL_TRACE, export_chrome_trace
        self._federate_worker_stats()    # pull workers' latest spans
        return export_chrome_trace(
            GLOBAL_TRACE.snapshot(), path=path,
            barrier_records=self._barrier_ledger.history())

    @_locked
    def slow_epochs(self) -> list:
        """Captured slow-epoch span trees (newest last), each
        ``{epoch, latency_ms, checkpoint, spans}``."""
        return list(self._slow_epochs)

    @_locked
    def barrier_blame(self) -> list:
        """Name who is holding up every in-flight barrier, NOW.

        Walks the live per-epoch accounting — local jobs' barrier
        events, every RemoteWorker's epoch events + per-job failure
        maps, and the federated per-exchange-edge counters (whose
        ``last_barrier_epoch`` says how far the barrier propagated on
        each link) — and returns one finding per suspect:

          {"epoch", "checkpoint", "age_ms", "kind", "job", "worker",
           "fragment", "actor", "link", "edge", "reason"}

        ``kind`` is ``local_job`` / ``worker`` / ``exchange_edge``. An
        exchange finding names the CONSUMER actor of the starved edge
        (parsed from the ``job:f<u>.<i>->f<d>.<j>`` edge id, resolved
        to its worker via the persisted placement), which is exactly
        the actor a partitioned link stops feeding — diagnosis by name
        within one tick, instead of waiting for the epoch-deadline
        recovery to kill the worker. Stats frames are chaos-META, so
        federation works through data-plane partitions. Empty list ⇔
        nothing in flight or everything already acked."""
        import re as _re
        import time as _time
        findings: list = []
        if not self._inflight:
            return findings
        # best-effort refresh of exchange counters; stats frames bypass
        # chaos partitions (rpc/faults.META_FRAME_TYPES)
        worker_stats = self._federate_worker_stats(force=True)
        edge_re = _re.compile(
            r"^(?P<job>.+):f(?P<uf>\d+)\.(?P<ua>\d+)"
            r"->f(?P<df>\d+)\.(?P<da>\d+)$")
        for epoch, ckpt in self._inflight:
            t0 = self._inject_time.get(epoch)
            age_ms = ((_time.perf_counter() - t0[0]) * 1e3
                      if t0 is not None else None)

            def _add(kind, reason, job=None, worker=None, fragment=None,
                     actor=None, link=None, edge=None,
                     _epoch=epoch, _ckpt=ckpt, _age=age_ms):
                findings.append({
                    "epoch": _epoch, "checkpoint": bool(_ckpt),
                    "age_ms": _age, "kind": kind, "job": job,
                    "worker": worker, "fragment": fragment,
                    "actor": actor, "link": link, "edge": edge,
                    "reason": reason,
                })
            # local in-process jobs: the barrier event is set when the
            # barrier flows out of the pipeline's Materialize
            for name, job in self.jobs.items():
                ev_map = getattr(job, "_barrier_events", None)
                if ev_map is None:
                    continue          # RemoteJob/SpanningJob: below
                if getattr(job, "_failure", None) is not None:
                    _add("local_job", f"job failed: "
                         f"{type(job._failure).__name__}: {job._failure}",
                         job=name, worker=-1)
                    continue
                ev = ev_map.get(epoch)
                if ev is None or not ev.is_set():
                    _add("local_job", "barrier not yet emitted by "
                         "pipeline", job=name, worker=-1)
            # worker processes: epoch acks + per-job failure maps
            for w in self.workers:
                if w.dead:
                    _add("worker", "worker marked dead",
                         worker=w.worker_id, link=w.link)
                    continue
                errs = w._epoch_errors.get(epoch) or {}
                for jname, err in sorted(errs.items()):
                    _add("worker", f"job error: {err}",
                         job=None if jname == "*" else jname,
                         worker=w.worker_id, link=w.link)
                ev = w._epoch_events.get(epoch)
                if ev is None or not ev.is_set():
                    _add("worker", "barrier not acked by worker",
                         worker=w.worker_id, link=w.link)
            # exchange edges: an "in" edge whose last seen barrier lags
            # the in-flight epoch is starving its consumer actor
            for wid, st in sorted(worker_stats.items()):
                for e in st.get("exchange", ()) or ():
                    if e.get("dir") != "in":
                        continue
                    if int(e.get("last_barrier_epoch") or 0) >= epoch:
                        continue
                    m = edge_re.match(e.get("edge", ""))
                    job = frag = act = None
                    if m:
                        job = m.group("job")
                        frag = int(m.group("df"))
                        act = int(m.group("da"))
                    peer = e.get("peer_worker")
                    link = (f"w{peer}->w{wid}"
                            if peer is not None else None)
                    _add("exchange_edge",
                         "barrier missing on exchange edge "
                         f"(last seen epoch "
                         f"{e.get('last_barrier_epoch')})",
                         job=job, worker=wid, fragment=frag, actor=act,
                         link=link, edge=e.get("edge"))
        return findings

    def profile_report(self) -> dict:
        """Roofline report over every dispatch this process has seen:
        AOT-``lower().compile()`` each recorded epoch callable (chip-free
        on the CPU stand-in) and place its arithmetic intensity against
        the configured chip peaks ([observability] chip_peak_flops /
        chip_peak_bandwidth). Triggers compiles, so it deliberately does
        NOT take the session API lock — the profiler registry it reads
        has its own lock, and ticks/scrapes must not stall behind XLA."""
        from ..common.profiling import GLOBAL_PROFILER, roofline_report
        return roofline_report(GLOBAL_PROFILER.analyze(),
                               self.observability.chip_peak_flops,
                               self.observability.chip_peak_bandwidth)

    @_locked
    def close(self) -> None:
        """Graceful shutdown: stop all stream jobs, close sinks, close the
        session loop. A closed session cannot be reused."""
        if self.loop.is_closed():
            return
        self._serving.shutdown()      # stop the batch-task pool first
        if not self._fenced:
            self._drain_inflight()
        self.store.join_commits()     # deferred checkpoint encode lands
        for job in list(self.jobs.values()):
            sink = getattr(job.pipeline, "sink", None)
            if sink is not None:
                sink.close()
        jobs = list(self.jobs.values())

        async def _stop_all():
            # the gather future must be created INSIDE the session loop
            await asyncio.gather(*(job.stop() for job in jobs),
                                 return_exceptions=True)
            # abandoned per-input reader tasks (barrier_align / merge
            # recv futures) only PROCESS their cancellation on a later
            # loop tick; give them those ticks now or their queue.get
            # coroutines get GC-finalized after loop.close()
            for _ in range(3):
                await asyncio.sleep(0)

        self._await(_stop_all())
        self.jobs.clear()
        t = self._compaction_pump
        if t is not None and t.is_alive():
            t.join(timeout=30)
        for c in self.compactors:
            try:
                c.shutdown()
            except Exception:  # noqa: BLE001 - already dying
                pass
        self.compactors = []
        for w in self.workers:
            try:
                self._await(w.shutdown())
                self._await(w.aclose())
            except Exception:  # noqa: BLE001 - already dying
                pass
            w.terminate()
        self.workers = []
        # finalize abandoned executor generators (reschedule/stop leave
        # their `execute()` async generators suspended in `queue.get()`)
        # while the loop is still alive — if GC ran after loop.close(),
        # the asyncgen finalizer hook would call_soon on a closed loop and
        # trip "Event loop is closed" in asyncio.Queue's finalizer. Collect
        # FIRST (dropped generators finalize through the hook, scheduling
        # acloses), give those acloses loop ticks to run, then shut down
        # whatever generators are still referenced.
        import gc
        gc.collect()

        async def _drain_finalizers():
            for _ in range(10):
                await asyncio.sleep(0)

        self.loop.run_until_complete(_drain_finalizers())
        self.loop.run_until_complete(self.loop.shutdown_asyncgens())
        self.loop.close()
        # detach from a remote meta last: observers above may still have
        # been delivering (the in-process MetaService has no close)
        meta_close = getattr(self.meta, "close", None)
        if meta_close is not None:
            try:
                meta_close()
            except Exception:  # noqa: BLE001 - already dying
                pass

    def _bump_generation(self) -> None:
        """Advance the session-generation fencing token (persisted in
        the meta store, propagated to every worker handle). Called at
        the top of every scoped recovery, after in-flight epochs
        drained: from here on, frames from the pre-recovery incarnation
        are stale and are refused on both sides of the wire."""
        self._generation += 1
        self.meta.store.put("session_generation", str(self._generation))
        for w in self.workers:
            w.generation = self._generation

    def _alloc_shard(self) -> int:
        self._next_shard += 1
        return self._next_shard - 1

    def _await(self, coro):
        if self.loop.is_running():
            raise RuntimeError("Session API is synchronous; do not call from "
                               "inside the event loop")
        return self.loop.run_until_complete(coro)
