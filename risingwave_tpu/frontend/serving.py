"""High-QPS serving plane: version-pinned plan cache + two-phase reads.

The read-side counterpart of the fused write-side ladder (PRs 4–7): the
north star's "millions of users" are overwhelmingly *readers*, and before
this module every batch SELECT re-planned, re-lowered, re-jitted, and ran
a single-phase scan+agg under the session API lock. Three composing legs
(ROADMAP item 3; reference: the per-frontend query caches and the
distributed batch scheduler, src/frontend/src/scheduler/distributed/
query.rs:69-115):

* **Two-phase distributed aggregation** — a grouped-agg plan splits into
  per-vnode-slice PARTIAL tasks (``batch/lower.py split_two_phase``)
  fired through the local ``BatchTaskManager`` thread pool, or through
  the ``batch_task`` worker frame when the scanned MV's table lives on
  worker processes (one task per root actor: the partial agg runs WHERE
  the vnode slice lives and only per-group state lanes cross the wire).
  A session-side ``BatchMergeAgg`` folds the lanes — bit-exact vs the
  single-phase path.

* **Version-pinned plan cache** — entries key on the statement's
  canonical form and carry the lowered executor chain, the presentation
  closure, and the result rows at a pinned data version. A repeated
  SELECT with an unchanged version returns the cached rows; a version
  bump re-executes the SAME executors against the new snapshot — zero
  re-plan, zero re-lower, zero new jit wrappers (the
  ``common/dispatch_count.py`` invariant). DDL clears the cache; an LRU
  bound from ``rw_config [batch] serving_cache_size`` caps it. On the
  Hummock tier each re-execution holds a version pin so concurrent
  compaction cannot vacuum the SSTs mid-scan.

* **Concurrent serving path** — cache hits never touch the session API
  lock, so readers neither serialize behind each other nor block barrier
  ticks. Re-executions of local plans run OPTIMISTICALLY: the session
  maintains a seqlock-style data version (odd while a mutation is in
  flight, bumped on every tick/commit); a read that observes the same
  even version on both sides of its scan is consistent, anything else
  retries and finally falls back behind the API lock. Plans that touch
  worker RPCs re-execute under the lock (the session socket protocol is
  single-driver).

docs/serving.md covers the contract; Session.metrics()["serving"],
Prometheus ``rw_serving_stat`` and the dashboard panel expose the
counters.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional

from ..batch.task import BatchTaskManager, vnode_partitions
from ..common.config import BatchConfig


class _Retired(Exception):
    """Internal: the entry died (catalog bump raced the lookup)."""


def _references_system_relation(sel) -> bool:
    """True iff the SELECT's FROM tree (joins, TVFs, subqueries, UNION
    ALL branches included) names a system-catalog relation. Those
    queries must NEVER enter the plan cache: their VALUES rows are
    materialized telemetry at plan time, and no data-version seqlock
    invalidates a stale snapshot of them."""
    from . import sqlast as A
    from .system_catalog import SYSTEM_RELATION_NAMES

    def _rel(rel) -> bool:
        if rel is None:
            return False
        if isinstance(rel, A.TableRef):
            return rel.name.lower() in SYSTEM_RELATION_NAMES
        if isinstance(rel, A.Join):
            return _rel(rel.left) or _rel(rel.right)
        if isinstance(rel, A.WindowTVF):
            return _rel(rel.table)
        if isinstance(rel, A.SubqueryRef):
            return _sel(rel.query)
        return False

    def _sel(s) -> bool:
        return _rel(s.from_) or (s.union_all is not None
                                 and _sel(s.union_all))

    return _sel(sel)


class ServingStats:
    """Thread-safe counters + a latency ring for p50/p99."""

    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self.cache_hits = 0
        self.cache_misses = 0
        self.reexecutions = 0          # version-bump re-runs (no replan)
        self.catalog_invalidations = 0
        self.two_phase_queries = 0
        self.tasks_fired_local = 0
        self.tasks_fired_remote = 0
        self.partials_merged = 0       # partial state rows folded
        self.fallbacks = 0             # BatchFallback → single-phase
        self.locked_reads = 0          # reads that needed the API lock
        self.system_catalog_reads = 0  # rw_catalog/pg_catalog bypasses
        self.task_workers: collections.Counter = collections.Counter()
        self._lat = collections.deque(maxlen=window)

    def bump(self, **kw) -> None:
        with self._lock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def saw_workers(self, worker_ids) -> None:
        with self._lock:
            self.task_workers.update(worker_ids)

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self._lat.append(seconds)

    def _percentile(self, sorted_lat: List[float], q: float) -> float:
        if not sorted_lat:
            return 0.0
        i = min(len(sorted_lat) - 1, int(q * len(sorted_lat)))
        return sorted_lat[i]

    def snapshot(self, cache_size: int = 0) -> dict:
        with self._lock:
            lat = sorted(self._lat)
            return {
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "reexecutions": self.reexecutions,
                "catalog_invalidations": self.catalog_invalidations,
                "two_phase_queries": self.two_phase_queries,
                "tasks_fired_local": self.tasks_fired_local,
                "tasks_fired_remote": self.tasks_fired_remote,
                "partials_merged": self.partials_merged,
                "fallbacks": self.fallbacks,
                "locked_reads": self.locked_reads,
                "system_catalog_reads": self.system_catalog_reads,
                "cache_size": cache_size,
                "queries": self.cache_hits + self.cache_misses,
                "task_workers": dict(self.task_workers),
                "p50_ms": round(self._percentile(lat, 0.5) * 1e3, 3),
                "p99_ms": round(self._percentile(lat, 0.99) * 1e3, 3),
            }


class _CacheEntry:
    """One cached SELECT: plan artifacts + pinned-version result."""

    __slots__ = ("key", "sel", "plan", "schema", "out_types", "runner",
                 "needs_lock", "two_phase", "data_version",
                 "pinned_version", "rows", "lock", "dead")

    def __init__(self, key, sel, plan, schema, out_types, runner,
                 needs_lock, two_phase):
        self.key = key
        self.sel = sel
        self.plan = plan
        self.schema = schema            # last_select_schema form
        self.out_types = out_types      # plan.schema types (to_python)
        self.runner = runner            # () -> physical row tuples
        self.needs_lock = needs_lock    # touches worker RPCs
        self.two_phase = two_phase
        self.data_version = -1
        self.pinned_version = None      # hummock vid at last execution
        self.rows = []
        self.lock = threading.Lock()    # one re-executor at a time
        self.dead = False


class ServingPlane:
    """Per-session serving state: plan cache, task pool, counters.

    Holds no back-reference to the Session — every entry point takes the
    session as an argument, so the plane can be torn down independently
    and never keeps a closed session alive."""

    def __init__(self, cfg: Optional[BatchConfig] = None):
        self.cfg = cfg or BatchConfig()
        self.stats = ServingStats()
        self.tasks = BatchTaskManager(
            max_workers=max(1, self.cfg.serving_threads))
        self._cache: "collections.OrderedDict[str, _CacheEntry]" = \
            collections.OrderedDict()
        self._cache_lock = threading.Lock()
        self._closed = False

    # -- cache plumbing -------------------------------------------------------

    def _cache_get(self, key: str) -> Optional[_CacheEntry]:
        with self._cache_lock:
            ent = self._cache.get(key)
            if ent is not None:
                self._cache.move_to_end(key)
            return ent

    def _cache_put(self, ent: _CacheEntry) -> None:
        if self.cfg.serving_cache_size <= 0:
            return
        with self._cache_lock:
            self._cache[ent.key] = ent
            self._cache.move_to_end(ent.key)
            while len(self._cache) > self.cfg.serving_cache_size:
                _, evicted = self._cache.popitem(last=False)
                evicted.dead = True

    def _cache_drop(self, key: str) -> None:
        with self._cache_lock:
            ent = self._cache.pop(key, None)
            if ent is not None:
                ent.dead = True

    def cache_len(self) -> int:
        with self._cache_lock:
            return len(self._cache)

    def invalidate_catalog(self) -> None:
        """DDL happened: every cached plan may reference dropped/changed
        relations — clear the cache (the reference invalidates frontend
        caches on catalog notification)."""
        with self._cache_lock:
            for ent in self._cache.values():
                ent.dead = True
            self._cache.clear()
        self.stats.bump(catalog_invalidations=1)

    def shutdown(self) -> None:
        self._closed = True
        self.invalidate_catalog()
        self.tasks.shutdown()

    def metrics(self) -> dict:
        return self.stats.snapshot(cache_size=self.cache_len())

    # -- the Session.query entry ----------------------------------------------

    def query(self, session, sel) -> list:
        """Serve one SELECT. Fast path (cache hit, unchanged version):
        lock-free. Version bump: re-execute the cached executors.
        Miss: plan + lower under the API lock, cache if servable, else
        run the session's stream-fold path."""
        from ..batch.executors import BatchFallback
        t0 = time.perf_counter()
        if _references_system_relation(sel):
            # system catalogs are telemetry materialized at plan time:
            # never cached (no key is ever formed), always planned
            # fresh under the API lock for a consistent snapshot. NO
            # _drain_inflight here — these relations read no stream
            # state, and rw_barrier_inflight exists precisely to be
            # queried WHILE a barrier is stuck; draining first would
            # block on (then hide) the very barrier being diagnosed
            self.stats.bump(system_catalog_reads=1)
            with session._api_lock:
                plan = session._plan(sel)
                session.last_select_schema = [
                    (f.name, f.type) for f in plan.schema
                    if not f.name.startswith("_")]
                rows = session._query_stream_fold(sel, plan)
            self.stats.record_latency(time.perf_counter() - t0)
            return rows
        key = repr(sel)
        ent = self._cache_get(key)
        if ent is not None:
            try:
                rows = self._serve_cached(session, ent)
                self.stats.record_latency(time.perf_counter() - t0)
                return rows
            except _Retired:
                pass
            except BatchFallback:
                # the data grew into a shape the cached executors cannot
                # serve (duplicate join build keys, partial-agg table
                # overflow): drop the entry and take the full path below
                # — it rebuilds or lands on the stream-fold, exactly
                # like the pre-cache behavior
                self._cache_drop(key)
                self.stats.bump(fallbacks=1)
            except Exception:
                # a failing cached plan must not wedge the statement:
                # drop the entry and surface the error
                self._cache_drop(key)
                raise
        with session._api_lock:
            session._drain_inflight()
            plan = session._plan(sel)
            session.last_select_schema = [
                (f.name, f.type) for f in plan.schema
                if not f.name.startswith("_")]
            ent = self._build_entry(session, key, sel, plan)
            if ent is not None:
                try:
                    rows = self._execute_locked(session, ent)
                except BatchFallback:
                    self.stats.bump(fallbacks=1)
                    ent = None
            if ent is not None:
                self.stats.bump(cache_misses=1)
                self._cache_put(ent)
                self.stats.record_latency(time.perf_counter() - t0)
                return rows
            return session._query_stream_fold(sel, plan)

    # -- execution ------------------------------------------------------------

    def _finish(self, session, ent: _CacheEntry, phys: list) -> list:
        out = [
            tuple(None if v is None else ent.out_types[i].to_python(v)
                  for i, v in enumerate(r))
            for r in phys
        ]
        return session._present(out, ent.sel, ent.plan)

    def _pin(self, session):
        pin = getattr(session.store, "pin", None)
        return pin() if pin is not None else None

    def _execute_locked(self, session, ent: _CacheEntry) -> list:
        """Run an entry's executors while HOLDING the session API lock
        (first execution, RPC-touching plans, contended fallback). The
        lock serializes against every mutator, so the observed data
        version is stable across the run."""
        snap = self._pin(session)
        try:
            rows = self._finish(session, ent, ent.runner())
        finally:
            if snap is not None:
                ent.pinned_version = snap.version.vid
                snap.unpin()
        ent.rows = rows
        ent.data_version = session._data_version
        return list(rows)

    def _serve_cached(self, session, ent: _CacheEntry) -> list:
        if ent.dead:
            raise _Retired()
        v = session._data_version
        if v == ent.data_version and not (v & 1):
            self.stats.bump(cache_hits=1)
            session.last_select_schema = ent.schema
            return list(ent.rows)
        with ent.lock:
            if ent.dead:
                raise _Retired()
            v = session._data_version
            if v == ent.data_version and not (v & 1):
                self.stats.bump(cache_hits=1)
                session.last_select_schema = ent.schema
                return list(ent.rows)
            rows = self._reexecute(session, ent)
            self.stats.bump(reexecutions=1)
            session.last_select_schema = ent.schema
            return rows

    def _reexecute(self, session, ent: _CacheEntry) -> list:
        """The data version moved: run the SAME executors again (zero
        replan / relower / new jit wrappers). Local plans run
        optimistically under the seqlock protocol; RPC-touching plans
        and contended reads serialize briefly behind the API lock —
        never the other way around, so ticks are never blocked by a
        reader."""
        if not ent.needs_lock:
            for _ in range(max(1, self.cfg.serving_read_retries)):
                v0 = session._data_version
                if (v0 & 1) or session._inflight:
                    time.sleep(0.0002)
                    continue
                # hold a version pin for the scan (Hummock tier): a
                # concurrent compactor must not vacuum the SSTs under us
                snap = self._pin(session)
                try:
                    rows = self._finish(session, ent, ent.runner())
                except Exception:
                    if session._data_version != v0:
                        continue          # torn read: mutation raced us
                    raise
                finally:
                    if snap is not None:
                        snap.unpin()
                if session._data_version == v0:
                    if snap is not None:
                        ent.pinned_version = snap.version.vid
                    ent.rows = rows
                    ent.data_version = v0
                    return list(rows)
        self.stats.bump(locked_reads=1)
        with session._api_lock:
            session._drain_inflight()
            return self._execute_locked(session, ent)

    # -- entry construction ---------------------------------------------------

    def _build_entry(self, session, key, sel, plan) -> Optional[_CacheEntry]:
        """Lower ``plan`` into a reusable runner. Preference order:
        two-phase distributed agg (local slices or worker-side partial
        tasks) → single-phase batch executors (with remote-fragment
        pushdown) → None (stream-fold, uncached)."""
        from ..batch.executors import BatchFallback
        from ..batch.lower import lower_plan, split_two_phase
        from .build import collect_leaves
        from .planner import PMvScan

        schema = [(f.name, f.type) for f in plan.schema
                  if not f.name.startswith("_")]
        out_types = [f.type for f in plan.schema]

        def entry(runner, needs_lock, two_phase):
            return _CacheEntry(key, sel, plan, schema, out_types, runner,
                               needs_lock, two_phase)

        split = None
        if self.cfg.serving_tasks > 1:
            split = split_two_phase(plan)
        if split is not None:
            base = split.base
            hosts = (session._mv_hosts(base.mv.name)
                     if isinstance(base, PMvScan) else [])
            if hosts:
                runner = self._remote_two_phase_runner(
                    session, split, base.mv, hosts)
                if runner is not None:
                    self.stats.bump(two_phase_queries=1)
                    return entry(runner, needs_lock=True, two_phase=True)
            else:
                runner = self._local_two_phase_runner(session, split)
                if runner is not None:
                    self.stats.bump(two_phase_queries=1)
                    return entry(runner, needs_lock=False, two_phase=True)

        # single-phase: the pre-existing batch fast path, now cached
        if session._remote_specs or session._spanning_specs:
            plan_pushed = session._push_remote_fragments(plan)
        else:
            plan_pushed = plan
        remote_mvs = {
            leaf.mv.name for leaf in collect_leaves(plan_pushed)
            if isinstance(leaf, PMvScan)
            and session._mv_worker(leaf.mv.name) is not None
        }
        try:
            lowered = None if remote_mvs else lower_plan(
                plan_pushed, session.store, catalog=session.catalog)
        except BatchFallback:
            lowered = None
        if lowered is None:
            return None
        from ..batch.executors import run_batch
        from .planner import PRemoteFragment
        has_remote = any(isinstance(leaf, PRemoteFragment)
                         for leaf in collect_leaves(plan_pushed))
        return entry(lambda: run_batch(lowered),
                     needs_lock=has_remote, two_phase=False)

    def _local_two_phase_runner(self, session, split):
        """Partial tasks over vnode slices of the SESSION store, fired
        through the task-manager thread pool; merge in this thread. The
        executor chain (and its jit wrappers) is built exactly once."""
        from ..batch.lower import lower_plan
        n = max(1, self.cfg.serving_tasks)
        slices = vnode_partitions(n)
        partials = []
        for sl in slices:
            ex = lower_plan(split.partial_plan, session.store, vnodes=sl)
            if ex is None:
                return None
            partials.append(ex)
        holder: dict = {"rows": []}
        merge = split.merge_executor(lambda: holder["rows"])
        from ..batch.executors import run_batch

        def runner():
            tids = [self.tasks.fire_task(lambda _vn, _ex=ex: _ex)
                    for ex in partials]
            self.stats.bump(tasks_fired_local=len(tids))
            rows: list = []
            try:
                for t in tids:
                    rows.extend(self.tasks.collect(t))
            except BaseException:
                # a failed slice aborts the query: abandon the siblings
                # so their futures don't leak in the task map
                for t in tids:
                    self.tasks.discard(t)
                raise
            self.stats.bump(partials_merged=len(rows))
            holder["rows"] = rows
            return run_batch(merge)

        return runner

    def _remote_two_phase_runner(self, session, split, mv, hosts):
        """Partial tasks WHERE THE VNODES LIVE: one ``batch_task`` frame
        per worker hosting a slice of the MV's table (a sharded-root
        spanning job has ≥2 such workers, each owning a contiguous vnode
        range; a whole-job placement has one, sub-sliced by vnode for
        scan parallelism). Only partial state rows cross the wire; the
        merge runs in the session."""
        import asyncio
        import base64

        from ..common.row import decode_value_row
        from .plan_json import defs_to_json, plan_to_json

        plan_json = plan_to_json(split.partial_plan)
        defs_json = defs_to_json([mv])
        types = [f.type for f in split.partial_schema]
        reqs = []
        if len(hosts) == 1:
            worker, _rng = hosts[0]
            for sl in vnode_partitions(max(1, self.cfg.serving_tasks)):
                reqs.append((worker, sl))
        else:
            # each root actor serves ITS placed vnode range, explicitly:
            # after a live migration (meta/rescale.py) a store may hold
            # handed-off leftovers OUTSIDE the actor's owned range — an
            # unrestricted scan would double-count them against the
            # range's current owner (docs/scaling.md)
            reqs = [(worker,
                     None if rng is None else list(range(rng[0], rng[1])))
                    for worker, rng in hosts]
        holder: dict = {"rows": []}
        merge = split.merge_executor(lambda: holder["rows"])
        from ..batch.executors import BatchFallback, run_batch
        name = mv.name

        def runner():
            async def _fire():
                frames = []
                for worker, vnodes in reqs:
                    frame = {"type": "batch_task", "job": name,
                             "plan": plan_json, "defs": defs_json}
                    if vnodes is not None:
                        frame["vnodes"] = list(vnodes)
                    # data-plane request: unbounded like _remote_scan
                    frames.append(worker.request(frame, timeout=0))
                return await asyncio.gather(*frames)

            resps = session._await(_fire())
            rows: list = []
            workers_seen = []
            for (worker, _vn), resp in zip(reqs, resps):
                if not resp.get("ok"):
                    raise BatchFallback(
                        f"remote partial task on worker "
                        f"{worker.worker_id}: {resp.get('error')}")
                workers_seen.append(resp.get("worker", worker.worker_id))
                for b in resp["rows"]:
                    rows.append(decode_value_row(base64.b64decode(b),
                                                 types))
            self.stats.bump(tasks_fired_remote=len(reqs),
                            partials_merged=len(rows))
            self.stats.saw_workers(workers_seen)
            holder["rows"] = rows
            return run_batch(merge)

        return runner
