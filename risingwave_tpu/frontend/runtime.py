"""Single-process streaming runtime: jobs, queue sources, changelog buses.

Counterpart of the reference's playground-mode compute runtime
(reference: src/cmd_all/src/playground.rs + LocalStreamManager
src/stream/src/task/stream_manager.rs:96 — one process, real executors,
in-memory state store). Jobs are asyncio tasks draining an executor
pipeline into a MaterializeExecutor; epochs are driven centrally by the
Session (the GlobalBarrierManager stand-in), which pushes chunks + barriers
into every job's QueueSources and awaits barrier completion — the same
inject/collect cycle as the reference's checkpoint loop (SURVEY.md §3.2).

MV-on-MV: each job owns a ChangelogBus republishing its post-materialize
messages; downstream jobs subscribe and receive (snapshot chunks, then live
deltas) — the backfill protocol of executor/backfill.rs reduced to the
between-epochs case (the session only creates jobs at epoch boundaries, so
the snapshot is exactly the upstream state at a barrier cut).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional

from ..common.chunk import StreamChunk, physical_chunk
from ..common.types import Schema
from ..storage.state_table import StateTable
from ..stream.dispatch import MsgQueue
from ..stream.executor import Executor
from ..stream.materialize import MaterializeExecutor
from ..stream.message import Barrier, Message, Watermark


class QueueSource(Executor):
    """Executor fed externally through an asyncio queue."""

    identity = "QueueSource"

    def __init__(self, schema: Schema):
        self.schema = schema
        self.queue = MsgQueue()

    def push(self, msg: Message) -> None:
        self.queue.put_nowait(msg)

    async def execute(self) -> AsyncIterator[Message]:
        while True:
            msg = await self.queue.get()
            if msg is None:      # hard shutdown
                return
            yield msg
            if isinstance(msg, Barrier) and msg.is_stop():
                return


class ChangelogBus:
    """Fan-out of a job's output messages to subscriber queues."""

    def __init__(self) -> None:
        self.subscribers: list[QueueSource] = []

    def publish(self, msg: Message) -> None:
        for q in self.subscribers:
            q.push(msg)

    def subscribe(self, q: QueueSource) -> None:
        self.subscribers.append(q)

    def unsubscribe(self, q: QueueSource) -> None:
        if q in self.subscribers:
            self.subscribers.remove(q)


class StreamJob:
    """One materialized view job: executor pipeline → Materialize → bus."""

    def __init__(self, name: str, pipeline: MaterializeExecutor,
                 sources: list[QueueSource], actors: list = ()):
        self.name = name
        self.pipeline = pipeline
        self.sources = sources
        # extra fragment actors (multi-fragment builds, frontend/fragments):
        # coroutine factories spawned alongside the root pipeline task
        self.actors = list(actors)
        self.bus = ChangelogBus()
        self.table: StateTable = pipeline.table
        self._barrier_events: dict[int, asyncio.Event] = {}
        self._task: Optional[asyncio.Task] = None
        self._actor_tasks: list[asyncio.Task] = []
        self._failure: Optional[BaseException] = None

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        for factory in self.actors:
            self._actor_tasks.append(
                asyncio.ensure_future(self._run_actor(factory), loop=loop))
        self._task = asyncio.ensure_future(self._run(), loop=loop)

    async def _run_actor(self, factory) -> None:
        try:
            await factory()
        except asyncio.CancelledError:
            raise
        except BaseException as e:   # noqa: BLE001 - surfaced on next await
            self._failure = e
            for ev in self._barrier_events.values():
                ev.set()
            raise

    async def _run(self) -> None:
        try:
            async for msg in self.pipeline.execute():
                self.bus.publish(msg)
                if isinstance(msg, Barrier):
                    ev = self._barrier_events.setdefault(
                        msg.epoch.curr, asyncio.Event())
                    ev.set()
        except BaseException as e:   # noqa: BLE001 - surfaced on next await
            self._failure = e
            for ev in self._barrier_events.values():
                ev.set()
            raise

    async def wait_barrier(self, epoch: int) -> None:
        if self._failure is not None:
            # already dead: epochs injected after the failure have no event
            # to set — waiting would hang the conductor forever
            raise RuntimeError(
                f"stream job {self.name!r} failed") from self._failure
        ev = self._barrier_events.setdefault(epoch, asyncio.Event())
        await ev.wait()
        self._barrier_events.pop(epoch, None)
        if self._failure is not None:
            raise RuntimeError(
                f"stream job {self.name!r} failed") from self._failure

    def snapshot_messages(self, epoch_barrier: Barrier,
                          capacity: int = 1024) -> list[Message]:
        """Initial feed for a new subscriber: current MV rows as insert
        chunks (the backfill snapshot), before live deltas resume."""
        rows = list(self.table.scan_all())
        msgs: list[Message] = []
        for i in range(0, len(rows), capacity):
            msgs.append(physical_chunk(
                self.pipeline.schema, rows[i:i + capacity], capacity))
        return msgs

    async def stop(self) -> None:
        for t in self._actor_tasks:
            t.cancel()
        for t in self._actor_tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._actor_tasks.clear()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
