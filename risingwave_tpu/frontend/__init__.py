"""SQL frontend: parser → binder → planner → optimizer → stream/batch plans.

Counterpart of the reference's frontend stack
(reference: src/sqlparser/ (parser), src/frontend/src/binder/,
planner/, optimizer/, stream_fragmenter/ — SURVEY.md §2.6). Python is the
right tool here: the frontend is control-plane, runs once per DDL, and emits
plans whose *runtime* is the jitted executor graph.
"""

from .parser import parse_sql  # noqa: F401
from .catalog import Catalog, SourceDef, TableDef  # noqa: F401
from .session import Session  # noqa: F401
