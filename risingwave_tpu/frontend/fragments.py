"""Multi-fragment pipeline builder over the dispatch fabric.

Round-3 verdict (weak #3): PermitChannel / HashDispatcher / MergeExecutor
existed and passed unit tests but no built pipeline used them. This module
is the integration: a grouped aggregation builds as a MULTI-FRAGMENT job —

    upstream fragment (source → stateless chain)
        └─ HashDispatcher over group keys (update-pair splitting live)
             ├─ PermitChannel → agg actor 0 ─┐
             ├─ PermitChannel → agg actor 1 ─┤  MergeExecutor (barrier
             └─ ...          → agg actor N-1─┘  alignment) → Materialize

mirroring the reference's fragment graph with exchange edges
(reference: dispatch.rs:532 hash dispatch + :635-650 update-pair rule;
merge.rs:114 SelectReceivers alignment; exchange/permit.rs:35 credit flow
control; meta/fragment.py is the planner-side cut this realizes).

State layout: all N agg actors share ONE logical state table (the
reference's model — one table, vnode-prefixed key space, disjoint per
actor). Each actor writes only its own groups; on recovery every actor
scans the shared table and keeps the rows whose group key hashes to its
shard (``load_shard``), so recovery and reschedule work across ANY change
of fragment parallelism — the vnode-bitmap reassignment of
stream/scale.rs:657 expressed as a reload filter.
"""

from __future__ import annotations

from ..stream.dispatch import (
    ChannelSource, HashDispatcher, MergeExecutor, SimpleDispatcher,
    open_channel,
)
from ..stream.hash_agg import HashAggExecutor, agg_state_schema
from ..stream.hash_join import HashJoinExecutor
from ..storage.state_table import StateTable


def build_fragmented_agg(plan, ctx):
    """Build a grouped agg as upstream-fragment → N agg actors → merge.

    Returns the MergeExecutor (the root the enclosing build continues
    from); actor coroutine factories are appended to ``ctx.actors`` for the
    StreamJob to spawn."""
    from .build import build_plan

    cfg = ctx.config
    n = cfg.fragment_parallelism
    upstream = build_plan(plan.input, ctx)

    key_fields = [plan.input.schema[i] for i in plan.group_keys]
    st0 = ctx.state_table(
        agg_state_schema(key_fields, plan.agg_calls),
        list(range(len(plan.group_keys))))

    in_chans = [open_channel(cfg.exchange_permits) for _ in range(n)]
    out_chans = [open_channel(cfg.exchange_permits) for _ in range(n)]
    dispatcher = HashDispatcher(in_chans, plan.group_keys, upstream.schema)

    aggs = []
    for i in range(n):
        st = None
        if st0 is not None:
            st = StateTable(ctx.store, st0.table_id, st0.schema,
                            list(st0.pk_indices))
        src = ChannelSource(in_chans[i], upstream.schema)
        aggs.append(HashAggExecutor(
            src, list(plan.group_keys), list(plan.agg_calls),
            state_table=st, table_capacity=cfg.agg_table_capacity,
            out_capacity=cfg.chunk_capacity, load_shard=(i, n),
            hbm_group_budget=cfg.agg_hbm_budget))

    async def run_upstream():
        async for msg in upstream.execute():
            await dispatcher.dispatch(msg)

    def agg_actor(i: int):
        async def run():
            out = SimpleDispatcher(out_chans[i])
            async for msg in aggs[i].execute():
                await out.dispatch(msg)
        return run

    ctx.actors.append(run_upstream)
    for i in range(n):
        ctx.actors.append(agg_actor(i))
    return MergeExecutor(out_chans, aggs[0].schema)


def build_fragmented_join(plan, ctx, join_types):
    """Build an equi-join as TWO upstream fragments → N join actors → merge.

    Both inputs hash-dispatch by their join keys (the same vnode hash on
    each side, so matching keys always land on the same actor — the
    reference's requirement that both exchange edges of a HashJoin share
    one distribution, dispatch.rs:532), with update-pair splitting live on
    both edges (dispatch.rs:635-650). Each actor joins its key shard on
    its own device arena; the N actors share the two logical state tables
    (disjoint key ranges) and recovery re-filters rows by shard
    (``load_shard``), so kill/recovery works across ANY parallelism change.
    """
    from .build import build_plan

    cfg = ctx.config
    n = cfg.fragment_parallelism
    left_up = build_plan(plan.left, ctx)
    right_up = build_plan(plan.right, ctx)

    from .build import join_state_pk
    lst0 = ctx.state_table(plan.left.schema,
                           join_state_pk(plan.left_keys, plan.left.pk))
    rst0 = ctx.state_table(plan.right.schema,
                           join_state_pk(plan.right_keys, plan.right.pk))

    l_chans = [open_channel(cfg.exchange_permits) for _ in range(n)]
    r_chans = [open_channel(cfg.exchange_permits) for _ in range(n)]
    out_chans = [open_channel(cfg.exchange_permits) for _ in range(n)]
    l_disp = HashDispatcher(l_chans, plan.left_keys, left_up.schema)
    r_disp = HashDispatcher(r_chans, plan.right_keys, right_up.schema)

    joins = []
    for i in range(n):
        lst = rst = None
        if lst0 is not None:
            lst = StateTable(ctx.store, lst0.table_id, lst0.schema,
                             list(lst0.pk_indices))
            rst = StateTable(ctx.store, rst0.table_id, rst0.schema,
                             list(rst0.pk_indices))
        joins.append(HashJoinExecutor(
            ChannelSource(l_chans[i], left_up.schema),
            ChannelSource(r_chans[i], right_up.schema),
            list(plan.left_keys), list(plan.right_keys),
            join_type=join_types[plan.kind], condition=plan.condition,
            left_state_table=lst, right_state_table=rst,
            key_capacity=cfg.join_key_capacity,
            bucket_width=cfg.join_bucket_width,
            out_capacity=cfg.chunk_capacity, load_shard=(i, n),
            hbm_key_budget=cfg.join_hbm_budget))

    def upstream_actor(up, disp):
        async def run():
            async for msg in up.execute():
                await disp.dispatch(msg)
        return run

    def join_actor(i: int):
        async def run():
            out = SimpleDispatcher(out_chans[i])
            async for msg in joins[i].execute():
                await out.dispatch(msg)
        return run

    ctx.actors.append(upstream_actor(left_up, l_disp))
    ctx.actors.append(upstream_actor(right_up, r_disp))
    for i in range(n):
        ctx.actors.append(join_actor(i))
    return MergeExecutor(out_chans, joins[0].schema)
