"""Prometheus text-format metrics endpoint.

Counterpart of the reference's per-node Prometheus exporters
(reference: src/stream/src/executor/monitor/streaming_stats.rs:27-88 —
barrier latency / actor exec counters scraped by the generated Grafana
dashboards, docs/metrics.md semantics). ``render_metrics`` flattens
``Session.metrics()`` into the exposition format; ``serve_metrics``
mounts it on a tiny threaded HTTP server at ``/metrics`` so a stock
Prometheus scrape config works against a playground session.

``Session.metrics()`` federates worker processes' stats over the control
socket, so worker-hosted jobs' counters appear in the same exposition —
one scrape covers the whole cluster (the reference scrapes each compute
node separately; here the session is the aggregation point).
"""

from __future__ import annotations

import http.server
import threading
from typing import Optional


def _sanitize(s: str) -> str:
    out = []
    for ch in str(s):
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    return "".join(out)


def render_metrics(session) -> str:
    """Session.metrics() → Prometheus exposition text."""
    m = session.metrics()
    lines = [
        "# HELP rw_epoch Last completed epoch.",
        "# TYPE rw_epoch counter",
        f"rw_epoch {m['epoch']}",
    ]
    lat = m.get("barrier_latency") or {}
    lines += ["# HELP rw_barrier_latency_ms Barrier inject-to-collect "
              "latency percentile (windowed).",
              "# TYPE rw_barrier_latency_ms gauge"]
    for key, q in (("p50_ms", "0.5"), ("p90_ms", "0.9"), ("p99_ms", "0.99")):
        v = lat.get(key)
        if v is not None:
            lines.append(
                f'rw_barrier_latency_ms{{quantile="{q}"}} {v}')
    barrier = m.get("barrier") or {}
    if barrier:
        lines += ["# HELP rw_barrier_stage_seconds Per-stage barrier "
                  "waterfall percentile over the ledger history ring "
                  "(common/barrier_ledger.py stage vocabulary).",
                  "# TYPE rw_barrier_stage_seconds gauge"]
        for stage, pct in sorted((barrier.get("stages") or {}).items()):
            for key, q in (("p50_ms", "0.5"), ("p99_ms", "0.99")):
                v = pct.get(key)
                if v is not None:
                    lines.append(
                        f'rw_barrier_stage_seconds'
                        f'{{stage="{_sanitize(stage)}",quantile="{q}"}} '
                        f'{round(v / 1e3, 6)}')
        lines += ["# HELP rw_barrier_inflight Barriers injected but not "
                  "yet fully collected (the async pipeline's in-flight "
                  "window occupancy).",
                  "# TYPE rw_barrier_inflight gauge",
                  f'rw_barrier_inflight {barrier.get("inflight", 0)}',
                  "# HELP rw_barrier_total Barriers completed by result "
                  "(ok = collected + committed, failed = a job died "
                  "during collection).",
                  "# TYPE rw_barrier_total counter"]
        totals = barrier.get("total") or {}
        for result in ("ok", "failed"):
            lines.append(
                f'rw_barrier_total{{result="{result}"}} '
                f'{totals.get(result, 0)}')
    lines += ["# HELP rw_executor_counter Per-executor streaming counters.",
              "# TYPE rw_executor_counter counter"]
    for job, pipeline in (m.get("jobs") or {}).items():
        for ident, stats in pipeline.items():
            for name, value in stats.items():
                if not isinstance(value, (int, float)):
                    continue
                lines.append(
                    f'rw_executor_counter{{job="{_sanitize(job)}",'
                    f'executor="{_sanitize(ident)}",'
                    f'counter="{_sanitize(name)}"}} {value}')
    lines += ["# HELP rw_state_bytes Device-state bytes per job.",
              "# TYPE rw_state_bytes gauge"]
    for job, nbytes in (m.get("state_bytes") or {}).items():
        total = nbytes if isinstance(nbytes, (int, float)) else \
            sum(v for v in nbytes.values()
                if isinstance(v, (int, float)))
        lines.append(f'rw_state_bytes{{job="{_sanitize(job)}"}} {total}')
    workers = m.get("workers") or []
    if workers:
        lines += ["# HELP rw_worker_up Worker process liveness "
                  "(1 = serving, 0 = dead).",
                  "# TYPE rw_worker_up gauge"]
        for w in workers:
            lines.append(
                f'rw_worker_up{{worker="{w["worker"]}"}} '
                f'{0 if w.get("dead") else 1}')
    if "slow_epoch_total" in m:
        lines += ["# HELP rw_slow_epoch_total Epochs whose barrier "
                  "latency tripped the slow-epoch threshold.",
                  "# TYPE rw_slow_epoch_total counter",
                  f"rw_slow_epoch_total {m['slow_epoch_total']}"]
    storage = m.get("storage") or {}
    if storage:
        lines += ["# HELP rw_storage_stat Durable-tier counters "
                  "(hummock: version id, level shape, compaction + "
                  "vacuum progress).",
                  "# TYPE rw_storage_stat gauge"]
        tier = _sanitize(storage.get("tier", "unknown"))
        for name, value in storage.items():
            if not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            lines.append(
                f'rw_storage_stat{{tier="{tier}",'
                f'stat="{_sanitize(name)}"}} {value}')
        for c in storage.get("compactors", ()):
            lines.append(
                f'rw_compactor_up{{worker="{c["worker"]}"}} '
                f'{0 if c.get("dead") else 1}')
    exchange = m.get("exchange") or []
    if exchange:
        lines += ["# HELP rw_exchange_stat Per-exchange-edge counters "
                  "(chunks/bytes forwarded, permit waits, backlog depth) "
                  "for cross-worker fragment edges.",
                  "# TYPE rw_exchange_stat gauge"]
        for e in exchange:
            labels = (f'edge="{_sanitize(str(e.get("edge")))}",'
                      f'dir="{_sanitize(str(e.get("dir")))}",'
                      f'worker="{e.get("worker")}"')
            for stat in ("chunks", "bytes", "permits_waited", "barriers",
                         "backlog"):
                value = e.get(stat)
                if isinstance(value, (int, float)):
                    lines.append(
                        f'rw_exchange_stat{{{labels},'
                        f'stat="{stat}"}} {value}')
    serving = m.get("serving") or {}
    if serving:
        lines += ["# HELP rw_serving_stat Serving-plane counters "
                  "(plan-cache hits/misses, two-phase tasks fired, "
                  "partial states merged, read latency percentiles).",
                  "# TYPE rw_serving_stat gauge"]
        for name, value in serving.items():
            if not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            lines.append(
                f'rw_serving_stat{{stat="{_sanitize(name)}"}} {value}')
        for wid, n in (serving.get("task_workers") or {}).items():
            lines.append(
                f'rw_serving_task_total{{worker="{_sanitize(wid)}"}} {n}')
    lead = m.get("leadership") or {}
    if lead:
        lines += ["# HELP rw_leader_term This session's lease term "
                  "(strictly monotonic across failovers; the un-fenced "
                  "conductor holds the highest).",
                  "# TYPE rw_leader_term gauge",
                  f'rw_leader_term {lead.get("term") or 0}',
                  "# HELP rw_leader_is_writer 1 when this session is "
                  "the un-fenced barrier conductor, else 0.",
                  "# TYPE rw_leader_is_writer gauge",
                  f'rw_leader_is_writer {lead.get("is_writer", 0)}',
                  "# HELP rw_failover_total Leadership transitions this "
                  "session performed, by kind (promotion, demotion, "
                  "election_lost).",
                  "# TYPE rw_failover_total counter",
                  f'rw_failover_total{{kind="promotion"}} '
                  f'{lead.get("promotions", 0)}',
                  f'rw_failover_total{{kind="demotion"}} '
                  f'{lead.get("demotions", 0)}',
                  f'rw_failover_total{{kind="election_lost"}} '
                  f'{lead.get("elections_lost", 0)}',
                  "# HELP rw_failover_duration_seconds leader_down-to-"
                  "promoted wall seconds of the most recent failover "
                  "this session won.",
                  "# TYPE rw_failover_duration_seconds gauge"]
        if lead.get("last_failover_ms") is not None:
            lines.append(f'rw_failover_duration_seconds '
                         f'{round(lead["last_failover_ms"] / 1e3, 6)}')
    chaos = m.get("chaos") or {}
    if chaos:
        lines += ["# HELP rw_chaos_injection_total Network fault plane "
                  "injections by kind (rpc/faults.py), session process "
                  "plus every worker's plane.",
                  "# TYPE rw_chaos_injection_total counter"]
        merged: dict = dict(chaos.get("injections") or {})
        for _wid, wc in (chaos.get("workers") or {}).items():
            for kind, n in (wc.get("injections") or {}).items():
                merged[kind] = merged.get(kind, 0) + n
        for kind, n in sorted(merged.items()):
            lines.append(
                f'rw_chaos_injection_total{{kind="{_sanitize(kind)}"}} '
                f'{n}')
        lines += ["# HELP rw_chaos_stat Fault-plane hardening counters "
                  "(fencing generation, stale acks dropped, duplicate "
                  "replies/acks deduped).",
                  "# TYPE rw_chaos_stat gauge"]
        for stat in ("generation", "stale_acks_dropped",
                     "dup_replies_dropped", "dup_acks_dropped"):
            value = chaos.get(stat)
            if isinstance(value, (int, float)):
                lines.append(
                    f'rw_chaos_stat{{stat="{stat}"}} {value}')
    scaler = m.get("autoscaler") or {}
    if scaler:
        # monotonic total, not the capped decision-history ring length
        n_decisions = scaler.get("decisions_total",
                                 len(scaler.get("decisions") or ()))
        lines += ["# HELP rw_autoscaler_stat Elastic scaling plane "
                  "counters (meta/autoscaler.py decisions, executed live "
                  "migrations, moved vnodes).",
                  "# TYPE rw_autoscaler_stat counter",
                  f'rw_autoscaler_stat{{stat="decisions"}} '
                  f'{n_decisions}',
                  f'rw_autoscaler_stat{{stat="migrations"}} '
                  f'{scaler.get("migrations", 0)}',
                  f'rw_autoscaler_stat{{stat="moved_vnodes"}} '
                  f'{scaler.get("moved_vnodes", 0)}',
                  "# HELP rw_autoscaler_enabled Autoscaler policy "
                  "armed (config [autoscaler] enabled).",
                  "# TYPE rw_autoscaler_enabled gauge",
                  f'rw_autoscaler_enabled '
                  f'{1 if scaler.get("enabled") else 0}']
        lines += ["# HELP rw_autoscaler_parallelism Observed fragment "
                  "parallelism per spanning job.",
                  "# TYPE rw_autoscaler_parallelism gauge"]
        for job, st in sorted((scaler.get("jobs") or {}).items()):
            sig = st.get("signals") or {}
            if "parallelism" in sig:
                lines.append(
                    f'rw_autoscaler_parallelism{{job="{_sanitize(job)}"}} '
                    f'{sig["parallelism"]}')
    profiling = m.get("profiling") or {}
    if profiling:
        # merge worker processes' dispatch records under the same
        # qualnames (one scrape covers the whole cluster's dispatches)
        merged: dict = {}
        sources = [profiling.get("dispatch") or {}]
        sources += [(wp or {}) for wp in
                    (profiling.get("workers") or {}).values()]
        for src in sources:
            for qn, rec in src.items():
                agg = merged.setdefault(
                    qn, {"calls": 0, "total_s": 0.0, "compiles": 0,
                         "complete_s": 0.0})
                agg["calls"] += rec.get("calls", 0)
                agg["total_s"] += rec.get("total_s", 0.0)
                agg["compiles"] += rec.get("compiles", 0)
                agg["complete_s"] += rec.get("complete_s", 0.0)
        lines += ["# HELP rw_dispatch_total Jitted-epoch dispatches "
                  "per qualname (common/profiling.py), session plus "
                  "every worker process.",
                  "# TYPE rw_dispatch_total counter"]
        for qn, rec in sorted(merged.items()):
            lines.append(
                f'rw_dispatch_total{{qualname="{_sanitize(qn)}"}} '
                f'{rec["calls"]}')
        lines += ["# HELP rw_dispatch_seconds Cumulative dispatch "
                  "wall seconds per qualname.",
                  "# TYPE rw_dispatch_seconds counter"]
        for qn, rec in sorted(merged.items()):
            lines.append(
                f'rw_dispatch_seconds{{qualname="{_sanitize(qn)}"}} '
                f'{round(rec["total_s"], 6)}')
        lines += ["# HELP rw_compile_total Jit-cache-miss/recompile "
                  "events per qualname.",
                  "# TYPE rw_compile_total counter"]
        for qn, rec in sorted(merged.items()):
            lines.append(
                f'rw_compile_total{{qualname="{_sanitize(qn)}"}} '
                f'{rec["compiles"]}')
        lines += ["# HELP rw_dispatch_complete_seconds Cumulative "
                  "enqueue-to-host-visible completion seconds per "
                  "qualname, resolved when a fetch future over the "
                  "dispatch's outputs lands (profiler honesty under "
                  "async dispatch — enqueue wall time reads near-zero "
                  "while pipelining).",
                  "# TYPE rw_dispatch_complete_seconds counter"]
        for qn, rec in sorted(merged.items()):
            lines.append(
                f'rw_dispatch_complete_seconds'
                f'{{qualname="{_sanitize(qn)}"}} '
                f'{round(rec["complete_s"], 6)}')
        hbm = profiling.get("hbm") or {}
        if hbm:
            lines += ["# HELP rw_hbm_bytes Per-job/per-executor resident "
                      "device-state bytes charged to the HBM ledger "
                      "(federated from every worker).",
                      "# TYPE rw_hbm_bytes gauge"]
            for job, entry in (hbm.get("jobs") or {}).items():
                lines.append(
                    f'rw_hbm_bytes{{job="{_sanitize(job)}",'
                    f'executor="_total"}} {entry.get("bytes", 0)}')
                for ident, nb in (entry.get("executors") or {}).items():
                    lines.append(
                        f'rw_hbm_bytes{{job="{_sanitize(job)}",'
                        f'executor="{_sanitize(ident)}"}} {nb}')
            lines += ["# HELP rw_hbm_headroom_bytes HBM capacity minus "
                      "resident state and analyzed peak temp bytes "
                      "([observability] hbm_capacity_bytes).",
                      "# TYPE rw_hbm_headroom_bytes gauge",
                      f'rw_hbm_headroom_bytes '
                      f'{hbm.get("headroom_bytes", 0)}']
    pipe = m.get("pipeline") or {}
    if pipe:
        lines += ["# HELP rw_pipeline_depth Configured asynchronous "
                  "epoch pipeline depth ([streaming] pipeline_depth; "
                  "1 = synchronous ticks).",
                  "# TYPE rw_pipeline_depth gauge",
                  f"rw_pipeline_depth {pipe.get('depth', 1)}",
                  "# HELP rw_pipeline_stat Async epoch pipeline "
                  "counters: flushes deferred across ticks, explicit "
                  "drains, fetch completions, max in-flight dispatch "
                  "occupancy, and currently pending flushes.",
                  "# TYPE rw_pipeline_stat gauge"]
        for k in ("pending_flushes", "deferred_flushes", "drains",
                  "completions", "max_inflight"):
            lines.append(f'rw_pipeline_stat{{stat="{k}"}} '
                         f'{pipe.get(k, 0)}')
    het = m.get("hetero") or {}
    if het.get("jobs"):
        lines += ["# HELP rw_hetero_jobs MVs registered with the "
                  "heterogeneous tick compiler "
                  "(stream/tick_compiler.py).",
                  "# TYPE rw_hetero_jobs gauge",
                  f"rw_hetero_jobs {het.get('jobs', 0)}",
                  "# HELP rw_hetero_dispatches_per_tick Compiled "
                  "schedule size: epoch dispatches issued per tick "
                  "(shape-class supergroups + mega-epochs).",
                  "# TYPE rw_hetero_dispatches_per_tick gauge",
                  f"rw_hetero_dispatches_per_tick "
                  f"{het.get('dispatches_per_tick', 0)}",
                  "# HELP rw_hetero_schedule_compiles Schedule "
                  "recompilations (DDL-driven re-bucketing) since "
                  "session start.",
                  "# TYPE rw_hetero_schedule_compiles counter",
                  f"rw_hetero_schedule_compiles "
                  f"{het.get('schedule_compiles', 0)}",
                  "# HELP rw_hetero_group_jobs Member MVs per compiled "
                  "dispatch group.",
                  "# TYPE rw_hetero_group_jobs gauge"]
        for i, g in enumerate(het.get("groups") or []):
            lines.append(
                f'rw_hetero_group_jobs{{group="{i}",'
                f'kind="{_sanitize(g.get("kind", ""))}"}} '
                f'{len(g.get("jobs") or [])}')
        attr = het.get("attribution") or {}
        if any(attr.values()):
            lines += ["# HELP rw_hetero_flush_weight Per-job cost "
                      "attribution weight (cumulative dirty groups "
                      "flushed) within each fused dispatch.",
                      "# TYPE rw_hetero_flush_weight counter"]
            for qn, jobs in sorted(attr.items()):
                for job, w in sorted(jobs.items()):
                    lines.append(
                        f'rw_hetero_flush_weight'
                        f'{{qualname="{_sanitize(qn)}",'
                        f'job="{_sanitize(job)}"}} {w}')
    retry = m.get("retry") or {}
    if retry:
        lines += ["# HELP rw_retry_total Per-site boundary retry "
                  "counters (object store / broker / sink delivery).",
                  "# TYPE rw_retry_total counter"]
        for site, counters in retry.items():
            for event, value in counters.items():
                lines.append(
                    f'rw_retry_total{{site="{_sanitize(site)}",'
                    f'event="{_sanitize(event)}"}} {value}')
    sinks = m.get("sinks") or {}
    if sinks:
        lines += ["# HELP rw_sink_degraded Sink delivery health "
                  "(1 = degraded: backend down, log accumulating).",
                  "# TYPE rw_sink_degraded gauge",
                  "# HELP rw_sink_stat Sink-decouple counters "
                  "(pending undelivered rows, delivery failures, "
                  "delivered epoch).",
                  "# TYPE rw_sink_stat gauge"]
        for name, h in sinks.items():
            lines.append(
                f'rw_sink_degraded{{sink="{_sanitize(name)}"}} '
                f'{1 if h.get("degraded") else 0}')
            for stat, value in h.items():
                if not isinstance(value, (int, float)) \
                        or isinstance(value, bool):
                    continue
                lines.append(
                    f'rw_sink_stat{{sink="{_sanitize(name)}",'
                    f'stat="{_sanitize(stat)}"}} {value}')
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Threaded /metrics endpoint over a live Session."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        sess = session

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):       # noqa: N802 - stdlib API
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = render_metrics(sess).encode()
                except Exception as e:   # session mid-shutdown
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-endpoint")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def serve_metrics(session, host: str = "127.0.0.1",
                  port: int = 0) -> MetricsServer:
    return MetricsServer(session, host, port)
