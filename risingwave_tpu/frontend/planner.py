"""Planner: bound SELECT → stream plan tree (with stream-key derivation).

Counterpart of the reference's Planner + stream-side optimizer phases
(reference: src/frontend/src/planner/mod.rs:37,53 and
optimizer/plan_node/stream_*.rs). Each plan node carries its ``pk`` — the
stream key that identifies rows across updates (the reference's logical_pk):
Source appends a hidden ``_row_id``; Agg's pk is its group keys; Join's is
the concatenation of both sides' pks; Project keeps pk columns alive by
appending hidden columns when the SELECT list drops them (exactly the
reference's add-logical-pk rule).

Scalar-subquery comparisons in WHERE lower to DynamicFilter; ORDER BY +
LIMIT lowers to TopN; DISTINCT lowers to group-by-all-columns Agg
(reference: the corresponding optimizer rules under
src/frontend/src/optimizer/rule/).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

from ..common.types import Field, Schema, TIMESTAMP
from ..expr.agg import AggCall
from ..expr.expr import Expr, FunctionCall, InputRef, Literal, call
from ..ops.topn import OrderSpec
from . import sqlast as A
from .binder import (
    AGG_KINDS, WINDOW_ONLY_KINDS, BindError, BoundAgg, BoundWindow,
    ExprBinder, Scope, ScopeColumn, _AggPlaceholder, _SubqueryPlaceholder,
    _WindowPlaceholder, contains_placeholder, rewrite_placeholders,
)
from .catalog import Catalog, CatalogError, MaterializedViewDef, SourceDef, TableDef


class PlanError(ValueError):
    pass


# -- plan nodes ---------------------------------------------------------------


@dataclasses.dataclass
class PlanNode:
    schema: Schema
    pk: tuple                        # stream-key column indices

    @property
    def children(self) -> tuple:
        return ()

    def label(self) -> str:
        return type(self).__name__[1:]

    def explain(self, indent: int = 0) -> str:
        lines = [" " * indent + self._describe()]
        for c in self.children:
            lines.append(c.explain(indent + 2))
        return "\n".join(lines)

    def _describe(self) -> str:
        return f"{self.label()} {{pk={list(self.pk)}}}"


@dataclasses.dataclass
class PSource(PlanNode):
    source: SourceDef
    row_id_index: int = -1           # hidden _row_id column index


@dataclasses.dataclass
class PTableScan(PlanNode):
    table: TableDef


@dataclasses.dataclass
class PMvScan(PlanNode):
    mv: MaterializedViewDef


@dataclasses.dataclass
class PExchange(PlanNode):
    """Leaf standing in for a remote-exchange edge inside a SHIPPED
    fragment subtree: the fragment below this point runs in a different
    fragment (possibly on a different worker process), and its output
    arrives here over permit-metered exchange channels (reference: the
    ExchangeNode leaves the fragmenter leaves behind,
    src/frontend/src/stream_fragmenter/mod.rs:115). ``upstream`` names
    the feeding fragment id in the job's span graph; the worker's build
    factory resolves it to a merge over the edge's channels."""

    upstream: int = -1

    def _describe(self):
        return f"Exchange {{upstream=f{self.upstream}, pk={list(self.pk)}}}"


@dataclasses.dataclass
class PRemoteFragment(PlanNode):
    """A batch stage shipped to the worker PROCESS hosting its state; the
    session sees only the stage's output rows (reference: distributed
    batch stages over compute nodes,
    src/frontend/src/scheduler/distributed/query.rs:69,115).
    ``fetch()`` runs the remote task and returns physical rows."""

    job: str = ""
    fetch: Any = None                # () -> list[physical row tuples]

    @property
    def children(self):
        return ()

    def _describe(self):
        return f"RemoteFragment {{job={self.job}}}"


@dataclasses.dataclass
class PProject(PlanNode):
    input: PlanNode
    exprs: tuple                     # runtime Expr per output column

    @property
    def children(self):
        return (self.input,)

    def _describe(self):
        return (f"Project {{exprs={[_expr_str(e) for e in self.exprs]}, "
                f"pk={list(self.pk)}}}")


@dataclasses.dataclass
class PFilter(PlanNode):
    input: PlanNode
    predicate: Expr

    @property
    def children(self):
        return (self.input,)

    def _describe(self):
        return f"Filter {{pred={_expr_str(self.predicate)}, pk={list(self.pk)}}}"


@dataclasses.dataclass
class PHopWindow(PlanNode):
    input: PlanNode
    time_col: int
    slide: int
    size: int

    @property
    def children(self):
        return (self.input,)


@dataclasses.dataclass
class PAgg(PlanNode):
    input: PlanNode
    group_keys: tuple                # input column indices
    agg_calls: tuple                 # AggCall...
    append_only_input: bool = False
    eowc: bool = False
    #: batch two-phase aggregation (batch/lower.py split_two_phase):
    #: "single" = ordinary one-shot agg; "partial" = emit raw per-group
    #: state lanes instead of projected outputs — the distributed serving
    #: plane ships partial-phase subtrees to the workers owning the vnode
    #: slices and merges the lanes in the session (reference: the
    #: two-phase agg split in src/frontend/src/scheduler/distributed/
    #: query.rs:69-115). ``schema`` of a partial node is the lane
    #: transport schema, not the user-facing agg schema.
    phase: str = "single"

    @property
    def children(self):
        return (self.input,)

    def _describe(self):
        calls = [f"{c.kind}({c.arg if c.arg >= 0 else '*'})"
                 for c in self.agg_calls]
        ph = "" if self.phase == "single" else f", phase={self.phase}"
        return (f"{'SimpleAgg' if not self.group_keys else 'HashAgg'} "
                f"{{keys={list(self.group_keys)}, aggs={calls}, "
                f"pk={list(self.pk)}{ph}}}")


@dataclasses.dataclass
class PJoin(PlanNode):
    left: PlanNode
    right: PlanNode
    kind: str                        # inner/left/right/full/left_semi/left_anti
    left_keys: tuple
    right_keys: tuple
    condition: Optional[Expr]        # residual non-equi condition, over concat
    #: PG NOT IN semantics for a left_anti join: a NULL in the subquery
    #: (build side) means NO probe row passes. The planner also filters
    #: NULL probe keys below the join (they never pass NOT IN).
    null_aware: bool = False

    @property
    def children(self):
        return (self.left, self.right)

    def _describe(self):
        na = ", null_aware" if self.null_aware else ""
        return (f"HashJoin {{type={self.kind}, on={list(self.left_keys)}="
                f"{list(self.right_keys)}{na}, pk={list(self.pk)}}}")


@dataclasses.dataclass
class PTopN(PlanNode):
    input: PlanNode
    order: tuple                     # OrderSpec...
    limit: int
    offset: int
    with_ties: bool = False
    group_by: tuple = ()

    @property
    def children(self):
        return (self.input,)

    def _describe(self):
        return (f"TopN {{order={[(o.col, 'desc' if o.desc else 'asc') for o in self.order]}, "
                f"limit={self.limit}, offset={self.offset}, pk={list(self.pk)}}}")


@dataclasses.dataclass
class PDynFilter(PlanNode):
    input: PlanNode
    right: PlanNode                  # 1-row plan producing the bound
    key_col: int
    cmp: str

    @property
    def children(self):
        return (self.input, self.right)

    def _describe(self):
        return f"DynamicFilter {{col={self.key_col} {self.cmp} <sub>, pk={list(self.pk)}}}"


@dataclasses.dataclass
class PUnion(PlanNode):
    inputs: tuple

    @property
    def children(self):
        return tuple(self.inputs)


@dataclasses.dataclass
class PValues(PlanNode):
    rows: tuple


@dataclasses.dataclass
class POverWindow(PlanNode):
    """Window functions over a shared (partition, order) frame; output =
    input columns ⧺ one column per call (reference: StreamOverWindow plan
    node, optimizer/plan_node/stream_over_window.rs)."""

    input: PlanNode
    calls: tuple                     # stream.over_window.WindowCall...
    eowc: bool = False

    @property
    def children(self):
        return (self.input,)


@dataclasses.dataclass
class PTemporalJoin(PlanNode):
    """Process-time lookup join (reference: temporal_join.rs:352): the
    stream side probes the right relation's CURRENT materialized rows; no
    stream-side state, no retraction on table changes."""

    input: PlanNode                  # the stream side
    right_kind: str                  # "table" | "mv"
    right_def: object                # TableDef | MaterializedViewDef
    left_keys: tuple
    right_keys: tuple
    outer: bool = False
    condition: object = None

    @property
    def children(self):
        return (self.input,)


@dataclasses.dataclass
class PProjectSet(PlanNode):
    """Set-returning projection: each input row yields one output row per
    element of the table function's result (reference: ProjectSetExecutor,
    src/stream/src/executor/project_set.rs). ``exprs`` are per-output-col;
    exactly one is a _TableFuncExpr. Output pk = input pk ⧺ hidden index."""

    input: PlanNode
    exprs: tuple

    @property
    def children(self):
        return (self.input,)


def _expr_str(e: Expr) -> str:
    if isinstance(e, InputRef):
        return f"${e.index}"
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, FunctionCall):
        return f"{e.name}({', '.join(_expr_str(a) for a in e.args)})"
    return type(e).__name__


# -- helpers ------------------------------------------------------------------


def _conjuncts(e: A.Expr) -> list:
    if isinstance(e, A.BinaryOp) and e.op == "AND":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


_CMP_TO_FN = {
    ">": "greater_than", ">=": "greater_than_or_equal",
    "<": "less_than", "<=": "less_than_or_equal",
}
_CMP_FLIP = {">": "<", ">=": "<=", "<": ">", "<=": ">="}


class Planner:
    """Plans one SELECT against the catalog. ``fresh`` — hidden-column name
    uniquifier shared across nested planners."""

    def __init__(self, catalog: Catalog, lenient: bool = False,
                 session=None):
        # lenient = DDL replay during recovery: rules tightened after a
        # statement was logged must WARN, not make the store unloadable
        self.catalog = catalog
        self.lenient = lenient
        # live Session backing the rw_catalog telemetry relations; None
        # in session-less contexts (describe, DDL replay) — builders
        # then return their schema with no rows
        self.session = session

    # -- entry ----------------------------------------------------------------

    def plan_select(self, sel: A.Select) -> PlanNode:
        if sel.union_all is not None:
            left = self.plan_select(dataclasses.replace(sel, union_all=None))
            right = self.plan_select(sel.union_all)
            if len(left.schema) != len(right.schema):
                raise PlanError("UNION ALL arms must have equal arity")
            # align pk layout: use full row as key via dedicated hidden cols
            # (reference unions carry a source-id in the stream key)
            return PUnion(schema=left.schema, pk=tuple(range(len(left.schema))),
                          inputs=(left, right))

        if sel.from_ is None:
            return self._plan_no_from(sel)

        # WHERE: split conjuncts into dynamic-filter rewrites and plain ones;
        # plain equality conjuncts may be consumed as join keys by keyless
        # (comma-syntax) joins during relation planning — the reference's
        # predicate-pushdown-into-join rule
        dyn_conjuncts: list = []
        in_conjuncts: list = []
        plain: list = []
        if sel.where is not None:
            for conj in _conjuncts(sel.where):
                if isinstance(conj, A.InSubquery):
                    in_conjuncts.append(conj)
                elif self._has_subquery(conj):
                    dyn_conjuncts.append(conj)
                else:
                    plain.append(conj)

        node, scope = self._plan_relation(sel.from_, plain)

        for conj in plain:
            pred = ExprBinder(scope).bind(conj)
            node = PFilter(schema=node.schema, pk=node.pk, input=node,
                           predicate=pred)

        # IN (SELECT …) conjuncts become left semi joins; NOT IN becomes
        # a NULL-AWARE left anti join (reference: subquery unnesting Apply
        # rules, src/frontend/src/optimizer/rule/apply_join_transpose_rule.rs):
        # NULL probe keys are filtered below the join, and a NULL produced
        # by the subquery yields no rows (batch) / a loud error (streaming).
        for conj in in_conjuncts:
            node = self._plan_in_subquery(conj, node, scope)

        # dynamic filters apply pre-projection (reference: the subquery
        # Apply-rewrite places DynamicFilter below the projection)
        for conj in dyn_conjuncts:
            node = self._plan_dynamic_filter(conj, node, scope)

        has_aggs = bool(sel.group_by) or self._select_has_aggs(sel)
        has_windows = self._select_has_windows(sel)
        if self._select_has_table_funcs(sel):
            if has_aggs or has_windows:
                raise PlanError("set-returning functions cannot mix with "
                                "aggregates/window functions; use a subquery")
            node, scope = self._plan_project_set(sel, node, scope)
        elif has_windows:
            if has_aggs:
                raise PlanError(
                    "window functions cannot mix with GROUP BY/aggregates "
                    "in one SELECT; use a subquery")
            node, scope = self._plan_over_window(sel, node, scope)
        elif has_aggs:
            node, scope = self._plan_agg(sel, node, scope)
        else:
            node, scope = self._plan_projection(sel, node, scope)

        if sel.having is not None and not has_aggs:
            raise PlanError("HAVING without aggregation")

        if sel.distinct:
            # dedup over the VISIBLE columns; hidden stream-key columns are
            # dropped (the distinct keys become the new stream key)
            visible = tuple(i for i, f in enumerate(node.schema)
                            if not f.name.startswith("_"))
            if len(visible) != len(node.schema):
                node = PProject(
                    schema=node.schema.select(visible), pk=(), input=node,
                    exprs=tuple(InputRef(i, node.schema[i].type)
                                for i in visible))
            n = len(node.schema)
            node = PAgg(
                schema=Schema(tuple(node.schema)), pk=tuple(range(n)),
                input=node, group_keys=tuple(range(n)), agg_calls=())

        if sel.order_by or sel.limit is not None:
            node = self._plan_topn(sel, node, scope)
        return node

    # -- FROM -----------------------------------------------------------------

    def _plan_relation(self, rel: A.Relation, pending_conjuncts=None):
        if isinstance(rel, A.TableRef):
            return self._plan_table_ref(rel)
        if isinstance(rel, A.TableFuncRef):
            return self._plan_table_func_ref(rel)
        if isinstance(rel, A.WindowTVF):
            return self._plan_window_tvf(rel)
        if isinstance(rel, A.SubqueryRef):
            node = self.plan_select(rel.query)
            return node, Scope.of_schema(node.schema, rel.alias)
        if isinstance(rel, A.Join):
            return self._plan_join(rel, pending_conjuncts)
        raise PlanError(f"unsupported relation {type(rel).__name__}")

    def _plan_table_ref(self, ref: A.TableRef):
        # system catalogs (pg_catalog / information_schema / rw_catalog)
        # resolve before user relations, served as constant VALUES from
        # the live catalog (reference: frontend system_catalog/)
        from .system_catalog import system_relation
        sysrel = system_relation(self.catalog, ref.name,
                                 session=self.session)
        if sysrel is not None:
            schema, rows = sysrel
            lit_rows = tuple(
                tuple(Literal(v, f.type) for v, f in zip(r, schema))
                for r in rows)
            alias = ref.alias or ref.name.rsplit(".", 1)[-1]
            node = PValues(schema=schema, pk=(), rows=lit_rows)
            return node, Scope.of_schema(schema, alias)
        # BI tools qualify user relations with the schema pg_tables
        # reports ('public.t'): the catalog is keyed on bare names
        name = ref.name
        if name.startswith("public."):
            name = name[len("public."):]
        kind, d = self.catalog.resolve_relation(name)
        alias = ref.alias or name
        if kind == "source":
            # hidden _row_id appended: the stream key of a keyless source
            # (reference: row_id_gen.rs + logical source planning)
            from ..common.types import SERIAL
            schema = Schema(tuple(d.schema) + (Field("_row_id", SERIAL),))
            n = len(schema)
            node = PSource(schema=schema, pk=(n - 1,), source=d,
                           row_id_index=n - 1)
            scope = Scope([
                ScopeColumn(f.name, alias, i, f.type)
                for i, f in enumerate(d.schema)
            ])
            return node, scope
        if kind == "table":
            node = PTableScan(schema=d.schema, pk=tuple(d.pk), table=d)
            return node, Scope.of_schema(d.schema, alias)
        node = PMvScan(schema=d.schema, pk=tuple(d.pk), mv=d)
        n_vis = getattr(d, "n_visible", len(d.schema))
        scope = Scope([
            ScopeColumn(f.name, alias, i, f.type)
            for i, f in enumerate(d.schema) if i < n_vis
        ])
        return node, scope

    def _plan_table_func_ref(self, ref: A.TableFuncRef):
        """FROM generate_series(…) with constant args → Values leaf
        (reference: table function scan lowered to batch values when
        constant; src/frontend/src/optimizer/plan_node/logical_table_function.rs)."""
        from ..stream.project_set import TABLE_FUNC_KINDS, series_values
        name = ref.name.lower()
        if name not in TABLE_FUNC_KINDS:
            raise PlanError(f"unknown table function {ref.name!r}")
        binder = ExprBinder(Scope([]))
        args = []
        binder_types = []
        for a in ref.args:
            b = binder.bind(a)
            if not isinstance(b, Literal):
                raise PlanError(
                    f"FROM {name}(...) requires constant arguments")
            args.append(b.value)
            binder_types.append(b.type)
        from ..common.types import INT64 as _I64, VARCHAR
        if name == "regexp_split_to_table":
            out_t = VARCHAR
        elif name == "unnest":
            if not binder_types or not binder_types[0].is_list:
                raise PlanError("unnest() requires an array argument")
            out_t = binder_types[0].elem_type
        else:
            out_t = _I64
        vals = series_values(name, args)
        # series elements are physical scalars; literals carry python values
        vals = [None if v is None else out_t.to_python(v) for v in vals]
        rows = tuple((Literal(v, out_t),) for v in vals)
        alias = ref.alias or name
        schema = Schema((Field(alias, out_t),))
        node = PValues(schema=schema, pk=(), rows=rows)
        return node, Scope.of_schema(schema, alias)

    def _plan_window_tvf(self, tvf: A.WindowTVF):
        node, scope = self._plan_table_ref(tvf.table)
        tc = scope.resolve(tvf.time_col, None)
        if tc.type.kind != TIMESTAMP.kind:
            raise PlanError(f"window TVF time column must be timestamp")
        alias = tvf.alias or tvf.table.name

        def lit_us(e) -> int:
            b = ExprBinder(scope).bind(e)
            if not isinstance(b, Literal):
                raise PlanError("window TVF size/slide must be literal")
            return int(b.value)

        n_in = len(node.schema)
        if tvf.kind == "tumble":
            (size,) = map(lit_us, tvf.args)
            # TUMBLE = projection: all columns + window_start + window_end
            exprs = [InputRef(i, f.type) for i, f in enumerate(node.schema)]
            ws = call("tumble_start", InputRef(tc.index, tc.type),
                      Literal(size, TIMESTAMP))
            exprs.append(ws)
            exprs.append(ws + Literal(size, TIMESTAMP))
            schema = Schema(tuple(node.schema) + (
                Field("window_start", TIMESTAMP), Field("window_end", TIMESTAMP)))
            node = PProject(schema=schema, pk=node.pk, input=node,
                            exprs=tuple(exprs))
        else:
            slide, size = map(lit_us, tvf.args)
            schema = Schema(tuple(node.schema) + (
                Field("window_start", TIMESTAMP), Field("window_end", TIMESTAMP)))
            # pk extends with window_start: one input row yields size/slide rows
            node = PHopWindow(schema=schema, pk=tuple(node.pk) + (n_in,),
                              input=node, time_col=tc.index, slide=slide,
                              size=size)
        new_scope = Scope(
            scope.columns + [
                ScopeColumn("window_start", alias, n_in, TIMESTAMP),
                ScopeColumn("window_end", alias, n_in + 1, TIMESTAMP),
            ])
        return node, new_scope

    def _plan_join(self, j: A.Join, pending_conjuncts=None):
        if j.temporal:
            return self._plan_temporal_join(j)
        left, lscope = self._plan_relation(j.left, pending_conjuncts)
        right, rscope = self._plan_relation(j.right, pending_conjuncts)
        n_left = len(left.schema)
        scope = lscope.concat(rscope, n_left)

        # split ON into equi-keys and residual condition
        lkeys, rkeys, residual = [], [], []
        if j.on is not None:
            for conj in _conjuncts(j.on):
                pair = self._equi_pair(conj, scope, n_left)
                if pair is not None:
                    lkeys.append(pair[0])
                    rkeys.append(pair[1])
                else:
                    residual.append(conj)
        if not lkeys and j.kind == "inner" and pending_conjuncts:
            # comma-syntax join: pull equality conjuncts out of WHERE
            # (consumed conjuncts no longer filter above the join)
            for conj in list(pending_conjuncts):
                pair = self._equi_pair(conj, scope, n_left)
                if pair is not None:
                    lkeys.append(pair[0])
                    rkeys.append(pair[1])
                    pending_conjuncts.remove(conj)
        if not lkeys:
            raise PlanError("join requires at least one equality condition "
                            "(nested-loop streaming join unsupported)")
        cond = None
        post_filters: list = []
        if residual:
            from ..expr.expr import uses_host_callback
            bound = [ExprBinder(scope).bind(c) for c in residual]
            for b in bound:
                if uses_host_callback(b):
                    # host-tier string predicates cannot run inside the
                    # jitted join core; for inner joins they are equivalent
                    # to a filter above the join
                    if j.kind != "inner":
                        raise PlanError(
                            "string predicates in outer-join conditions "
                            "are not supported; filter in a subquery")
                    post_filters.append(b)
                elif cond is None:
                    cond = b
                else:
                    cond = call("and", cond, b)

        schema = Schema(tuple(left.schema) + tuple(right.schema))
        pk = tuple(left.pk) + tuple(i + n_left for i in right.pk)
        node: PlanNode = PJoin(
            schema=schema, pk=pk, left=left, right=right,
            kind=j.kind, left_keys=tuple(lkeys),
            right_keys=tuple(rkeys), condition=cond)
        for b in post_filters:
            node = PFilter(schema=node.schema, pk=node.pk, input=node,
                           predicate=b)
        return node, scope

    def _plan_temporal_join(self, j: A.Join):
        """FOR SYSTEM_TIME AS OF PROCTIME(): right side must be a named
        table/MV; its current rows are probed, not streamed. The probe
        side must be append-only (a retraction's enrichment would be
        recomputed from the table's CURRENT rows and could fail to cancel
        the originally emitted rows)."""
        if j.kind not in ("inner", "left"):
            raise PlanError("temporal joins support INNER and LEFT only")
        if not isinstance(j.right, A.TableRef):
            raise PlanError("temporal join right side must be a table/MV")
        left, lscope = self._plan_relation(j.left)
        if not _plan_is_append_only(left):
            if self.lenient:
                import warnings
                warnings.warn(
                    "temporal join probe side is not append-only; the "
                    "job will fail at the first retraction (statement "
                    "predates the append-only rule)")
            else:
                raise PlanError(
                    "temporal join requires an append-only probe side "
                    "(sources / append-only tables through stateless "
                    "operators); this input can retract")
        kind, rdef = self.catalog.resolve_relation(j.right.name)
        if kind == "source":
            raise PlanError("temporal join right side must be materialized")
        alias = j.right.alias or j.right.name
        # scope = VISIBLE columns only (hidden '_' stream-key cols of an
        # MV stay out of name resolution, as in _plan_table_ref)
        n_vis = getattr(rdef, "n_visible", len(rdef.schema))
        rscope = Scope([
            ScopeColumn(f.name, alias, i, f.type)
            for i, f in enumerate(rdef.schema) if i < n_vis
        ])
        n_left = len(left.schema)
        scope = lscope.concat(rscope, n_left)
        lkeys, rkeys, residual = [], [], []
        for conj in _conjuncts(j.on) if j.on is not None else []:
            pair = self._equi_pair(conj, scope, n_left)
            if pair is not None:
                lkeys.append(pair[0])
                rkeys.append(pair[1])
            else:
                residual.append(conj)
        if not lkeys:
            raise PlanError("temporal join requires an equality condition")
        cond = None
        if residual:
            if j.kind == "left":
                raise PlanError("non-equi conditions on LEFT temporal "
                                "joins are not supported")
            bound = [ExprBinder(scope).bind(c) for c in residual]
            cond = bound[0]
            for b in bound[1:]:
                cond = call("and", cond, b)
        schema = Schema(tuple(left.schema) + tuple(rdef.schema))
        # stream key: the probe side's key + the table pk (a probe row can
        # match several table rows unless probing by full pk)
        pk = tuple(left.pk) + tuple(i + n_left for i in rdef.pk)
        return PTemporalJoin(
            schema=schema, pk=pk, input=left,
            right_kind="table" if kind == "table" else "mv",
            right_def=rdef, left_keys=tuple(lkeys), right_keys=tuple(rkeys),
            outer=j.kind == "left", condition=cond), scope

    def _equi_pair(self, conj, scope: Scope, n_left: int):
        if not (isinstance(conj, A.BinaryOp) and conj.op == "="):
            return None
        try:
            l = ExprBinder(scope).bind(conj.left)
            r = ExprBinder(scope).bind(conj.right)
        except BindError:
            return None
        if isinstance(l, InputRef) and isinstance(r, InputRef):
            if l.index < n_left <= r.index:
                return (l.index, r.index - n_left)
            if r.index < n_left <= l.index:
                return (r.index, l.index - n_left)
        return None

    # -- projection / aggregation ---------------------------------------------

    def _expand_stars(self, sel: A.Select, scope: Scope) -> list:
        items = []
        for item in sel.items:
            if isinstance(item.expr, A.Star):
                for c in scope.columns:
                    if item.expr.table is None or c.table == item.expr.table:
                        items.append(A.SelectItem(
                            A.ColumnRef(c.name, c.table), c.name))
            else:
                items.append(item)
        return items

    def _plan_projection(self, sel: A.Select, node: PlanNode, scope: Scope):
        items = self._expand_stars(sel, scope)
        exprs, fields = [], []
        for item in items:
            e = ExprBinder(scope).bind(item.expr)
            exprs.append(e)
            fields.append(Field(item.alias or self._auto_name(item.expr), e.type))
        # keep the stream key alive: append hidden pk columns not projected
        out_pk = []
        for pk_col in node.pk:
            found = None
            for i, e in enumerate(exprs):
                if isinstance(e, InputRef) and e.index == pk_col:
                    found = i
                    break
            if found is None:
                exprs.append(InputRef(pk_col, node.schema[pk_col].type))
                fields.append(Field(f"_pk{len(out_pk)}", node.schema[pk_col].type))
                found = len(exprs) - 1
            out_pk.append(found)
        proj = PProject(schema=Schema(tuple(fields)), pk=tuple(out_pk),
                        input=node, exprs=tuple(exprs))
        new_scope = Scope([
            ScopeColumn(f.name, None, i, f.type)
            for i, f in enumerate(proj.schema)
        ])
        return proj, new_scope

    def _plan_agg(self, sel: A.Select, node: PlanNode, scope: Scope):
        # 1. bind group keys
        group_exprs = [ExprBinder(scope).bind(g) for g in sel.group_by]
        # 2. bind select items + having with agg collection
        aggs: list[BoundAgg] = []
        items = self._expand_stars(sel, scope)
        bound_items = []
        for item in items:
            b = ExprBinder(scope, agg_ctx=aggs).bind(item.expr)
            bound_items.append((b, item.alias or self._auto_name(item.expr)))
        bound_having = None
        having_dyn: list = []  # (bound_lhs_tree, cmp_fn_name, subquery)
        if sel.having is not None:
            plain_h: list = []
            for conj in _conjuncts(sel.having):
                if self._has_subquery(conj):
                    # HAVING agg CMP (SELECT …) → DynamicFilter above the
                    # agg (reference: the same Apply rewrite as WHERE-level
                    # scalar subqueries; q102 shape). Bind the agg side NOW
                    # so its agg call registers before the pre-projection.
                    if not (isinstance(conj, A.BinaryOp)
                            and conj.op in _CMP_TO_FN):
                        raise PlanError("HAVING subquery only supported as "
                                        "'agg CMP (SELECT …)'")
                    lsub = isinstance(conj.left, A.ScalarSubquery)
                    rsub = isinstance(conj.right, A.ScalarSubquery)
                    if lsub == rsub:
                        raise PlanError(
                            "exactly one side must be a scalar subquery")
                    col_ast = conj.right if lsub else conj.left
                    sub = conj.left if lsub else conj.right
                    op = _CMP_FLIP[conj.op] if lsub else conj.op
                    lhs_b = ExprBinder(scope, agg_ctx=aggs).bind(col_ast)
                    having_dyn.append((lhs_b, _CMP_TO_FN[op], sub))
                else:
                    plain_h.append(conj)
            if plain_h:
                e = plain_h[0]
                for c in plain_h[1:]:
                    e = A.BinaryOp("AND", e, c)
                bound_having = ExprBinder(scope, agg_ctx=aggs).bind(e)

        # 3. pre-projection: group keys first, then agg args
        pre_exprs = list(group_exprs)
        for a in aggs:
            if hasattr(a, "arg_expr"):
                a.call = dataclasses.replace(a.call, arg=len(pre_exprs))
                pre_exprs.append(a.arg_expr)  # type: ignore[attr-defined]
            elif a.call.arg >= 0:
                # remap plain column arg into pre-projection position
                pre_exprs.append(InputRef(a.call.arg,
                                          node.schema[a.call.arg].type))
                a.call = dataclasses.replace(a.call, arg=len(pre_exprs) - 1)
        pre_fields = [
            Field(f"k{i}", e.type) for i, e in enumerate(group_exprs)
        ] + [
            Field(f"a{i}", e.type)
            for i, e in enumerate(pre_exprs[len(group_exprs):])
        ]
        pre = PProject(schema=Schema(tuple(pre_fields)), pk=(), input=node,
                       exprs=tuple(pre_exprs))

        # 4. the agg node: output = group keys ++ agg outputs
        nk = len(group_exprs)
        agg_fields = tuple(
            Field(f"k{i}", e.type) for i, e in enumerate(group_exprs)
        ) + tuple(
            Field(f"agg{i}", a.call.output_type) for i, a in enumerate(aggs)
        )
        agg_node = PAgg(
            schema=Schema(agg_fields), pk=tuple(range(nk)), input=pre,
            group_keys=tuple(range(nk)),
            agg_calls=tuple(a.call for a in aggs),
            append_only_input=_plan_is_append_only(pre),
            eowc=sel.emit_on_window_close)

        # 5. post-projection: rewrite select items over agg output
        def agg_ref(i: int) -> Expr:
            return InputRef(nk + i, aggs[i].call.output_type)

        def rewrite_tree(e: Expr) -> Expr:
            # replace group-key subexpressions first, then agg placeholders
            for gi, g in enumerate(group_exprs):
                if _expr_eq(e, g):
                    return InputRef(gi, g.type)
            if isinstance(e, _AggPlaceholder):
                return agg_ref(e.agg_index)
            if isinstance(e, FunctionCall):
                return dataclasses.replace(
                    e, args=tuple(rewrite_tree(a) for a in e.args))
            from ..expr.expr import Cast as RCast
            if isinstance(e, RCast):
                return dataclasses.replace(e, arg=rewrite_tree(e.arg))
            if isinstance(e, InputRef):
                raise PlanError(
                    f"column ${e.index} must appear in GROUP BY or an "
                    "aggregate")
            return e

        post_node: PlanNode = agg_node
        if bound_having is not None:
            post_node = PFilter(schema=agg_node.schema, pk=agg_node.pk,
                                input=post_node,
                                predicate=rewrite_tree(bound_having))
        for lhs_b, cmp_fn, sub in having_dyn:
            key = rewrite_tree(lhs_b)
            if not isinstance(key, InputRef):
                raise PlanError("HAVING dynamic-filter side must be a "
                                "single aggregate or group key")
            right_plan = self.plan_select(sub.query)
            if len(right_plan.schema) < 1:
                raise PlanError("scalar subquery must produce one column")
            post_node = PDynFilter(
                schema=post_node.schema, pk=post_node.pk, input=post_node,
                right=right_plan, key_col=key.index, cmp=cmp_fn)
        out_exprs, out_fields = [], []
        for b, name in bound_items:
            e = rewrite_tree(b)
            out_exprs.append(e)
            out_fields.append(Field(name, e.type))
        out_pk = []
        for pk_col in agg_node.pk:
            found = None
            for i, e in enumerate(out_exprs):
                if isinstance(e, InputRef) and e.index == pk_col:
                    found = i
                    break
            if found is None:
                out_exprs.append(InputRef(pk_col, agg_node.schema[pk_col].type))
                out_fields.append(
                    Field(f"_pk{len(out_pk)}", agg_node.schema[pk_col].type))
                found = len(out_exprs) - 1
            out_pk.append(found)
        proj = PProject(schema=Schema(tuple(out_fields)), pk=tuple(out_pk),
                        input=post_node, exprs=tuple(out_exprs))
        new_scope = Scope([
            ScopeColumn(f.name, None, i, f.type)
            for i, f in enumerate(proj.schema)
        ])
        return proj, new_scope

    def _plan_over_window(self, sel: A.Select, node: PlanNode, scope: Scope):
        """SELECT with OVER clauses → pre-projection (input cols + hidden
        partition/order/arg exprs) → POverWindow → post-projection."""
        from ..stream.over_window import WindowCall
        wins: list[BoundWindow] = []
        items = self._expand_stars(sel, scope)
        bound_items = []
        for item in items:
            b = ExprBinder(scope, win_ctx=wins).bind(item.expr)
            bound_items.append((b, item.alias or self._auto_name(item.expr)))
        first = wins[0]
        for w in wins[1:]:
            same = (len(w.partition_exprs) == len(first.partition_exprs)
                    and all(_expr_eq(a, b) for a, b in
                            zip(w.partition_exprs, first.partition_exprs))
                    and len(w.order_exprs) == len(first.order_exprs)
                    and all(_expr_eq(a[0], b[0]) and a[1:] == b[1:]
                            for a, b in
                            zip(w.order_exprs, first.order_exprs)))
            if not same:
                raise PlanError("all window functions in one SELECT must "
                                "share PARTITION BY / ORDER BY")

        pre_exprs: list[Expr] = [
            InputRef(i, f.type) for i, f in enumerate(node.schema)]

        def col_of(e: Expr) -> int:
            for i, pe in enumerate(pre_exprs):
                if _expr_eq(pe, e):
                    return i
            pre_exprs.append(e)
            return len(pre_exprs) - 1

        part_idx = tuple(col_of(p) for p in first.partition_exprs)
        order_specs = tuple(
            OrderSpec(col_of(oe), desc, nulls_last,
                      is_string=oe.type.is_string)
            for (oe, desc, nulls_last) in first.order_exprs)
        calls = tuple(
            WindowCall(
                kind=w.kind, output_type=w.output_type,
                arg=col_of(w.arg_expr) if w.arg_expr is not None else -1,
                offset=w.offset, partition_by=part_idx,
                order_by=order_specs)
            for w in wins)
        n_base = len(node.schema)
        if len(pre_exprs) > n_base:
            pre_schema = Schema(tuple(node.schema) + tuple(
                Field(f"_w{i}", e.type)
                for i, e in enumerate(pre_exprs[n_base:])))
            pre: PlanNode = PProject(schema=pre_schema, pk=node.pk,
                                     input=node, exprs=tuple(pre_exprs))
        else:
            pre = node
        n_in = len(pre.schema)
        win_schema = Schema(tuple(pre.schema) + tuple(
            Field(f"_win{i}", c.output_type) for i, c in enumerate(calls)))
        wnode = POverWindow(schema=win_schema, pk=pre.pk, input=pre,
                            calls=calls, eowc=sel.emit_on_window_close)

        def rw(e: Expr) -> Expr:
            if isinstance(e, _WindowPlaceholder):
                return InputRef(n_in + e.win_index, e.type)
            if isinstance(e, FunctionCall):
                return dataclasses.replace(
                    e, args=tuple(rw(a) for a in e.args))
            from ..expr.expr import Cast as RCast
            if isinstance(e, RCast):
                return dataclasses.replace(e, arg=rw(e.arg))
            return e

        out_exprs, out_fields = [], []
        for b, name in bound_items:
            e = rw(b)
            out_exprs.append(e)
            out_fields.append(Field(name, e.type))
        out_pk = []
        for pk_col in wnode.pk:
            found = None
            for i, e in enumerate(out_exprs):
                if isinstance(e, InputRef) and e.index == pk_col:
                    found = i
                    break
            if found is None:
                out_exprs.append(InputRef(pk_col, win_schema[pk_col].type))
                out_fields.append(
                    Field(f"_pk{len(out_pk)}", win_schema[pk_col].type))
                found = len(out_exprs) - 1
            out_pk.append(found)
        proj = PProject(schema=Schema(tuple(out_fields)), pk=tuple(out_pk),
                        input=wnode, exprs=tuple(out_exprs))
        new_scope = Scope([
            ScopeColumn(f.name, None, i, f.type)
            for i, f in enumerate(proj.schema)
        ])
        return proj, new_scope

    # -- TopN / dynamic filter / misc -----------------------------------------

    def _plan_topn(self, sel: A.Select, node: PlanNode, scope: Scope):
        order = []
        for oi in sel.order_by:
            b = ExprBinder(scope).bind(oi.expr)
            if not isinstance(b, InputRef):
                raise PlanError("ORDER BY expression must be an output column")
            nulls_last = oi.nulls_last
            if nulls_last is None:
                nulls_last = not oi.desc     # PG default
            order.append(OrderSpec(b.index, oi.desc, nulls_last,
                                   is_string=b.type.is_string))
        if sel.limit is None:
            # bare ORDER BY on an MV is a presentation property; keep plan
            return node
        return PTopN(schema=node.schema, pk=node.pk, input=node,
                     order=tuple(order), limit=sel.limit,
                     offset=sel.offset or 0, with_ties=sel.with_ties)

    def _plan_dynamic_filter(self, conj, node: PlanNode, scope: Scope):
        if not (isinstance(conj, A.BinaryOp) and conj.op in _CMP_TO_FN):
            raise PlanError(
                "subquery only supported as 'col CMP (SELECT ...)'")
        lsub = isinstance(conj.left, A.ScalarSubquery)
        rsub = isinstance(conj.right, A.ScalarSubquery)
        if lsub == rsub:
            raise PlanError("exactly one side must be a scalar subquery")
        col_ast = conj.right if lsub else conj.left
        sub = conj.left if lsub else conj.right
        op = _CMP_FLIP[conj.op] if lsub else conj.op
        b = ExprBinder(scope).bind(col_ast)
        if not isinstance(b, InputRef):
            raise PlanError("dynamic filter LHS must be a plain column")
        right_plan = self.plan_select(sub.query)
        if len(right_plan.schema) < 1:
            raise PlanError("scalar subquery must produce one column")
        return PDynFilter(schema=node.schema, pk=node.pk, input=node,
                          right=right_plan, key_col=b.index,
                          cmp=_CMP_TO_FN[op])

    def _plan_in_subquery(self, conj: A.InSubquery, node: PlanNode,
                          scope: Scope) -> PlanNode:
        b = ExprBinder(scope).bind(conj.expr)
        if not isinstance(b, InputRef):
            raise PlanError("IN (SELECT …) operand must be a plain column")
        sub = self.plan_select(conj.query)
        n_visible = sum(1 for f in sub.schema
                        if not f.name.startswith("_"))
        if n_visible != 1 or not sub.schema[0].name or \
                sub.schema[0].name.startswith("_"):
            raise PlanError("IN subquery must produce exactly one column")
        # hidden stream-key columns (appended by the planner) ride along
        # as the semi-join state's pk; only column 0 joins
        if conj.negated:
            # PG NOT IN NULL semantics: a NULL probe value never passes
            # (x <> NULL is unknown), so filter it below the join; a NULL
            # in the subquery means NO row passes — the anti join carries
            # ``null_aware`` so each engine enforces it (batch: emit
            # nothing; streaming: reject loudly rather than diverge).
            # KNOWN divergence: PG keeps a NULL probe row when the
            # subquery is EMPTY (NOT IN over the empty set is TRUE); the
            # static filter drops it regardless. Incrementally exact
            # behavior would retract those rows on the subquery's
            # empty→non-empty transition — out of scope, and the corner
            # (NULL probe AND always-empty subquery) is documented here
            # rather than silently wrong in the common case.
            node = PFilter(schema=node.schema, pk=node.pk, input=node,
                           predicate=call("is_not_null", b))
            return PJoin(schema=node.schema, pk=node.pk, left=node,
                         right=sub, kind="left_anti",
                         left_keys=(b.index,), right_keys=(0,),
                         condition=None, null_aware=True)
        return PJoin(schema=node.schema, pk=node.pk, left=node, right=sub,
                     kind="left_semi", left_keys=(b.index,), right_keys=(0,),
                     condition=None)

    def _plan_no_from(self, sel: A.Select) -> PlanNode:
        binder = ExprBinder(Scope([]))
        row = tuple(binder.bind(i.expr) for i in sel.items)
        from ..stream.project_set import TableFuncCall, series_values
        if len(row) == 1 and isinstance(row[0], TableFuncCall):
            # FROM-less set-returning select: SELECT unnest(ARRAY[…])
            tf = row[0]
            if not all(isinstance(a, Literal) for a in tf.args):
                raise PlanError(
                    "set-returning function without FROM requires "
                    "constant arguments")
            vals = series_values(tf.name, [a.value for a in tf.args])
            out_t = tf.type
            name = sel.items[0].alias or tf.name
            lit_rows = tuple(
                (Literal(None if v is None else out_t.to_python(v),
                         out_t),) for v in vals)
            return PValues(schema=Schema((Field(name, out_t),)), pk=(),
                           rows=lit_rows)
        fields = tuple(
            Field(item.alias or self._auto_name(item.expr), e.type)
            for item, e in zip(sel.items, row))
        return PValues(schema=Schema(fields), pk=(), rows=(row,))

    # -- small helpers --------------------------------------------------------

    def _has_subquery(self, e) -> bool:
        if isinstance(e, A.ScalarSubquery):
            return True
        if isinstance(e, A.BinaryOp):
            return self._has_subquery(e.left) or self._has_subquery(e.right)
        if isinstance(e, A.UnaryOp):
            return self._has_subquery(e.operand)
        return False

    def _select_has_aggs(self, sel: A.Select) -> bool:
        def walk(e) -> bool:
            if isinstance(e, A.FuncCall):
                if e.name.lower() in AGG_KINDS:
                    return True
                return any(walk(a) for a in e.args)
            if isinstance(e, A.BinaryOp):
                return walk(e.left) or walk(e.right)
            if isinstance(e, A.UnaryOp):
                return walk(e.operand)
            if isinstance(e, A.Case):
                return any(walk(c) or walk(r) for c, r in e.branches) or (
                    e.else_result is not None and walk(e.else_result))
            if isinstance(e, A.Cast):
                return walk(e.expr)
            return False
        return any(walk(i.expr) for i in sel.items
                   if not isinstance(i.expr, A.Star)) or (
            sel.having is not None and walk(sel.having))

    def _plan_project_set(self, sel: A.Select, node: PlanNode, scope: Scope):
        """Select list containing a set-returning function → PProjectSet.
        The table function must be a top-level select item; its elements
        land in that output column, other items replicate."""
        from ..stream.project_set import TableFuncCall
        items = self._expand_stars(sel, scope)
        exprs, fields = [], []
        n_tf = 0
        for item in items:
            b = ExprBinder(scope).bind(item.expr)
            if isinstance(b, TableFuncCall):
                n_tf += 1
            elif contains_placeholder(b, TableFuncCall):
                raise PlanError("set-returning functions must be top-level "
                                "select items")
            exprs.append(b)
            fields.append(Field(item.alias or self._auto_name(item.expr),
                                b.type))
        if n_tf != 1:
            raise PlanError("exactly one set-returning function per SELECT "
                            "is supported")
        # stream key: input pk passthrough + hidden element index
        out_pk = []
        for pk_col in node.pk:
            found = None
            for i, e in enumerate(exprs):
                if isinstance(e, InputRef) and e.index == pk_col:
                    found = i
                    break
            if found is None:
                exprs.append(InputRef(pk_col, node.schema[pk_col].type))
                fields.append(
                    Field(f"_pk{len(out_pk)}", node.schema[pk_col].type))
                found = len(exprs) - 1
            out_pk.append(found)
        from ..common.types import INT64 as _I64
        exprs.append(Literal(0, _I64))       # executor fills the index
        fields.append(Field("_pidx", _I64))
        out_pk.append(len(exprs) - 1)
        ps = PProjectSet(schema=Schema(tuple(fields)), pk=tuple(out_pk),
                         input=node, exprs=tuple(exprs))
        new_scope = Scope([
            ScopeColumn(f.name, None, i, f.type)
            for i, f in enumerate(ps.schema)
        ])
        return ps, new_scope

    def _select_has_table_funcs(self, sel: A.Select) -> bool:
        from ..stream.project_set import TABLE_FUNC_KINDS

        def walk(e) -> bool:
            if isinstance(e, A.FuncCall):
                return (e.name.lower() in TABLE_FUNC_KINDS
                        or any(walk(a) for a in e.args))
            if isinstance(e, A.BinaryOp):
                return walk(e.left) or walk(e.right)
            if isinstance(e, A.UnaryOp):
                return walk(e.operand)
            if isinstance(e, A.Cast):
                return walk(e.expr)
            return False
        return any(walk(i.expr) for i in sel.items
                   if not isinstance(i.expr, A.Star))

    def _select_has_windows(self, sel: A.Select) -> bool:
        def walk(e) -> bool:
            if isinstance(e, A.WindowFunc):
                return True
            if isinstance(e, A.FuncCall):
                return any(walk(a) for a in e.args)
            if isinstance(e, A.BinaryOp):
                return walk(e.left) or walk(e.right)
            if isinstance(e, A.UnaryOp):
                return walk(e.operand)
            if isinstance(e, A.Case):
                return any(walk(c) or walk(r) for c, r in e.branches) or (
                    e.else_result is not None and walk(e.else_result))
            if isinstance(e, A.Cast):
                return walk(e.expr)
            return False
        return any(walk(i.expr) for i in sel.items
                   if not isinstance(i.expr, A.Star))

    def _auto_name(self, e) -> str:
        if isinstance(e, A.ColumnRef):
            return e.name
        if isinstance(e, A.FuncCall):
            return e.name.lower()
        if isinstance(e, A.WindowFunc):
            return e.func.name.lower()
        return "?column?"


def _plan_is_append_only(plan: PlanNode) -> bool:
    """Conservative: true only for sources/append-only tables flowing
    through stateless row-preserving operators (reference: append-only
    derivation in the optimizer's stream properties)."""
    if isinstance(plan, PSource):
        return True
    if isinstance(plan, PTableScan):
        # DELETE/UPDATE DML can retract from ordinary tables; only
        # declared APPEND ONLY tables are safe probe sides
        return bool(getattr(plan.table, "append_only", False))
    if isinstance(plan, (PProject, PFilter, PHopWindow)):
        return _plan_is_append_only(plan.input)
    if isinstance(plan, PTemporalJoin):
        return _plan_is_append_only(plan.input)
    if isinstance(plan, PUnion):
        return all(_plan_is_append_only(i) for i in plan.inputs)
    if isinstance(plan, PJoin):
        # an inner/semi join of append-only inputs never retracts a row it
        # emitted (no deletes arrive on either side); every outer/anti
        # shape can retract its padded or emitted rows
        return (plan.kind in ("inner", "left_semi")
                and _plan_is_append_only(plan.left)
                and _plan_is_append_only(plan.right))
    return False


def _expr_eq(a: Expr, b: Expr) -> bool:
    """Structural equality of bound expressions (Expr overloads __eq__ for
    SQL sugar, so compare explicitly)."""
    if type(a) is not type(b):
        return False
    if isinstance(a, InputRef):
        return a.index == b.index
    if isinstance(a, Literal):
        return a.value == b.value and a.type.kind == b.type.kind
    if isinstance(a, FunctionCall):
        return (a.name == b.name and len(a.args) == len(b.args)
                and all(_expr_eq(x, y) for x, y in zip(a.args, b.args)))
    from ..expr.expr import Cast as RCast
    if isinstance(a, RCast):
        return a.type.kind == b.type.kind and _expr_eq(a.arg, b.arg)
    return a is b
