"""Recursive-descent SQL parser for the streaming subset.

Counterpart of the reference's hand-written parser
(reference: src/sqlparser/src/parser.rs — Postgres dialect plus streaming
extensions: CREATE SOURCE, CREATE MATERIALIZED VIEW, window TVFs, EMIT ON
WINDOW CLOSE). Precedence-climbing expression parsing; case-insensitive
keywords; '...' string literals; -- line comments.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional

from . import sqlast as A

_TOKEN_RE = re.compile(r"""
    \s+
  | --[^\n]*
  | (?P<num>\d+\.\d+|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z_0-9$]*)
  | (?P<op>->>|->|<>|!=|<=|>=|\|\||::|[-+*/%(),.<>=;\[\]])
""", re.VERBOSE)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "between", "is", "null",
    "case", "when", "then", "else", "end", "cast", "distinct", "join",
    "inner", "left", "right", "full", "outer", "on", "union", "all",
    "create", "drop", "insert", "into", "values", "table", "source",
    "materialized", "view", "index", "if", "exists", "with", "primary",
    "key", "watermark", "for", "interval", "asc", "desc", "nulls", "first",
    "last", "ties", "emit", "window", "close", "true", "false", "show",
    "tables", "sources", "flush", "tumble", "hop", "append", "only",
    "sink", "sinks", "over", "partition", "like", "extract", "set", "to",
    "parameters", "delete", "update", "explain", "alter", "system",
}


class Token:
    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: Any):
        self.kind = kind      # num / str / name / kw / op / eof
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlParseError(f"unexpected character {sql[pos]!r} at {pos}")
        pos = m.end()
        if m.lastgroup is None:
            continue
        text = m.group(m.lastgroup)
        if m.lastgroup == "num":
            v = float(text) if "." in text else int(text)
            out.append(Token("num", v))
        elif m.lastgroup == "str":
            out.append(Token("str", text[1:-1].replace("''", "'")))
        elif m.lastgroup == "name":
            low = text.lower()
            out.append(Token("kw" if low in KEYWORDS else "name", low))
        else:
            out.append(Token("op", text))
    out.append(Token("eof", None))
    return out


class SqlParseError(ValueError):
    pass


# interval unit -> microseconds (reference: INTERVAL literal binding)
_INTERVAL_UNITS = {
    "second": 1_000_000, "seconds": 1_000_000,
    "minute": 60_000_000, "minutes": 60_000_000,
    "hour": 3_600_000_000, "hours": 3_600_000_000,
    "day": 86_400_000_000, "days": 86_400_000_000,
}

_CMP_OPS = {"=", "<>", "!=", "<", "<=", ">", ">="}


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers --------------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise SqlParseError(f"expected {kw.upper()}, got {self.peek()}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise SqlParseError(f"expected {op!r}, got {self.peek()}")

    def ident(self) -> str:
        t = self.next()
        if t.kind not in ("name", "kw"):
            raise SqlParseError(f"expected identifier, got {t}")
        return t.value

    # -- statements -----------------------------------------------------------

    def parse_statements(self) -> list[A.Statement]:
        stmts = []
        while self.peek().kind != "eof":
            stmts.append(self.parse_statement())
            while self.eat_op(";"):
                pass
        return stmts

    def parse_statement(self) -> A.Statement:
        if self.eat_kw("explain"):
            return A.Explain(self.parse_statement())
        if self.at_kw("create"):
            return self._create()
        if self.at_kw("drop"):
            return self._drop()
        if self.at_kw("insert"):
            return self._insert()
        if self.at_kw("select"):
            return A.Query(self._select())
        if self.eat_kw("delete"):
            self.expect_kw("from")
            table = self.ident()
            where = self.parse_expr() if self.eat_kw("where") else None
            return A.Delete(table, where)
        if self.eat_kw("update"):
            table = self.ident()
            self.expect_kw("set")
            assigns = []
            while True:
                col = self.ident()
                self.expect_op("=")
                assigns.append((col, self.parse_expr()))
                if not self.eat_op(","):
                    break
            where = self.parse_expr() if self.eat_kw("where") else None
            return A.Update(table, tuple(assigns), where)
        if self.eat_kw("show"):
            what = self.ident()
            return A.ShowStatement(what)
        if self.eat_kw("flush"):
            return A.FlushStatement()
        if self.eat_kw("set"):
            name = self.ident()
            if not self.eat_op("="):
                self.expect_kw("to")
            t = self.next()
            return A.SetStatement(name, t.value)
        if self.eat_kw("alter"):
            # ALTER SYSTEM SET <param> = <value> | TO <value>: the
            # cluster-wide variant of SET (reference:
            # src/common/src/system_param/mod.rs hot propagation)
            self.expect_kw("system")
            self.expect_kw("set")
            name = self.ident()
            if not self.eat_op("="):
                self.expect_kw("to")
            t = self.next()
            return A.SetStatement(name, t.value, system=True)
        raise SqlParseError(f"unsupported statement at {self.peek()}")

    def _if_not_exists(self) -> bool:
        if self.eat_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            return True
        return False

    def _create(self) -> A.Statement:
        self.expect_kw("create")
        if self.eat_kw("source"):
            ine = self._if_not_exists()
            name = self.ident()
            columns, pk, watermark = self._column_defs()
            opts = self._with_options()
            return A.CreateSource(name, tuple(columns), opts,
                                  watermark=watermark, if_not_exists=ine)
        if self.eat_kw("table"):
            ine = self._if_not_exists()
            name = self.ident()
            columns, pk, _ = self._column_defs()
            opts = self._with_options()
            append_only = opts.pop("appendonly", "false") == "true"
            return A.CreateTable(name, tuple(columns), pk=tuple(pk),
                                 with_options=opts, append_only=append_only,
                                 if_not_exists=ine)
        if self.eat_kw("materialized"):
            self.expect_kw("view")
            ine = self._if_not_exists()
            name = self.ident()
            self.expect_kw("as")
            q = self._select()
            return A.CreateMaterializedView(name, q, if_not_exists=ine)
        if self.eat_kw("sink"):
            ine = self._if_not_exists()
            name = self.ident()
            from_name, q = None, None
            if self.eat_kw("from"):
                from_name = self.ident()
            else:
                self.expect_kw("as")
                q = self._select()
            opts = self._with_options()
            return A.CreateSink(name, from_name=from_name, query=q,
                                with_options=opts, if_not_exists=ine)
        if self.eat_kw("index"):
            ine = self._if_not_exists()
            name = self.ident()
            self.expect_kw("on")
            table = self.ident()
            self.expect_op("(")
            cols = [self.ident()]
            while self.eat_op(","):
                cols.append(self.ident())
            self.expect_op(")")
            return A.CreateIndex(name, table, tuple(cols), if_not_exists=ine)
        raise SqlParseError(f"unsupported CREATE at {self.peek()}")

    def _column_defs(self):
        columns, pk, watermark = [], [], None
        if not self.eat_op("("):
            return columns, pk, watermark
        while True:
            if self.eat_kw("primary"):
                self.expect_kw("key")
                self.expect_op("(")
                pk.append(self.ident())
                while self.eat_op(","):
                    pk.append(self.ident())
                self.expect_op(")")
            elif self.eat_kw("watermark"):
                self.expect_kw("for")
                col = self.ident()
                self.expect_kw("as")
                expr = self.parse_expr()
                watermark = (col, expr)
            else:
                cname = self.ident()
                tname = self._type_name()
                columns.append(A.ColumnDef(cname, tname))
                if self.eat_kw("primary"):
                    self.expect_kw("key")
                    pk.append(cname)
            if not self.eat_op(","):
                break
        self.expect_op(")")
        return columns, pk, watermark

    def _type_name(self) -> str:
        name = self.ident()
        # two-word types: double precision, timestamp with(out) time zone
        if name == "double" and self.peek().value == "precision":
            self.next()
            return "double"
        if name == "struct" and self.at_op("<"):
            # STRUCT<a BIGINT, b VARCHAR> — composite column type
            # (reference: struct_array.rs); flattened back to a string the
            # catalog's type_from_name re-parses
            self.next()
            fields = []
            while True:
                fname = self.ident()
                ftype = self._type_name()
                fields.append(f"{fname} {ftype}")
                if not self.eat_op(","):
                    break
            self.expect_op(">")
            return f"struct<{', '.join(fields)}>"
        if self.eat_op("("):
            # varchar(n) / decimal(p,s) — size args recorded but unused
            args = [self.next().value]
            while self.eat_op(","):
                args.append(self.next().value)
            self.expect_op(")")
        return name

    def _with_options(self) -> dict:
        opts = {}
        if self.eat_kw("with"):
            self.expect_op("(")
            while True:
                # option keys may be quoted ('datagen.split.num' = 2)
                k = (self.next().value if self.peek().kind == "str"
                     else self.ident())
                self.expect_op("=")
                t = self.next()
                opts[k] = t.value
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        return opts

    def _drop(self) -> A.DropStatement:
        self.expect_kw("drop")
        if self.eat_kw("materialized"):
            self.expect_kw("view")
            kind = "materialized_view"
        elif self.eat_kw("source"):
            kind = "source"
        elif self.eat_kw("sink"):
            kind = "sink"
        elif self.eat_kw("table"):
            kind = "table"
        elif self.eat_kw("index"):
            kind = "index"
        else:
            raise SqlParseError(f"unsupported DROP at {self.peek()}")
        if_exists = False
        if self.eat_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return A.DropStatement(kind, self.ident(), if_exists)

    def _insert(self) -> A.Insert:
        self.expect_kw("insert")
        self.expect_kw("into")
        table = self.ident()
        cols = []
        if self.eat_op("("):
            cols.append(self.ident())
            while self.eat_op(","):
                cols.append(self.ident())
            self.expect_op(")")
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.parse_expr()]
            while self.eat_op(","):
                row.append(self.parse_expr())
            self.expect_op(")")
            rows.append(tuple(row))
            if not self.eat_op(","):
                break
        return A.Insert(table, tuple(cols), tuple(rows))

    # -- SELECT ---------------------------------------------------------------

    def _select(self) -> A.Select:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        items = [self._select_item()]
        while self.eat_op(","):
            items.append(self._select_item())
        from_ = None
        if self.eat_kw("from"):
            from_ = self._relation()
            while self.eat_op(","):
                right = self._relation()
                from_ = A.Join("inner", from_, right, None)
        where = self.parse_expr() if self.eat_kw("where") else None
        group_by = []
        if self.eat_kw("group"):
            self.expect_kw("by")
            group_by.append(self.parse_expr())
            while self.eat_op(","):
                group_by.append(self.parse_expr())
        having = self.parse_expr() if self.eat_kw("having") else None
        order_by = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by.append(self._order_item())
            while self.eat_op(","):
                order_by.append(self._order_item())
        limit = offset = None
        with_ties = False
        if self.eat_kw("limit"):
            limit = int(self.next().value)
            if self.eat_kw("with"):
                self.expect_kw("ties")
                with_ties = True
        if self.eat_kw("offset"):
            offset = int(self.next().value)
        eowc = False
        if self.eat_kw("emit"):
            self.expect_kw("on")
            self.expect_kw("window")
            self.expect_kw("close")
            eowc = True
        union_all = None
        if self.eat_kw("union"):
            self.expect_kw("all")
            union_all = self._select()
        return A.Select(
            items=tuple(items), from_=from_, where=where,
            group_by=tuple(group_by), having=having, order_by=tuple(order_by),
            limit=limit, offset=offset, with_ties=with_ties,
            distinct=distinct, union_all=union_all,
            emit_on_window_close=eowc)

    def _select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.next()
            return A.SelectItem(A.Star())
        e = self.parse_expr()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "name":
            alias = self.next().value
        return A.SelectItem(e, alias)

    def _over_clause(self, fc: A.FuncCall) -> A.WindowFunc:
        """OVER (PARTITION BY e, … ORDER BY e [ASC|DESC], …)"""
        self.expect_op("(")
        partition_by: list = []
        order_by: list = []
        if self.eat_kw("partition"):
            self.expect_kw("by")
            partition_by.append(self.parse_expr())
            while self.eat_op(","):
                partition_by.append(self.parse_expr())
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by.append(self._order_item())
            while self.eat_op(","):
                order_by.append(self._order_item())
        self.expect_op(")")
        return A.WindowFunc(fc, tuple(partition_by), tuple(order_by))

    def _order_item(self) -> A.OrderItem:
        e = self.parse_expr()
        desc = False
        if self.eat_kw("desc"):
            desc = True
        else:
            self.eat_kw("asc")
        nulls_last = None
        if self.eat_kw("nulls"):
            if self.eat_kw("first"):
                nulls_last = False
            else:
                self.expect_kw("last")
                nulls_last = True
        return A.OrderItem(e, desc, nulls_last)

    def _relation(self) -> A.Relation:
        rel = self._relation_primary()
        while True:
            kind = None
            if self.eat_kw("join") or self.eat_kw("inner"):
                self.eat_kw("join")
                kind = "inner"
            elif self.at_kw("left", "right", "full"):
                kind = self.next().value
                self.eat_kw("outer")
                self.expect_kw("join")
            else:
                break
            right = self._relation_primary()
            temporal = False
            if self.eat_kw("for"):
                # FOR SYSTEM_TIME AS OF PROCTIME()
                if self.ident() != "system_time":
                    raise SqlParseError("expected SYSTEM_TIME after FOR")
                self.expect_kw("as")
                if self.ident() != "of":
                    raise SqlParseError("expected OF")
                if self.ident() != "proctime":
                    raise SqlParseError("only PROCTIME() temporal joins "
                                        "are supported")
                self.expect_op("(")
                self.expect_op(")")
                temporal = True
            on = None
            if self.eat_kw("on"):
                on = self.parse_expr()
            rel = A.Join(kind, rel, right, on, temporal=temporal)
        return rel

    def _relation_primary(self) -> A.Relation:
        if self.at_kw("tumble", "hop"):
            kind = self.next().value
            self.expect_op("(")
            table = A.TableRef(self.ident())
            self.expect_op(",")
            time_col = self.ident()
            args = []
            while self.eat_op(","):
                args.append(self._interval_or_expr())
            self.expect_op(")")
            alias = None
            if self.eat_kw("as"):
                alias = self.ident()
            elif self.peek().kind == "name":
                alias = self.next().value
            return A.WindowTVF(kind, table, time_col, tuple(args), alias)
        if self.eat_op("("):
            q = self._select()
            self.expect_op(")")
            alias = "subquery"
            if self.eat_kw("as"):
                alias = self.ident()
            elif self.peek().kind == "name":
                alias = self.next().value
            return A.SubqueryRef(q, alias)
        name = self.ident()
        # qualified relation names (pg_catalog.pg_tables,
        # information_schema.columns, …)
        while self.at_op("."):
            self.next()
            name += "." + self.ident()
        if self.at_op("("):
            # FROM table_function(args), e.g. generate_series(1, 10)
            self.next()
            args = []
            if not self.at_op(")"):
                args.append(self.parse_expr())
                while self.eat_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            alias = None
            if self.eat_kw("as"):
                alias = self.ident()
            elif self.peek().kind == "name":
                alias = self.next().value
            return A.TableFuncRef(name, tuple(args), alias)
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "name":
            alias = self.next().value
        return A.TableRef(name, alias)

    def _interval_or_expr(self):
        if self.at_kw("interval"):
            return self.parse_expr()
        return self.parse_expr()

    # -- expressions (precedence climbing) ------------------------------------

    def parse_expr(self):
        return self._or_expr()

    def _or_expr(self):
        e = self._and_expr()
        while self.eat_kw("or"):
            e = A.BinaryOp("OR", e, self._and_expr())
        return e

    def _and_expr(self):
        e = self._not_expr()
        while self.eat_kw("and"):
            e = A.BinaryOp("AND", e, self._not_expr())
        return e

    def _not_expr(self):
        if self.eat_kw("not"):
            return A.UnaryOp("NOT", self._not_expr())
        return self._cmp_expr()

    def _cmp_expr(self):
        e = self._add_expr()
        while True:
            if self.peek().kind == "op" and self.peek().value in _CMP_OPS:
                op = self.next().value
                if op == "!=":
                    op = "<>"
                e = A.BinaryOp(op, e, self._add_expr())
                continue
            negated = False
            save = self.i
            if self.eat_kw("not"):
                negated = True
            if self.eat_kw("in"):
                self.expect_op("(")
                if self.at_kw("select"):
                    q = self._select()
                    self.expect_op(")")
                    e = A.InSubquery(e, q, negated)
                    continue
                items = [self.parse_expr()]
                while self.eat_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                e = A.InList(e, tuple(items), negated)
                continue
            if self.eat_kw("between"):
                low = self._add_expr()
                self.expect_kw("and")
                high = self._add_expr()
                e = A.Between(e, low, high, negated)
                continue
            if self.eat_kw("like"):
                e = A.BinaryOp("NOT LIKE" if negated else "LIKE",
                               e, self._add_expr())
                continue
            if negated:
                self.i = save
            if self.eat_kw("is"):
                neg = self.eat_kw("not")
                self.expect_kw("null")
                e = A.IsNull(e, neg)
                continue
            return e

    def _add_expr(self):
        e = self._mul_expr()
        while self.at_op("+", "-", "||"):
            op = self.next().value
            e = A.BinaryOp(op, e, self._mul_expr())
        return e

    def _mul_expr(self):
        e = self._unary_expr()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            e = A.BinaryOp(op, e, self._unary_expr())
        return e

    def _unary_expr(self):
        if self.eat_op("-"):
            return A.UnaryOp("-", self._unary_expr())
        return self._postfix_expr()

    def _postfix_expr(self):
        e = self._primary_expr()
        while True:
            if self.eat_op("::"):
                e = A.Cast(e, self._type_name())
            elif self.eat_op("["):
                idx = self.parse_expr()
                self.expect_op("]")
                e = A.Subscript(e, idx)
            elif self.at_op("->", "->>"):
                op = self.next().value
                e = A.BinaryOp(op, e, self._primary_expr())
            elif (self.at_op(".")
                    and self.peek(1).kind in ("name", "kw")):
                # (expr).field — struct access; qualified column names
                # never reach here (consumed inside _primary_expr)
                self.next()
                e = A.FieldAccess(e, self.ident())
            else:
                return e

    def _primary_expr(self):
        t = self.peek()
        if t.kind == "num":
            self.next()
            return A.Lit(t.value)
        if t.kind == "str":
            self.next()
            return A.Lit(t.value, "varchar")
        if self.eat_kw("null"):
            return A.Lit(None)
        if self.eat_kw("true"):
            return A.Lit(True)
        if self.eat_kw("false"):
            return A.Lit(False)
        if self.at_kw("interval"):
            self.next()
            amount_tok = self.next()
            unit = None
            if amount_tok.kind == "str":
                # INTERVAL '5 seconds' / INTERVAL '5' SECOND
                parts = amount_tok.value.split()
                amount = float(parts[0])
                if len(parts) > 1:
                    unit = parts[1].lower()
            else:
                amount = amount_tok.value
            if unit is None and self.peek().kind == "name":
                unit = self.next().value
            unit = unit or "second"
            us = _INTERVAL_UNITS.get(unit)
            if us is None:
                raise SqlParseError(f"unsupported interval unit {unit!r}")
            return A.Lit(int(amount * us), "interval")
        if (t.kind in ("name", "kw") and str(t.value).lower() == "array"
                and self.peek(1).kind == "op" and self.peek(1).value == "["):
            self.next()
            self.expect_op("[")
            items: list = []
            if not self.at_op("]"):
                items.append(self.parse_expr())
                while self.eat_op(","):
                    items.append(self.parse_expr())
            self.expect_op("]")
            return A.ArrayLit(tuple(items))
        if self.eat_kw("case"):
            branches = []
            while self.eat_kw("when"):
                cond = self.parse_expr()
                self.expect_kw("then")
                branches.append((cond, self.parse_expr()))
            else_r = self.parse_expr() if self.eat_kw("else") else None
            self.expect_kw("end")
            return A.Case(tuple(branches), else_r)
        if self.eat_kw("cast"):
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            tn = self._type_name()
            self.expect_op(")")
            return A.Cast(e, tn)
        if self.eat_kw("extract"):
            # EXTRACT(field FROM expr)
            self.expect_op("(")
            field = self.ident()
            self.expect_kw("from")
            e = self.parse_expr()
            self.expect_op(")")
            return A.FuncCall("extract", (A.Lit(field, "varchar"), e))
        if (t.kind == "name" and t.value in ("date", "timestamp", "timestamptz")
                and self.peek(1).kind == "str"):
            # typed literal: DATE '1995-03-15' / TIMESTAMP '… 00:00:00'
            kind = self.next().value
            return A.Lit(self.next().value,
                         "date" if kind == "date" else "timestamp")
        if self.eat_op("("):
            if self.at_kw("select"):
                q = self._select()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind in ("name", "kw"):
            name = self.ident()
            if self.eat_op("("):
                distinct = self.eat_kw("distinct")
                args: list = []
                if self.at_op("*"):
                    self.next()
                    args = [A.Star()]
                elif not self.at_op(")"):
                    args.append(self.parse_expr())
                    while self.eat_op(","):
                        args.append(self.parse_expr())
                self.expect_op(")")
                fc = A.FuncCall(name, tuple(args), distinct)
                if (self.peek().kind in ("name", "kw")
                        and str(self.peek().value).lower() == "within"):
                    # ordered-set agg: fn(frac…) WITHIN GROUP (ORDER BY v)
                    # rewrites to fn(v [, frac]) — percentile_cont / mode
                    self.next()
                    if self.ident() != "group":
                        raise SqlParseError("expected GROUP after WITHIN")
                    self.expect_op("(")
                    self.expect_kw("order")
                    self.expect_kw("by")
                    v = self.parse_expr()
                    self.expect_op(")")
                    fc = A.FuncCall(name, (v,) + fc.args, distinct)
                if (self.peek().kind in ("name", "kw")
                        and str(self.peek().value).lower() == "filter"
                        and self.peek(1).kind == "op"
                        and self.peek(1).value == "("):
                    self.next()
                    self.expect_op("(")
                    self.expect_kw("where")
                    cond = self.parse_expr()
                    self.expect_op(")")
                    fc = dataclasses.replace(fc, filter=cond)
                if self.eat_kw("over"):
                    return self._over_clause(fc)
                return fc
            if self.eat_op("."):
                if self.at_op("*"):
                    self.next()
                    return A.Star(table=name)
                col = self.ident()
                return A.ColumnRef(col, table=name)
            return A.ColumnRef(name)
        raise SqlParseError(f"unexpected token {t} in expression")


def parse_sql(sql: str) -> list[A.Statement]:
    return Parser(sql).parse_statements()


def parse_one(sql: str) -> A.Statement:
    stmts = parse_sql(sql)
    if len(stmts) != 1:
        raise SqlParseError(f"expected one statement, got {len(stmts)}")
    return stmts[0]
