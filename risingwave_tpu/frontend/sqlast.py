"""SQL AST node definitions.

Counterpart of the reference's sqlparser AST (reference: src/sqlparser/src/
ast/mod.rs — trimmed to the streaming-SQL subset this frontend accepts:
CREATE SOURCE / TABLE / MATERIALIZED VIEW / INDEX, DROP, INSERT, SELECT with
joins, GROUP BY, HAVING, ORDER BY / LIMIT / OFFSET, window TVFs
(TUMBLE/HOP), scalar subqueries, UNION ALL, EMIT ON WINDOW CLOSE).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Union


# -- expressions --------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ColumnRef:
    name: str
    table: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Lit:
    value: Any               # python value; None = NULL
    type_hint: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class FuncCall:
    name: str
    args: tuple
    distinct: bool = False
    #: aggregate FILTER (WHERE <cond>) clause (reference:
    #: src/sqlparser/src/ast/mod.rs Function.filter)
    filter: Optional[Any] = None


@dataclasses.dataclass(frozen=True)
class BinaryOp:
    op: str                  # +,-,*,/,%,=,<>,<,<=,>,>=,AND,OR,||
    left: Any
    right: Any


@dataclasses.dataclass(frozen=True)
class UnaryOp:
    op: str                  # NOT, -
    operand: Any


@dataclasses.dataclass(frozen=True)
class Case:
    # [(cond, result), ...], else_result
    branches: tuple
    else_result: Optional[Any] = None


@dataclasses.dataclass(frozen=True)
class InList:
    expr: Any
    items: tuple
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Between:
    expr: Any
    low: Any
    high: Any
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class IsNull:
    expr: Any
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Cast:
    expr: Any
    type_name: str


@dataclasses.dataclass(frozen=True)
class ScalarSubquery:
    query: "Select"


@dataclasses.dataclass(frozen=True)
class InSubquery:
    """<expr> [NOT] IN (SELECT …) — planned as a left semi/anti join
    (reference: the ApplyJoin subquery-unnesting rules in
    src/frontend/src/optimizer/rule/apply_join_transpose_rule.rs)."""

    expr: Any
    query: "Select"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ArrayLit:
    """ARRAY[e1, e2, …] constructor."""

    items: tuple


@dataclasses.dataclass(frozen=True)
class Subscript:
    """<expr>[<index>] — 1-based array element access (PG semantics)."""

    expr: Any
    index: Any


@dataclasses.dataclass(frozen=True)
class FieldAccess:
    """(<expr>).field — struct field access."""

    expr: Any
    field: str


@dataclasses.dataclass(frozen=True)
class WindowFunc:
    """fn(args) OVER (PARTITION BY … ORDER BY …)."""

    func: "FuncCall"
    partition_by: tuple = ()
    order_by: tuple = ()     # OrderItem...


@dataclasses.dataclass(frozen=True)
class Star:
    table: Optional[str] = None


Expr = Union[ColumnRef, Lit, FuncCall, BinaryOp, UnaryOp, Case, InList,
             Between, IsNull, Cast, ScalarSubquery, Star]


# -- relations ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class TableFuncRef:
    """FROM generate_series(1, 10) [AS g]."""

    name: str
    args: tuple
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class WindowTVF:
    """TUMBLE(t, time_col, interval) / HOP(t, time_col, slide, size)."""

    kind: str                # "tumble" | "hop"
    table: TableRef
    time_col: str
    args: tuple              # (size,) for tumble; (slide, size) for hop
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Join:
    kind: str                # inner/left/right/full/left_semi/left_anti
    left: Any
    right: Any
    on: Optional[Expr]
    #: FOR SYSTEM_TIME AS OF PROCTIME() — process-time temporal join:
    #: probe the right side's CURRENT materialized rows, no retractions
    temporal: bool = False


@dataclasses.dataclass(frozen=True)
class SubqueryRef:
    query: "Select"
    alias: str


Relation = Union[TableRef, TableFuncRef, WindowTVF, Join, SubqueryRef]


# -- statements ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class OrderItem:
    expr: Expr
    desc: bool = False
    nulls_last: Optional[bool] = None   # None = PG default by direction


@dataclasses.dataclass(frozen=True)
class Select:
    items: tuple             # SelectItem...
    from_: Optional[Relation]
    where: Optional[Expr] = None
    group_by: tuple = ()
    having: Optional[Expr] = None
    order_by: tuple = ()     # OrderItem...
    limit: Optional[int] = None
    offset: Optional[int] = None
    with_ties: bool = False
    distinct: bool = False
    union_all: Optional["Select"] = None   # SELECT ... UNION ALL SELECT ...
    emit_on_window_close: bool = False


@dataclasses.dataclass(frozen=True)
class ColumnDef:
    name: str
    type_name: str


@dataclasses.dataclass(frozen=True)
class CreateSource:
    name: str
    columns: tuple           # ColumnDef...
    with_options: dict
    watermark: Optional[tuple] = None    # (col, delay_expr)
    append_only: bool = True
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateTable:
    name: str
    columns: tuple
    pk: tuple = ()
    with_options: dict = dataclasses.field(default_factory=dict)
    append_only: bool = False
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateMaterializedView:
    name: str
    query: Select
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateSink:
    """CREATE SINK name FROM upstream | AS SELECT … WITH (connector=…)."""

    name: str
    from_name: Optional[str] = None
    query: Optional[Select] = None
    with_options: dict = dataclasses.field(default_factory=dict)
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateIndex:
    name: str
    table: str
    columns: tuple
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class DropStatement:
    kind: str                # source/table/materialized_view/index
    name: str
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Insert:
    table: str
    columns: tuple
    rows: tuple              # tuple of value-expr tuples


@dataclasses.dataclass(frozen=True)
class Delete:
    table: str
    where: Optional[Any] = None


@dataclasses.dataclass(frozen=True)
class Update:
    table: str
    assignments: tuple            # ((col, expr), ...)
    where: Optional[Any] = None


@dataclasses.dataclass(frozen=True)
class Query:
    """Top-level SELECT statement."""

    select: Select


@dataclasses.dataclass(frozen=True)
class ShowStatement:
    what: str                # tables/sources/materialized_views


@dataclasses.dataclass(frozen=True)
class FlushStatement:
    pass


@dataclasses.dataclass(frozen=True)
class SetStatement:
    """SET param = value (system params / session vars). ``system``
    marks the ALTER SYSTEM SET variant: the change propagates to every
    session attached to the same meta via a notification."""

    name: str
    value: Any
    system: bool = False


@dataclasses.dataclass(frozen=True)
class Explain:
    """EXPLAIN <statement>: show the optimized plan without executing
    (reference: handler/explain.rs — plan-only path)."""

    stmt: "Statement"


Statement = Union[CreateSink, CreateSource, CreateTable, CreateMaterializedView,
                  CreateIndex, DropStatement, Insert, Delete, Update, Query,
                  ShowStatement, FlushStatement, SetStatement, Explain]
