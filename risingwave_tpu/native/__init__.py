"""Native runtime components (C++, ctypes-bound).

The compute path is JAX/XLA on the TPU; the *runtime around it* — here the
checkpoint row codec — is native C++ where the reference's equivalent tier
is native Rust (src/common/src/util/value_encoding/, memcmp_encoding.rs).
The library builds on first use with the in-image toolchain (g++ -O3) and
caches the .so next to the source keyed by a content hash; environments
without a compiler fall back to the Python encoders transparently
(``codec() is None``). Set RW_TPU_DISABLE_NATIVE=1 to force the fallback.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "rowcodec.cpp")

_lib = None
_tried = False

# DataType.kind -> native type code (rowcodec.cpp header comment)
_CODE_BY_KIND = {
    "BOOL": 0, "INT16": 1, "INT32": 2, "DATE": 2,
    "INT64": 3, "TIME": 3, "TIMESTAMP": 3, "INTERVAL": 3, "SERIAL": 3,
    "DECIMAL": 3,
    "FLOAT32": 4, "FLOAT64": 5,
    "VARCHAR": 6, "BYTEA": 6,
}


def _build() -> Optional[ctypes.CDLL]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_DIR, f"_rowcodec_{digest}.so")
    if not os.path.exists(so_path):
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)
        except (OSError, subprocess.SubprocessError):
            return None
    lib = ctypes.CDLL(so_path)
    lib.rw_encode.restype = ctypes.c_longlong
    lib.rw_encode.argtypes = [
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong),
    ]
    if lib.rw_abi_version() != 1:
        return None
    return lib


import threading as _threading

_build_lock = _threading.Lock()


def codec() -> Optional["RowCodec"]:
    """The process-wide codec, or None when native is unavailable.
    Thread-safe: sessions pre-warm the build from a background thread."""
    global _lib, _tried
    with _build_lock:
        if not _tried:
            _tried = True
            if os.environ.get("RW_TPU_DISABLE_NATIVE") != "1":
                lib = _build()
                if lib is not None:
                    _lib = RowCodec(lib)
    return _lib


class RowCodec:
    def __init__(self, lib: ctypes.CDLL):
        self.lib = lib

    def _prep_columns(self, datas: Sequence[np.ndarray],
                      masks: Sequence[np.ndarray], types) -> tuple:
        """-> (codes, data_ptrs, mask_ptrs, blob_ptrs, off_ptrs, keepalive,
        blob_bytes)"""
        from ..common.types import GLOBAL_STRING_DICT
        n = len(types)
        codes = (ctypes.c_int * n)()
        data_ptrs = (ctypes.c_void_p * n)()
        mask_ptrs = (ctypes.c_void_p * n)()
        blob_ptrs = (ctypes.c_void_p * n)()
        off_ptrs = (ctypes.c_void_p * n)()
        keep = []
        blob_bytes = 0
        for i, t in enumerate(types):
            code = _CODE_BY_KIND[t.kind.name]
            codes[i] = code
            mask = np.ascontiguousarray(masks[i], np.uint8)
            keep.append(mask)
            mask_ptrs[i] = mask.ctypes.data_as(ctypes.c_void_p).value
            if code == 6:
                # datas[i] is already delta-gathered by _encode: the uniq
                # set and blob are dirty-sized, not capacity-sized
                ids = np.ascontiguousarray(datas[i]).astype(np.int64)
                uniq, inv = np.unique(ids, return_inverse=True)
                parts = [GLOBAL_STRING_DICT.lookup(int(u)).encode("utf-8")
                         for u in uniq]
                offs = np.zeros(len(parts) + 1, np.int64)
                np.cumsum([len(p) for p in parts], out=offs[1:])
                blob = np.frombuffer(b"".join(parts) or b"\x00", np.uint8)
                blob_bytes += max((len(p) for p in parts), default=0)
                inv64 = np.ascontiguousarray(inv, np.int64)
                keep.extend((blob, offs, inv64))
                data_ptrs[i] = inv64.ctypes.data_as(ctypes.c_void_p).value
                blob_ptrs[i] = blob.ctypes.data_as(ctypes.c_void_p).value
                off_ptrs[i] = offs.ctypes.data_as(ctypes.c_void_p).value
            else:
                # coerce to the dtype the C side reads for this code —
                # the Python encoders coerce via int()/float() the same way
                want = {0: np.uint8, 1: np.int16, 2: np.int32,
                        3: np.int64, 4: np.float32, 5: np.float64}[code]
                arr = np.ascontiguousarray(datas[i])
                if arr.dtype != want:
                    arr = arr.astype(want)
                keep.append(arr)
                data_ptrs[i] = arr.ctypes.data_as(ctypes.c_void_p).value
        return codes, data_ptrs, mask_ptrs, blob_ptrs, off_ptrs, keep, \
            blob_bytes

    def _encode(self, key_mode: int, datas, masks, types,
                indices: np.ndarray) -> list:
        n = len(types)
        sel = np.ascontiguousarray(indices, np.int64)
        n_sel = len(sel)
        if n_sel == 0:
            return []
        # gather the dirty delta FIRST: all per-column prep (string
        # uniquing, dtype coercion) must scale with the delta, not the
        # full state capacity
        datas = [np.asarray(d).reshape(-1)[sel] for d in datas]
        masks = [np.asarray(m).reshape(-1)[sel] for m in masks]
        (codes, data_ptrs, mask_ptrs, blob_ptrs, off_ptrs, keep,
         blob_bytes) = self._prep_columns(datas, masks, types)
        idx = np.arange(n_sel, dtype=np.int64)
        out_offsets = np.zeros(n_sel + 1, np.int64)
        # capacity estimate: ≤9B per fixed col per row; each string col
        # ≤ 2x its longest string (escape doubling) + framing per row
        cap = n_sel * (9 * n + 8 + 2 * blob_bytes + 6) + 64
        for _ in range(3):
            out = np.zeros(cap, np.uint8)
            written = self.lib.rw_encode(
                key_mode, n, codes, data_ptrs, mask_ptrs, blob_ptrs,
                off_ptrs, idx.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_longlong)),
                n_sel,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                cap,
                out_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)))
            if written >= 0:
                buf = out.tobytes()
                return [buf[out_offsets[r]:out_offsets[r + 1]]
                        for r in range(n_sel)]
            cap *= 4
        raise RuntimeError("native row encode: buffer growth failed")

    def encode_value_rows(self, datas, masks, types, indices) -> list:
        """Columnar buffers -> value-encoded bytes per selected row
        (byte-identical to common/row.py encode_value_row)."""
        return self._encode(0, datas, masks, types, indices)

    def encode_keys(self, datas, masks, types, indices) -> list:
        """Columnar buffers -> memcomparable key bytes per selected row
        (byte-identical to common/row.py encode_key)."""
        return self._encode(1, datas, masks, types, indices)
