// Native row serde: batch key/value encoding for the checkpoint path.
//
// C++ counterpart of the hot host-side encoding loops in
// risingwave_tpu/common/row.py (the reference implements the same tier in
// Rust: src/common/src/util/value_encoding/ and util/memcmp_encoding.rs).
// The checkpoint write path walks dirty device rows on the host; doing the
// per-row, per-column byte packing in Python dominates barrier cost at
// real state sizes, so this library encodes whole dirty batches from
// columnar numpy buffers in one call.
//
// Byte formats are EXACTLY those of common/row.py (tests cross-check):
//   value row:  per column: 0x00 (null) | 0x01 + payload
//               bool: 1 byte; int*: little-endian int64; float: LE f64;
//               string: u32 LE length + utf8 bytes
//   key:        per column: 0x00 (null) | 0x01 + memcomparable payload
//               bool: 1 byte; int16/32/64: sign-flipped big-endian;
//               float: order-preserving f64 bit transform;
//               string: 0x00 -> 0x00 0xff escape, 0x00 0x00 terminator
//
// Type codes: 0=bool(u8), 1=int16, 2=int32, 3=int64, 4=float32,
//             5=float64, 6=string (data = int64 uniq index per row;
//             blob/offsets give the uniq string table).

#include <cstdint>
#include <cstring>

namespace {

inline uint64_t f64_key_bits(double v) {
    uint64_t bits;
    std::memcpy(&bits, &v, 8);
    if (bits & (1ULL << 63)) {
        bits = ~bits;                 // negative: flip all
    } else {
        bits |= (1ULL << 63);         // positive: flip sign
    }
    return bits;
}

inline void put_be(unsigned char* out, uint64_t v, int nbytes) {
    for (int i = 0; i < nbytes; ++i) {
        out[i] = (unsigned char)(v >> (8 * (nbytes - 1 - i)));
    }
}

struct ColView {
    int code;
    const void* data;
    const unsigned char* mask;
    const unsigned char* blob;        // string uniq blob (code 6)
    const long long* offsets;         // uniq offsets, len = n_uniq + 1
};

inline double load_f(const ColView& c, long long row) {
    if (c.code == 4) return (double)((const float*)c.data)[row];
    return ((const double*)c.data)[row];
}

inline int64_t load_i(const ColView& c, long long row) {
    switch (c.code) {
        case 0: return ((const unsigned char*)c.data)[row];
        case 1: return ((const int16_t*)c.data)[row];
        case 2: return ((const int32_t*)c.data)[row];
        default: return ((const int64_t*)c.data)[row];
    }
}

// returns bytes written, or -1 on overflow of [out, out+cap)
inline long long enc_value_col(const ColView& c, long long row,
                               unsigned char* out, long long cap) {
    if (!c.mask[row]) {
        if (cap < 1) return -1;
        out[0] = 0x00;
        return 1;
    }
    long long w = 0;
    if (cap < 2) return -1;
    out[w++] = 0x01;
    switch (c.code) {
        case 0:
            out[w++] = ((const unsigned char*)c.data)[row] ? 1 : 0;
            break;
        case 4: case 5: {
            if (cap < 1 + 8) return -1;
            double d = load_f(c, row);
            std::memcpy(out + w, &d, 8);    // little-endian host assumed
            w += 8;
            break;
        }
        case 6: {
            long long u = ((const int64_t*)c.data)[row];
            long long lo = c.offsets[u], hi = c.offsets[u + 1];
            long long n = hi - lo;
            if (cap < 1 + 4 + n) return -1;
            uint32_t len32 = (uint32_t)n;
            std::memcpy(out + w, &len32, 4);
            w += 4;
            std::memcpy(out + w, c.blob + lo, n);
            w += n;
            break;
        }
        default: {
            if (cap < 1 + 8) return -1;
            int64_t v = load_i(c, row);
            std::memcpy(out + w, &v, 8);
            w += 8;
            break;
        }
    }
    return w;
}

inline long long enc_key_col(const ColView& c, long long row,
                             unsigned char* out, long long cap) {
    if (!c.mask[row]) {
        if (cap < 1) return -1;
        out[0] = 0x00;
        return 1;
    }
    if (cap < 2) return -1;
    long long w = 0;
    out[w++] = 0x01;
    switch (c.code) {
        case 0:
            out[w++] = ((const unsigned char*)c.data)[row] ? 1 : 0;
            break;
        case 1: {
            if (cap < 1 + 2) return -1;
            uint64_t u = (uint64_t)(load_i(c, row) + (1LL << 15));
            put_be(out + w, u, 2);
            w += 2;
            break;
        }
        case 2: {
            if (cap < 1 + 4) return -1;
            uint64_t u = (uint64_t)(load_i(c, row) + (1LL << 31));
            put_be(out + w, u, 4);
            w += 4;
            break;
        }
        case 4: case 5: {
            if (cap < 1 + 8) return -1;
            put_be(out + w, f64_key_bits(load_f(c, row)), 8);
            w += 8;
            break;
        }
        case 6: {
            long long u = ((const int64_t*)c.data)[row];
            long long lo = c.offsets[u], hi = c.offsets[u + 1];
            for (long long i = lo; i < hi; ++i) {
                unsigned char ch = c.blob[i];
                if (ch == 0x00) {
                    if (w + 2 > cap) return -1;
                    out[w++] = 0x00;
                    out[w++] = 0xff;
                } else {
                    if (w + 1 > cap) return -1;
                    out[w++] = ch;
                }
            }
            if (w + 2 > cap) return -1;
            out[w++] = 0x00;
            out[w++] = 0x00;
            break;
        }
        default: {
            if (cap < 1 + 8) return -1;
            uint64_t u = (uint64_t)load_i(c, row) ^ (1ULL << 63);
            put_be(out + w, u, 8);
            w += 8;
            break;
        }
    }
    return w;
}

inline long long encode_rows(bool key_mode, int ncols, const ColView* cols,
                             const long long* idx, long long n_sel,
                             unsigned char* out, long long out_cap,
                             long long* out_offsets) {
    long long pos = 0;
    out_offsets[0] = 0;
    for (long long r = 0; r < n_sel; ++r) {
        long long row = idx[r];
        for (int ci = 0; ci < ncols; ++ci) {
            long long w = key_mode
                ? enc_key_col(cols[ci], row, out + pos, out_cap - pos)
                : enc_value_col(cols[ci], row, out + pos, out_cap - pos);
            if (w < 0) return -1;
            pos += w;
        }
        out_offsets[r + 1] = pos;
    }
    return pos;
}

}  // namespace

extern "C" {

// Shared signature for both encoders. Per column i:
//   typecodes[i], data[i], masks[i]; for code-6 columns blob[i]/offsets[i]
//   hold the uniq string table and data[i] is int64 uniq-index per row.
// idx selects rows; returns total bytes or -1 if out_cap is too small.
long long rw_encode(int key_mode, int ncols, const int* typecodes,
                    const void** data, const unsigned char** masks,
                    const unsigned char** blobs, const long long** offsets,
                    const long long* idx, long long n_sel,
                    unsigned char* out, long long out_cap,
                    long long* out_offsets) {
    ColView cols[256];
    if (ncols > 256) return -2;
    for (int i = 0; i < ncols; ++i) {
        cols[i].code = typecodes[i];
        cols[i].data = data[i];
        cols[i].mask = masks[i];
        cols[i].blob = blobs ? blobs[i] : nullptr;
        cols[i].offsets = offsets ? offsets[i] : nullptr;
    }
    return encode_rows(key_mode != 0, ncols, cols, idx, n_sel, out, out_cap,
                       out_offsets);
}

int rw_abi_version() { return 1; }

}  // extern "C"
