"""UDF specs + function shipping for the out-of-process plane.

A registered UDF is a ``UdfSpec``; the process-global ``UDF_SPECS``
registry is the replay source for server (re)spawns: every spawn
replays every live registration, so a freshly respawned server is
always a function-complete replacement (the "seeded respawn" of
ISSUE 15).

Function shipping — the ONE place a function crosses a process
boundary, at REGISTRATION time (batches never carry code, and no user
VALUE is ever pickled):

* by reference — ``module:qualname`` when the module imports and the
  attribute resolves back to the very same object (plain ``def``s in
  importable modules; the spawned server inherits the client's
  ``sys.path`` so test-local modules resolve too);
* by code — ``marshal`` of the code object + defaults + closure cells
  for lambdas/closures. Marshal carries only code and plain data; the
  server rebuilds the function against a minimal globals namespace
  (builtins + numpy/math/re/json), so a closure over sockets, sessions
  or other live state refuses loudly (``UdfNotPortableError``) instead
  of half-shipping.
"""

from __future__ import annotations

import base64
import dataclasses
import importlib
import marshal
from typing import Callable, Dict, Optional, Sequence, Tuple

from ..common.types import DataType


class UdfNotPortableError(TypeError):
    """The function cannot cross the process boundary (unmarshalable
    closure, unresolvable reference). Register it under
    ``[udf] mode = "inproc"`` — the documented degraded mode — or move
    it to an importable module."""


@dataclasses.dataclass(frozen=True)
class UdfSpec:
    name: str
    fn: Callable
    arg_types: Tuple[DataType, ...]
    return_type: DataType
    vectorized: bool = False


#: process-global registry: name -> UdfSpec. The client plane replays it
#: into every (re)spawned server; ``expr/udf.py`` register/drop mutate it.
UDF_SPECS: Dict[str, UdfSpec] = {}


def get_udf(name: str) -> UdfSpec:
    spec = UDF_SPECS.get(name)
    if spec is None:
        raise KeyError(f"no registered UDF {name!r}")
    return spec


# -- function shipping --------------------------------------------------------

def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def ship_function(fn: Callable) -> dict:
    """Function → JSON-safe shipping payload (see module docstring)."""
    mod = getattr(fn, "__module__", None)
    qn = getattr(fn, "__qualname__", "") or ""
    # "__main__" names a DIFFERENT module in the server process (its
    # own entry point) — scripts' functions must ship by code instead
    if mod and mod != "__main__" and qn and "<" not in qn \
            and "." not in qn:
        try:
            m = importlib.import_module(mod)
            if getattr(m, qn, None) is fn:
                return {"how": "ref", "module": mod, "qualname": qn}
        except ImportError:
            pass
    code = getattr(fn, "__code__", None)
    if code is None:
        raise UdfNotPortableError(
            f"{fn!r} has no code object to ship (builtin/partial?); "
            "use a plain function, or [udf] mode = \"inproc\"")
    try:
        payload = {
            "how": "code",
            "code": _b64(marshal.dumps(code)),
            "name": fn.__name__,
            "defaults": _b64(marshal.dumps(fn.__defaults__)),
            "closure": _b64(marshal.dumps(tuple(
                c.cell_contents for c in (fn.__closure__ or ())))),
        }
    except ValueError as e:
        raise UdfNotPortableError(
            f"UDF {fn.__name__!r} closes over unmarshalable state "
            f"({e}); move it to an importable module or register it "
            "under [udf] mode = \"inproc\"") from None
    return payload


def load_function(d: dict) -> Callable:
    """Shipping payload → callable (server side)."""
    if d["how"] == "ref":
        m = importlib.import_module(d["module"])
        fn = getattr(m, d["qualname"], None)
        if not callable(fn):
            raise UdfNotPortableError(
                f"{d['module']}:{d['qualname']} did not resolve to a "
                "callable on the server")
        return fn
    import builtins
    import json as _json
    import math
    import re as _re
    import time as _time
    import types

    import numpy as _np
    code = marshal.loads(_unb64(d["code"]))
    defaults = marshal.loads(_unb64(d["defaults"]))
    cells = tuple(types.CellType(v)
                  for v in marshal.loads(_unb64(d["closure"])))
    # code-shipped functions rebuild against a MINIMAL namespace: a
    # lambda referencing its defining module's other globals must ship
    # by reference (importable module) instead
    glb = {"__builtins__": builtins, "np": _np, "numpy": _np,
           "math": math, "re": _re, "json": _json, "time": _time}
    return types.FunctionType(code, glb, d["name"], defaults,
                              cells or None)


def spec_to_wire(spec: UdfSpec) -> dict:
    from ..common.interchange import udf_type_to_wire
    return {
        "name": spec.name,
        "fn": ship_function(spec.fn),
        "arg_types": [udf_type_to_wire(t) for t in spec.arg_types],
        "return_type": udf_type_to_wire(spec.return_type),
        "vectorized": spec.vectorized,
    }


def spec_from_wire(d: dict) -> UdfSpec:
    from ..common.interchange import udf_type_from_wire
    return UdfSpec(
        name=d["name"],
        fn=load_function(d["fn"]),
        arg_types=tuple(udf_type_from_wire(t) for t in d["arg_types"]),
        return_type=udf_type_from_wire(d["return_type"]),
        vectorized=bool(d["vectorized"]),
    )
