"""The one sanctioned evaluator of a registered UDF callable.

Both sides of the wire run THIS code — the server on decoded wire
batches, the inproc degraded mode on the same decoded host columns — so
out-of-process results are bit-exact vs in-process by construction:
there is exactly one strict-NULL / type-conversion implementation.

The ``udf-boundary`` rwlint rule (analysis/rules_boundary.py) enforces
the choke point: no module outside this file and ``udf/server.py`` may
call ``eval_udf_batch`` (the client's inproc path carries the one
reasoned allow), and nothing may invoke a registry spec's ``.fn``
directly.

Column convention (host, LOGICAL):
  * fixed-width arguments/results are numpy arrays in the physical
    encoding (DECIMAL = scaled int64, BOOL = bool, ...);
  * string-typed arguments/results are object arrays of ``str``/None —
    decoded BEFORE this layer (dictionary ids never cross it);
  * masks are numpy bool arrays; strict-NULL means any NULL argument
    yields NULL without calling the function, and a function returning
    None yields NULL.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .registry import UdfSpec


def strict_mask(masks: Sequence[np.ndarray]) -> np.ndarray:
    m = np.asarray(masks[0], dtype=bool).copy()
    for mm in masks[1:]:
        m &= np.asarray(mm, dtype=bool)
    return m


def eval_udf_batch(spec: UdfSpec, datas: Sequence[np.ndarray],
                   masks: Sequence[np.ndarray]
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Evaluate one columnar batch. Returns ``(data, mask)`` in the
    column convention above."""
    m = strict_mask(masks)
    rt = spec.return_type
    if spec.vectorized:
        # vectorized contract (unchanged from the in-process original):
        # fn(*numpy_arrays) over PHYSICAL values, full arrays in — the
        # strict mask applies to the result, not the inputs. No VARCHAR.
        out = np.asarray(spec.fn(*[np.asarray(d) for d in datas]))
        return out.astype(rt.np_dtype), m
    n = len(m)
    if rt.is_string:
        out: np.ndarray = np.empty(n, dtype=object)
        out.fill(None)
    else:
        out = np.full(n, rt.null_sentinel(), rt.np_dtype)
    rows = np.nonzero(m)[0]
    for r in rows:
        args = [a[r] if t.is_string else t.to_python(a[r])
                for t, a in zip(spec.arg_types, datas)]
        v = spec.fn(*args)
        if v is None:
            m[r] = False
        elif rt.is_string:
            out[r] = v if isinstance(v, str) else v.decode()
        else:
            out[r] = rt.to_physical(v)
    return out, m


def decode_string_args(spec: UdfSpec, datas: Sequence[np.ndarray],
                       masks: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Physical host columns → the column convention: string-typed args
    decode dictionary ids to object arrays of str (masked-out slots
    stay None — their ids are sentinels, not lookups). Runs CLIENT-side
    in both modes, so the wire and the inproc path see identical
    inputs."""
    out: List[np.ndarray] = []
    for t, d, mk in zip(spec.arg_types, datas, masks):
        d = np.asarray(d)
        if t.is_string and d.dtype != object:
            mk = np.asarray(mk, dtype=bool)
            dec = np.empty(len(mk), dtype=object)
            dec.fill(None)
            for i in np.nonzero(mk)[0]:
                dec[i] = t.to_python(d[i])
            d = dec
        out.append(d)
    return out
