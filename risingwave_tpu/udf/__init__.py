"""Out-of-process UDF plane (ISSUE 15, docs/robustness.md).

Counterpart of the reference's Arrow-Flight UDF boundary
(reference: src/udf/src/lib.rs:28 ArrowFlightUdfClient — user functions
live behind a wire so one slow, hanging, or crashing UDF can never wedge
an epoch). Layout:

``registry.py``  UdfSpec + the process-global spec registry + function
                 shipping (by importable reference, or marshaled code
                 for lambdas — never pickle of user VALUES).
``runtime.py``   the one sanctioned evaluator of a registered callable
                 (shared bit-exact by the server and the inproc
                 degraded mode; rwlint rule ``udf-boundary`` keeps it
                 the single choke point).
``client.py``    UdfPlane — spawn/kill/respawn + per-call deadlines +
                 bounded-retry batch replay + generation fencing +
                 bounded in-flight backpressure; routes ``expr/udf.py``.
``server.py``    the standalone server process (`ctl udf serve`, or
                 auto-spawned by the plane) answering udf_call frames
                 over rpc/wire.py with common/interchange.py batches.
"""

from .client import (  # noqa: F401
    UdfCallError, UdfError, UdfNotPortableError, UdfOverloadedError,
    UdfServerError, UdfTimeoutError, udf_plane,
)
from .registry import UdfSpec, get_udf  # noqa: F401
