"""Client side of the out-of-process UDF plane.

``UdfPlane`` is the process-global boundary every registered UDF call
crosses (``expr/udf.py`` routes here; the ``udf-boundary`` lint keeps
it that way). It owns the robustness contract the other planes already
have (docs/robustness.md "UDF isolation plane"):

* per-call DEADLINES (``[udf] call_timeout_s``) — a UDF that hangs,
  busy-loops, or segfaults its server never stalls the caller past the
  deadline;
* crash/timeout detection → KILL + seeded RESPAWN (the fresh server is
  re-seeded with every live registration) + bounded-retry REPLAY of the
  batch — UDF calls are pure per-row, so replaying a batch is safe;
* exhausted retries surface a TYPED error (``UdfTimeoutError`` /
  ``UdfCallError``) that fails the statement, never the epoch loop;
* GENERATION FENCING — every frame carries (gen, rid); a stale server
  incarnation's late or chaos-duplicated reply is dropped, counted,
  never taken for a fresh one;
* BACKPRESSURE — at most ``max_inflight`` batches inside the boundary;
  excess callers fail typed (``UdfOverloadedError``) after
  ``queue_timeout_s`` instead of queueing unboundedly.

The wire rides rpc/wire.py sync frames on the ``s->udf`` fault-plane
link (replies: ``udf->s``), so a seeded ChaosSchedule drops/delays/
duplicates UDF traffic exactly like any internal link. Failpoint sites:
``udf.spawn``, ``udf.call``, ``udf.reply``, ``udf.respawn`` client-side
and ``udf.server.eval`` in the server process.

``[udf] mode = "inproc"`` is the documented DEGRADED mode: the same
decode + evaluator code runs in-process (bit-exact with the wire path),
with none of the isolation.
"""

from __future__ import annotations

import atexit
import itertools
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.config import UdfConfig
from ..common.failpoint import fail_point
from ..rpc.wire import read_frame_sync, write_frame_sync
from .registry import (
    UDF_SPECS, UdfNotPortableError, UdfSpec, get_udf, ship_function,
    spec_to_wire,
)
from .runtime import decode_string_args, eval_udf_batch

#: fault-plane link of the client→server direction (docs/robustness.md)
CALL_LINK = "s->udf"


class UdfError(RuntimeError):
    """Base of the plane's typed errors: fails the STATEMENT that
    evaluated the UDF; the epoch loop and every other job keep going."""


class UdfCallError(UdfError):
    """Retries exhausted: the batch could not be evaluated despite
    kill+respawn+replay."""


class UdfTimeoutError(UdfCallError):
    """Every attempt missed the per-call deadline (hanging/busy-looping
    user code, or a link eating frames faster than the retry budget)."""


class UdfOverloadedError(UdfError):
    """Backpressure: more than ``max_inflight`` batches were already
    inside the boundary for longer than ``queue_timeout_s``."""


class UdfServerError(UdfError):
    """The user function RAISED on the server. Deterministic, so it is
    surfaced immediately — no respawn/replay cycles are burned on it."""


class _LinkDown(Exception):
    """Internal: connection lost / EOF mid-conversation."""


class _CallTimeout(Exception):
    """Internal: the per-call deadline elapsed without a valid reply."""


class _ServerHandle:
    """One server incarnation: subprocess (or external addr) + sync
    socket. Mirrors worker/compactor client handles."""

    def __init__(self) -> None:
        self.proc: Optional[subprocess.Popen] = None
        self.sock: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self.external = False

    def spawn(self, spawn_timeout_s: float,
              trace_path: Optional[str]) -> None:
        env = dict(os.environ)
        # UDF evaluation is host numpy — never let a wedged accelerator
        # tunnel hang the server's (jax-importing) startup
        env.setdefault("JAX_PLATFORMS", "cpu")
        # by-reference function shipping resolves modules against the
        # CLIENT's import path (test-local modules included)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] +
            [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        argv = [sys.executable, "-m", "risingwave_tpu.udf.server",
                "--port", "0"]
        if trace_path:
            argv += ["--trace-path", trace_path]
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=None, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        assert self.proc.stdout is not None
        import select
        deadline = time.monotonic() + spawn_timeout_s
        buf = b""
        fd = self.proc.stdout.fileno()
        port = None
        while time.monotonic() < deadline:
            ready, _, _ = select.select(
                [fd], [], [], max(0.05, deadline - time.monotonic()))
            if not ready:
                continue
            chunk = os.read(fd, 4096)
            if not chunk:
                raise _LinkDown(
                    f"UDF server exited during startup "
                    f"(rc={self.proc.poll()})")
            buf += chunk
            for line in buf.decode(errors="replace").splitlines():
                if line.startswith("UDF_READY"):
                    port = int(line.split()[1])
                    break
            if port is not None:
                break
        if port is None:
            self.proc.kill()
            raise _LinkDown("UDF server startup timed out")
        self.port = port
        self.sock = socket.create_connection(("127.0.0.1", port))

    def connect_external(self, addr: str,
                         spawn_timeout_s: float) -> None:
        host, _, port = addr.rpartition(":")
        self.external = True
        self.sock = socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=spawn_timeout_s)
        self.sock.settimeout(None)

    @property
    def alive(self) -> bool:
        if self.sock is None:
            return False
        if self.proc is not None and self.proc.poll() is not None:
            return False
        return True

    def kill(self) -> None:
        """Kill -9 the incarnation (wedged servers don't get a graceful
        path — the whole point). External servers just lose the socket."""
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.proc = None


class UdfPlane:
    """Process-global UDF boundary (one per client process). Sessions
    configure it from ``[udf]``; registration and evaluation reach it
    through ``expr/udf.py``."""

    def __init__(self, config: Optional[UdfConfig] = None) -> None:
        self.config = config or UdfConfig()
        self.trace_dir: Optional[str] = None
        self._lock = threading.RLock()        # lifecycle + registry
        self._conn_lock = threading.RLock()   # one wire conversation
        self._sem = threading.BoundedSemaphore(
            max(1, self.config.max_inflight))
        self._sem_size = max(1, self.config.max_inflight)
        self._handle: Optional[_ServerHandle] = None
        self.generation = 0
        self._rid = itertools.count(1)
        self._inflight = 0
        self.stats: Dict[str, int] = {
            "calls": 0, "rows": 0, "retries": 0, "respawns": 0,
            "timeouts": 0, "user_errors": 0, "stale_replies_dropped": 0,
            "overloads": 0, "inflight_peak": 0, "spawns": 0,
        }

    # -- lifecycle -------------------------------------------------------------

    def configure(self, config: UdfConfig,
                  trace_dir: Optional[str] = None) -> None:
        with self._lock:
            self.config = config
            if trace_dir is not None:
                self.trace_dir = trace_dir
            if max(1, config.max_inflight) != self._sem_size:
                self._sem_size = max(1, config.max_inflight)
                self._sem = threading.BoundedSemaphore(self._sem_size)

    def register(self, spec: UdfSpec) -> None:
        """Validate portability EAGERLY (a spec that cannot ship must
        refuse at CREATE time, not at first call mid-epoch), record it,
        and ship it to a live server."""
        if self.config.mode != "inproc":
            from ..common.interchange import udf_type_to_wire
            for t in (*spec.arg_types, spec.return_type):
                udf_type_to_wire(t)
            ship_function(spec.fn)
        with self._lock:
            UDF_SPECS[spec.name] = spec
        with self._conn_lock:
            h = self._handle
            if h is not None and h.alive:
                try:
                    self._request(h, {"type": "udf_register",
                                      "spec": spec_to_wire(spec)},
                                  self.config.spawn_timeout_s)
                except (_LinkDown, _CallTimeout, OSError):
                    self._fail_server()   # next call respawns + replays

    def drop(self, name: str) -> None:
        with self._lock:
            UDF_SPECS.pop(name, None)
        with self._conn_lock:
            h = self._handle
            if h is not None and h.alive:
                try:
                    self._request(h, {"type": "udf_drop", "name": name},
                                  self.config.spawn_timeout_s)
                except (_LinkDown, _CallTimeout, OSError):
                    self._fail_server()

    def kill_server(self) -> None:
        """Chaos hook: SIGKILL the current server incarnation (the next
        call detects it, respawns, and replays)."""
        with self._lock:
            if self._handle is not None:
                self._handle.kill()

    def shutdown_server(self) -> None:
        """Tear the server down (tests / atexit). Registrations stay:
        the next call auto-respawns a seeded server."""
        self.kill_server()
        with self._lock:
            self._handle = None

    def server_pid(self) -> Optional[int]:
        with self._lock:
            h = self._handle
            return h.proc.pid if h is not None and h.proc is not None \
                else None

    # -- evaluation ------------------------------------------------------------

    def call(self, name: str, datas: List[np.ndarray],
             masks: List[np.ndarray]) -> Tuple[np.ndarray, np.ndarray]:
        """Evaluate one columnar batch of UDF ``name``. Inputs are host
        physical columns; returns the runtime column convention
        (udf/runtime.py). Raises only typed ``UdfError``s."""
        spec = get_udf(name)
        masks = [np.asarray(m, dtype=bool) for m in masks]
        datas = decode_string_args(spec, datas, masks)
        if self.config.mode == "inproc":
            # the documented degraded mode: same decode + same evaluator
            # as the server, in-process — none of the isolation
            return eval_udf_batch(spec, datas, masks)  # rwlint: allow(udf-boundary): [udf] mode="inproc" is the documented degraded mode — the one sanctioned in-process evaluation of user code
        # bind the semaphore object: configure() may swap self._sem for
        # a resized one mid-call, and releasing the NEW (full) semaphore
        # would raise an untyped ValueError out of the boundary
        sem = self._sem
        if not sem.acquire(timeout=self.config.queue_timeout_s):
            self.stats["overloads"] += 1
            raise UdfOverloadedError(
                f"UDF boundary at capacity ({self._sem_size} batches in "
                f"flight for > {self.config.queue_timeout_s}s) — raise "
                "[udf] max_inflight or shed load")
        with self._lock:
            self._inflight += 1
            self.stats["inflight_peak"] = max(
                self.stats["inflight_peak"], self._inflight)
        try:
            return self._call_process(spec, datas, masks)
        finally:
            with self._lock:
                self._inflight -= 1
            sem.release()

    def _call_process(self, spec: UdfSpec, datas, masks):
        from ..common.interchange import udf_batch_to_wire, wire_to_udf_col
        batch = udf_batch_to_wire(datas, masks, spec.arg_types)
        attempts = max(1, self.config.max_retries + 1)
        timed_out = False
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            if attempt:
                self.stats["retries"] += 1
            try:
                with self._conn_lock:
                    h = self._ensure_server()
                    fail_point("udf.call")
                    reply = self._request(
                        h, {"type": "udf_call", "name": spec.name,
                            "batch": batch},
                        self.config.call_timeout_s)
            except _CallTimeout as e:
                self.stats["timeouts"] += 1
                timed_out, last = True, e
                self._fail_server()
                continue
            except (_LinkDown, ConnectionError, OSError) as e:
                last = e
                self._fail_server()
                continue
            if not reply.get("ok", False):
                if reply.get("error_kind") == "user":
                    self.stats["user_errors"] += 1
                    raise UdfServerError(
                        f"UDF {spec.name!r} raised: {reply.get('error')}")
                raise UdfCallError(
                    f"UDF server rejected {spec.name!r}: "
                    f"{reply.get('error')}")
            fail_point("udf.reply")
            self.stats["calls"] += 1
            self.stats["rows"] += int(batch.get("n") or 0)
            return wire_to_udf_col(reply["result"], spec.return_type)
        kind = UdfTimeoutError if timed_out else UdfCallError
        raise kind(
            f"UDF {spec.name!r} failed after {attempts} attempts "
            f"(deadline {self.config.call_timeout_s}s per call, server "
            f"respawned {attempts - 1}x): {last}")

    # -- server management (under _conn_lock) ----------------------------------

    def _ensure_server(self) -> _ServerHandle:
        h = self._handle
        if h is not None and h.alive:
            return h
        fail_point("udf.spawn")
        h = _ServerHandle()
        if self.config.addr:
            h.connect_external(self.config.addr,
                               self.config.spawn_timeout_s)
        else:
            trace_path = None
            if self.trace_dir:
                trace_path = os.path.join(self.trace_dir,
                                          "chaos_trace_udf.jsonl")
            h.spawn(self.config.spawn_timeout_s, trace_path)
        with self._lock:
            self.generation += 1
            self.stats["spawns"] += 1
            self._handle = h
        # seeded respawn: replay EVERY live registration so the new
        # incarnation is a function-complete replacement
        try:
            for spec in list(UDF_SPECS.values()):
                r = self._request(h, {"type": "udf_register",
                                      "spec": spec_to_wire(spec)},
                                  self.config.spawn_timeout_s)
                if not r.get("ok", False):
                    raise _LinkDown(
                        f"registration replay of {spec.name!r} refused: "
                        f"{r.get('error')}")
        except (_CallTimeout, _LinkDown, ConnectionError, OSError) as e:
            self._fail_server()
            raise _LinkDown(f"registration replay failed: {e}") from e
        return h

    def _fail_server(self) -> None:
        """The incarnation failed (deadline/crash/link): kill it so the
        next attempt respawns fresh. ``udf.respawn`` marks the moment."""
        fail_point("udf.respawn")
        self.stats["respawns"] += 1
        with self._lock:
            if self._handle is not None:
                self._handle.kill()
                self._handle = None

    def _request(self, h: _ServerHandle, obj: dict,
                 timeout: float) -> dict:
        """One fenced request/reply. Replies whose (gen, rid) don't
        match the CURRENT request are dropped (stale incarnation, or a
        chaos-duplicated frame) — counted, never returned."""
        if h.sock is None:
            raise _LinkDown("no server connection")
        rid = next(self._rid)
        gen = self.generation
        obj = {**obj, "rid": rid, "gen": gen}
        deadline = time.monotonic() + max(0.001, timeout)
        try:
            h.sock.settimeout(max(0.001, timeout))
            write_frame_sync(h.sock, obj, link=CALL_LINK)
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout()
                h.sock.settimeout(remaining)
                resp = read_frame_sync(h.sock)
                if resp is None:
                    raise _LinkDown("UDF server connection lost")
                if resp.get("rid") != rid or resp.get("gen") != gen:
                    with self._lock:
                        self.stats["stale_replies_dropped"] += 1
                    continue
                return resp
        except socket.timeout:
            raise _CallTimeout(
                f"no reply within {timeout}s") from None

    # -- observability ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            h = self._handle
            return {
                "mode": self.config.mode,
                "generation": self.generation,
                "registered": len(UDF_SPECS),
                "server_alive": bool(h is not None and h.alive),
                "inflight": self._inflight,
                **dict(self.stats),
            }


_PLANE = UdfPlane()


def udf_plane() -> UdfPlane:
    return _PLANE


@atexit.register
def _shutdown_at_exit() -> None:   # pragma: no cover - interpreter exit
    try:
        _PLANE.kill_server()
    except Exception:  # noqa: BLE001
        pass
