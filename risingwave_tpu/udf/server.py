"""Standalone UDF server process.

Counterpart of the reference's external UDF server behind the
Arrow-Flight boundary (reference: src/udf/src/lib.rs:28 — user code in
its own process, batches over the wire). Launched by
``ctl udf serve [--port N]`` for an operator-managed server, or
auto-spawned by the client plane (udf/client.py) one per client
process.

Protocol (length-prefixed JSON frames, rpc/wire.py; every frame carries
the client's generation token ``gen`` which replies echo — the client
drops replies whose (gen, rid) don't match its current request, so a
stale or chaos-duplicated reply can never be taken for a fresh one):

    c → s   {"type":"udf_register","rid","gen","spec": spec_to_wire()}
    c → s   {"type":"udf_call","rid","gen","name",
             "batch": udf_batch_to_wire()}
    s → c   {"type":"reply","rid","gen","ok":true,"result": col} |
            {"type":"reply","rid","gen","ok":false,"error",
             "error_kind":"user"|"server"}
    c → s   {"type":"udf_drop","rid","gen","name"}
    c → s   {"type":"shutdown","rid","gen"}

A user function that raises replies ``error_kind: "user"`` — the client
surfaces it as a typed statement error WITHOUT burning respawn+replay
cycles (a deterministic exception would just recur). A function that
hangs or busy-loops simply never replies: the client's per-call
deadline kills this process and respawns it. Deliberately NO in-server
watchdog — the whole point of the plane is that the CLIENT owns the
robustness contract, so even ``os._exit``-hostile user code is covered.

Evaluation is intentionally inline on the event loop: one batch at a
time, in arrival order, so replay after a respawn is deterministic.
Replies ride the ``udf->s`` fault-plane link; the server adopts the
spawning process's chaos schedule via the RWTPU_CHAOS env (like worker
processes) and arms RWTPU_FAILPOINTS — an "exit" action at
``udf.server.eval`` is the deterministic kill-mid-batch the chaos tests
use.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ..common.failpoint import fail_point
from ..common.interchange import udf_col_to_wire, wire_to_udf_batch
from ..rpc.wire import read_frame, write_frame
from .registry import UdfSpec, spec_from_wire
from .runtime import eval_udf_batch

REPLY_LINK = "udf->s"


class UdfHost:
    """One server process: spec table + frame loop."""

    def __init__(self) -> None:
        self.specs: Dict[str, UdfSpec] = {}
        self.stats = {"registered": 0, "calls": 0, "rows": 0,
                      "user_errors": 0}

    def handle_register(self, frame: dict) -> dict:
        spec = spec_from_wire(frame["spec"])
        self.specs[spec.name] = spec
        self.stats["registered"] += 1
        return {"ok": True}

    def handle_drop(self, frame: dict) -> dict:
        self.specs.pop(frame.get("name"), None)
        return {"ok": True}

    def handle_call(self, frame: dict) -> dict:
        spec = self.specs.get(frame.get("name"))
        if spec is None:
            return {"ok": False, "error_kind": "server",
                    "error": f"UDF {frame.get('name')!r} is not "
                             "registered on this server"}
        fail_point("udf.server.eval")
        datas, masks = wire_to_udf_batch(frame["batch"], spec.arg_types)
        try:
            data, mask = eval_udf_batch(spec, datas, masks)
        except Exception as e:  # noqa: BLE001 - user code; shipped back typed
            self.stats["user_errors"] += 1
            return {"ok": False, "error_kind": "user",
                    "error": f"{type(e).__name__}: {e}"}
        self.stats["calls"] += 1
        self.stats["rows"] += int(frame["batch"].get("n") or 0)
        return {"ok": True,
                "result": udf_col_to_wire(data, mask, spec.return_type)}

    async def handle_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break                      # client went away
                t = frame.get("type")
                if t == "udf_call":
                    resp = self.handle_call(frame)
                elif t == "udf_register":
                    try:
                        resp = self.handle_register(frame)
                    except Exception as e:  # noqa: BLE001 - shipped back
                        resp = {"ok": False, "error_kind": "server",
                                "error": f"{type(e).__name__}: {e}"}
                elif t == "udf_drop":
                    resp = self.handle_drop(frame)
                elif t == "stats":
                    resp = {"ok": True, "udf": dict(self.stats)}
                elif t == "shutdown":
                    await self._reply(writer, frame, {"ok": True})
                    break
                else:
                    resp = {"ok": False, "error_kind": "server",
                            "error": f"unknown frame {t!r}"}
                await self._reply(writer, frame, resp)
        finally:
            writer.close()

    @staticmethod
    async def _reply(writer, frame: dict, resp: dict) -> None:
        resp.update({"type": "reply", "rid": frame.get("rid"),
                     "gen": frame.get("gen")})
        await write_frame(writer, resp, link=REPLY_LINK)


async def amain(port: int, trace_path: Optional[str] = None,
                persistent: bool = False) -> None:
    from ..common.failpoint import arm_from_env
    from ..rpc.faults import install_from_env
    install_from_env(trace_path=trace_path)
    arm_from_env()
    host = UdfHost()
    done = asyncio.Event()

    async def conn(reader, writer):
        try:
            await host.handle_conn(reader, writer)
        finally:
            # auto-spawned servers are one-client: losing it ends the
            # process (the plane respawns a fresh one when needed). A
            # `ctl udf serve` operator server is persistent — clients
            # come and go, registrations outlive any one of them.
            if not persistent:
                done.set()

    server = await asyncio.start_server(conn, "127.0.0.1", port)
    actual = server.sockets[0].getsockname()[1]
    print(f"UDF_READY {actual}", flush=True)
    async with server:
        await done.wait()


def main(argv=None) -> None:
    import argparse
    ap = argparse.ArgumentParser(
        description="out-of-process UDF evaluation server "
                    "(docs/robustness.md)")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--trace-path", default=None,
                    help="persist chaos injection traces here "
                         "(rpc/faults.py; inherited schedules only)")
    ap.add_argument("--persistent", action="store_true",
                    help="serve successive clients instead of exiting "
                         "when one disconnects (ctl udf serve)")
    args = ap.parse_args(argv)
    asyncio.run(amain(args.port, args.trace_path,
                      persistent=args.persistent))


if __name__ == "__main__":
    main()
