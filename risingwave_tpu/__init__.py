"""risingwave_tpu — a TPU-native streaming-SQL framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of RisingWave
(reference: /root/reference, a Rust streaming-SQL database): Postgres-style SQL
in, incrementally-maintained materialized views out, exactly-once barrier
checkpoints, epoch-MVCC state persistence, vnode-based data parallelism.

Architecture (not a port — see SURVEY.md §7):
  * columnar ``StreamChunk`` deltas are fixed-capacity device buffers with
    visibility masks (static shapes for XLA),
  * stateful operators (hash agg / hash join / top-n / dynamic filter) keep
    their state device-resident and update it inside jitted step functions,
  * data parallelism is vnode→mesh-shard via ``shard_map``; the hash shuffle
    is an in-step ICI all-to-all instead of the reference's gRPC exchange,
  * the control plane (barrier conductor, catalog, SQL frontend) stays host-side.
"""

import jax

# The framework traffics in int64 row ids / timestamps / keys (the reference's
# arrays are i64-heavy, e.g. src/common/src/array/mod.rs:334-376). JAX defaults
# to 32-bit; enable x64 once at import. Floats stay f32 unless a column's
# logical type says otherwise.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
