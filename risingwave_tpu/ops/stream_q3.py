"""TPC-H q3 streaming-MV core — join + agg + top-n as pure device steps.

The q3 MV (reference workload e2e_test/tpch/q3, streaming form) is

    SELECT l_orderkey, SUM(l_extendedprice * (1 - l_discount)) AS revenue,
           o_orderdate, o_shippriority
    FROM orders JOIN lineitem ON l_orderkey = o_orderkey
    WHERE o_mktsegment = 'BUILDING' AND o_orderdate < :date
      AND l_shipdate > :date
    GROUP BY l_orderkey, o_orderdate, o_shippriority
    ORDER BY revenue DESC, l_orderkey LIMIT 10

This core exploits the same structural facts the interval join does for
q7: the build side (orders) is keyed by its PRIMARY KEY, so there is at
most one build row per join key — probing is a hash lookup + gather,
never a candidate scan — and both inputs are append-only, so the only
retraction surface is the OUTPUT (a group leaving/entering the top-10,
or its revenue changing). Composition per chunk:

1. qualifying ORDER rows (segment + date filter applied AT INSERT — a
   filtered-out order is simply never stored, which IS the join+filter
   semantics) land in an open-addressing table
   (``ops/hash_table.py``) with o_orderdate / o_shippriority lanes;
2. qualifying LINEITEM rows probe that table (read-only ``ht_lookup``);
   matches become a synthetic joined chunk folded into a plain
   ``ops/grouped_agg.AggCore`` — SUM(revenue) plus MAX lanes carrying
   the functionally-dependent o_orderdate/o_shippriority;
3. the barrier flush recomputes the top-10 wholesale from the agg lanes
   (one masked lexicographic sort — the ops/topn.py full-sort lesson:
   recomputing membership beats pointer-chasing on a vector machine)
   and emits exactly the churn an executor TopN would: DELETE departed
   rows, INSERT arrived ones, identical rows suppressed.

Money stays integral: prices ride as cents, discounts as basis points,
``revenue_cents = price * (10000 - disc_bp) / 10000`` in int64 — no
float in the state, so fused/unfused parity is bit-exact.

Event-stream assumptions (sticky flags otherwise): append-only input
(``saw_delete``), an order precedes none of its lineitems being probed
within the SAME apply call is fine (orders of a chunk insert before its
lineitems probe), and a lineitem whose order was filtered out simply
never matches. Orders capacity bounds qualifying orders ever stored
(``orders_overflow``); agg capacity bounds live groups.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_INSERT, Column, StreamChunk,
)
from ..common.types import Field, INT64, Schema
from ..expr.agg import AggCall
from .grouped_agg import AggCore, AggState
from .hash_table import DeviceHashTable, ht_lookup, ht_lookup_or_insert, ht_new

_BIG = jnp.iinfo(jnp.int64).max


@struct.dataclass
class Q3State:
    orders: DeviceHashTable     # keyed by o_orderkey (qualifying only)
    odate: jax.Array            # int64[cap]: o_orderdate lane
    prio: jax.Array             # int64[cap]: o_shippriority lane
    agg: AggState               # revenue SUM + odate/prio MAX lanes
    emitted_key: jax.Array      # int64[K]: top-n rows downstream has seen
    emitted_rev: jax.Array      # int64[K]
    emitted_odate: jax.Array    # int64[K]
    emitted_prio: jax.Array     # int64[K]
    emitted_valid: jax.Array    # bool[K]
    orders_overflow: jax.Array  # bool scalar, sticky
    saw_delete: jax.Array       # bool scalar, sticky


class Q3Core:
    """Static config + pure steps for the q3 streaming MV.

    Input chunks use the unified order/lineitem event schema produced by
    ``connector/tpch.DeviceQ3Generator`` (column indices are
    constructor parameters so the core stays schema-agnostic):
    kind (0=order, 1=lineitem), orderkey, o_orderdate, o_shippriority,
    o_mktsegment, l_extendedprice_cents, l_discount_bp, l_shipdate.

    Output schema: (l_orderkey, revenue_cents, o_orderdate,
    o_shippriority) — the MV rows, emitted as top-``limit`` churn."""

    def __init__(self, cutoff_days: int, mktsegment: int = 0,
                 orders_capacity: int = 1 << 16,
                 agg_capacity: int = 1 << 16, limit: int = 10,
                 kind_col: int = 0, okey_col: int = 1, odate_col: int = 2,
                 prio_col: int = 3, mkt_col: int = 4, price_col: int = 5,
                 disc_col: int = 6, ship_col: int = 7):
        self.cutoff_days = int(cutoff_days)
        self.mktsegment = int(mktsegment)
        self.orders_capacity = int(orders_capacity)
        self.limit = int(limit)
        self.kind_col, self.okey_col = kind_col, okey_col
        self.odate_col, self.prio_col = odate_col, prio_col
        self.mkt_col, self.price_col = mkt_col, price_col
        self.disc_col, self.ship_col = disc_col, ship_col
        # revenue SUM + MAX lanes for the functionally-dependent order
        # attributes (constant per group, so MAX is the identity carry)
        self.agg = AggCore(
            key_types=(INT64,), group_keys=(0,),
            agg_calls=(AggCall("sum", 1, INT64), AggCall("max", 2, INT64),
                       AggCall("max", 3, INT64)),
            table_capacity=agg_capacity, out_capacity=2 * limit)
        self.out_schema = Schema((
            Field("l_orderkey", INT64), Field("revenue_cents", INT64),
            Field("o_orderdate", INT64), Field("o_shippriority", INT64),
        ))

    # -- state ----------------------------------------------------------------

    def init_state(self) -> Q3State:
        cap, K = self.orders_capacity, self.limit
        return Q3State(
            orders=ht_new((INT64,), cap),
            odate=jnp.zeros(cap, jnp.int64),
            prio=jnp.zeros(cap, jnp.int64),
            agg=self.agg.init_state(),
            emitted_key=jnp.zeros(K, jnp.int64),
            emitted_rev=jnp.zeros(K, jnp.int64),
            emitted_odate=jnp.zeros(K, jnp.int64),
            emitted_prio=jnp.zeros(K, jnp.int64),
            emitted_valid=jnp.zeros(K, jnp.bool_),
            orders_overflow=jnp.zeros((), jnp.bool_),
            saw_delete=jnp.zeros((), jnp.bool_),
        )

    # -- chunk step ------------------------------------------------------------

    def apply_chunk(self, state: Q3State, chunk: StreamChunk) -> Q3State:
        cap = self.orders_capacity
        cols = chunk.columns
        is_ins = (chunk.ops == OP_INSERT) | (chunk.ops == OP_UPDATE_INSERT)
        saw_delete = state.saw_delete | jnp.any(chunk.vis & ~is_ins)
        valid = chunk.vis & is_ins
        kind = cols[self.kind_col].data.astype(jnp.int64)
        okey = Column(cols[self.okey_col].data.astype(jnp.int64),
                      cols[self.okey_col].mask)
        odate = cols[self.odate_col].data.astype(jnp.int64)
        prio = cols[self.prio_col].data.astype(jnp.int64)
        mkt = cols[self.mkt_col].data.astype(jnp.int64)
        price = cols[self.price_col].data.astype(jnp.int64)
        disc = cols[self.disc_col].data.astype(jnp.int64)
        ship = cols[self.ship_col].data.astype(jnp.int64)

        # ---- orders: filter at insert (mktsegment + date cutoff)
        qual = (valid & (kind == 0) & okey.mask
                & (odate < self.cutoff_days) & (mkt == self.mktsegment))
        orders, slots, _, ovf = ht_lookup_or_insert(state.orders, [okey],
                                                    qual)
        tgt = jnp.where(qual, slots, cap)
        odate_lane = state.odate.at[tgt].set(odate, mode="drop")
        prio_lane = state.prio.at[tgt].set(prio, mode="drop")

        # ---- lineitems: shipdate filter, then probe the (just-updated)
        # orders table; a miss == the order was filtered out
        is_li = valid & (kind == 1) & okey.mask & (ship > self.cutoff_days)
        pslots, found = ht_lookup(orders, [okey], is_li)
        match = is_li & found
        safe = jnp.clip(pslots, 0, cap - 1)
        revenue = price * (10000 - disc) // 10000
        joined = StreamChunk(
            jnp.zeros(chunk.capacity, jnp.int8), match,
            (Column(okey.data, match), Column(revenue, match),
             Column(odate_lane[safe], match), Column(prio_lane[safe], match)))
        agg = self.agg.apply_chunk(state.agg, joined)

        return state.replace(
            orders=orders, odate=odate_lane, prio=prio_lane, agg=agg,
            orders_overflow=state.orders_overflow | ovf,
            saw_delete=saw_delete)

    # -- barrier flush ---------------------------------------------------------

    def flush_candidates(self, state: Q3State):
        """Full candidate arrays for the top-``limit`` recompute:
        ``(okey, rev, odate, prio, live)`` over every agg slot. The
        sharded epoch takes each shard's local top-``limit`` of these
        (``topk_perm``), all-gathers them, and feeds the union through
        the SAME ``flush_from_candidates`` the solo flush uses — group
        keys are shard-disjoint, so the global top-``limit`` is always
        inside the gathered union and the result is bit-identical."""
        lanes = state.agg.lanes
        live = lanes[0] > 0
        ofs = self.agg.call_lane_ofs
        rev, odate, prio = lanes[ofs[0]], lanes[ofs[1]], lanes[ofs[2]]
        okey = state.agg.table.key_data[0].astype(jnp.int64)
        return okey, rev, odate, prio, live

    @staticmethod
    def topk_perm(okey, rev, valid, limit: int):
        """Indices of the top-``limit`` candidates by (revenue DESC,
        orderkey ASC) — two stable argsorts; orderkeys are distinct, so
        the order is total and independent of candidate array order."""
        o1 = jnp.argsort(jnp.where(valid, okey, _BIG), stable=True)
        return o1[jnp.argsort(jnp.where(valid, -rev, _BIG)[o1],
                              stable=True)][:limit]

    def flush(self, state: Q3State):
        """Recompute the top-``limit`` by (revenue DESC, orderkey ASC)
        and emit churn vs the previously emitted rows. Returns
        (state, out_chunk [2*limit rows: deletes then inserts], packed
        [n_out, orders_overflow, agg_overflow, saw_delete])."""
        return self.flush_from_candidates(state, *self.flush_candidates(state))

    def flush_from_candidates(self, state: Q3State, okey, rev, odate,
                              prio, valid):
        """The top-``limit`` churn over an arbitrary candidate set (the
        solo flush passes every agg slot; the sharded flush passes the
        all-gathered union of per-shard top-``limit`` rows)."""
        K = self.limit
        perm = self.topk_perm(okey, rev, valid, K)
        new_valid = valid[perm]
        new_key, new_rev = okey[perm], rev[perm]
        new_odate, new_prio = odate[perm], prio[perm]

        same = (state.emitted_valid[:, None] & new_valid[None, :]
                & (state.emitted_key[:, None] == new_key[None, :])
                & (state.emitted_rev[:, None] == new_rev[None, :])
                & (state.emitted_odate[:, None] == new_odate[None, :])
                & (state.emitted_prio[:, None] == new_prio[None, :]))
        del_m = state.emitted_valid & ~jnp.any(same, axis=1)
        ins_m = new_valid & ~jnp.any(same, axis=0)

        ops = jnp.concatenate([jnp.full(K, OP_DELETE, jnp.int8),
                               jnp.full(K, OP_INSERT, jnp.int8)])
        vis = jnp.concatenate([del_m, ins_m])

        def col(old, new):
            return Column(jnp.concatenate([old, new]), vis)

        out = StreamChunk(ops, vis, (
            col(state.emitted_key, new_key),
            col(state.emitted_rev, new_rev),
            col(state.emitted_odate, new_odate),
            col(state.emitted_prio, new_prio)))
        packed = jnp.stack([
            jnp.sum(del_m) + jnp.sum(ins_m),
            state.orders_overflow.astype(jnp.int64),
            state.agg.overflow.astype(jnp.int64),
            state.saw_delete.astype(jnp.int64),
        ])
        state = state.replace(
            emitted_key=new_key, emitted_rev=new_rev,
            emitted_odate=new_odate, emitted_prio=new_prio,
            emitted_valid=new_valid)
        return state, out, packed

    # -- checkpoint / recovery -------------------------------------------------

    def export_host(self, state: Q3State) -> dict:
        import numpy as np
        host = jax.device_get(state)
        out = {f: np.asarray(getattr(host, f)) for f in (
            "odate", "prio", "emitted_key", "emitted_rev", "emitted_odate",
            "emitted_prio", "emitted_valid", "orders_overflow",
            "saw_delete")}
        out["orders_key_data"] = [np.asarray(a)
                                  for a in host.orders.key_data]
        out["orders_key_mask"] = [np.asarray(a)
                                  for a in host.orders.key_mask]
        out["orders_occupied"] = np.asarray(host.orders.occupied)
        out["agg"] = jax.tree_util.tree_map(np.asarray, host.agg)
        return out

    def import_host(self, payload: dict) -> Q3State:
        agg = jax.tree_util.tree_map(jnp.asarray, payload["agg"])
        return Q3State(
            orders=DeviceHashTable(
                key_data=tuple(jnp.asarray(a)
                               for a in payload["orders_key_data"]),
                key_mask=tuple(jnp.asarray(a)
                               for a in payload["orders_key_mask"]),
                occupied=jnp.asarray(payload["orders_occupied"])),
            agg=agg,
            **{f: jnp.asarray(payload[f]) for f in (
                "odate", "prio", "emitted_key", "emitted_rev",
                "emitted_odate", "emitted_prio", "emitted_valid",
                "orders_overflow", "saw_delete")},
        )
