"""Device-resident row-set state with diff-based delta emission.

Shared functional core for executors whose state is "a set of rows keyed by
pk, from which a *derived subset* is emitted downstream" — TopN (subset = the
rank window; reference: src/stream/src/executor/top_n/top_n_cache.rs:43) and
DynamicFilter (subset = rows passing the dynamic bound; reference:
src/stream/src/executor/dynamic_filter.rs:46-64). Instead of the reference's
per-row cache walks, the whole chunk upserts in one scatter round and the
emitted-subset diff is computed over all slots at flush time:

  * rows live in slot-indexed column arrays behind a pk hash table
    (ops/hash_table.py); ``live`` marks deletions (slots are reused on pk
    re-insertion, never compacted — same policy as the agg table);
  * within-chunk ordering (Delete then Insert of one pk in the same chunk)
    resolves by last-writer-wins via a scatter-max of row indices — scatter
    application order is undefined in XLA, so the winner is picked explicitly;
  * at flush the executor supplies ``in_set`` (bool per slot, any derived
    membership rule); the core diffs it against what was last emitted
    (membership flag + value copy) and gathers Insert / Delete / U-,U+ delta
    chunks exactly like the agg flush (ops/grouped_agg.py).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, Column,
    StreamChunk,
)
from .hash_table import DeviceHashTable, ht_lookup_or_insert, ht_new


@struct.dataclass
class RowSetState:
    table: DeviceHashTable            # keyed by pk columns
    live: jax.Array                   # bool[cap] — row currently exists
    cols: tuple[Column, ...]          # stored rows, [cap] per column
    emitted: jax.Array                # bool[cap] — in emitted subset at last flush
    emitted_cols: tuple[Column, ...]  # values as of last emission
    ckpt_dirty: jax.Array             # bool[cap] — touched since last checkpoint
    overflow: jax.Array               # bool scalar, sticky
    saw_delete: jax.Array             # bool scalar, sticky (append-only check)


def rs_new(pk_types: Sequence, col_types: Sequence, capacity: int) -> RowSetState:
    cols = tuple(
        Column(jnp.zeros(capacity, t.dtype), jnp.zeros(capacity, jnp.bool_))
        for t in col_types
    )
    return RowSetState(
        table=ht_new(pk_types, capacity),
        live=jnp.zeros(capacity, jnp.bool_),
        cols=cols,
        emitted=jnp.zeros(capacity, jnp.bool_),
        emitted_cols=cols,
        ckpt_dirty=jnp.zeros(capacity, jnp.bool_),
        overflow=jnp.zeros((), jnp.bool_),
        saw_delete=jnp.zeros((), jnp.bool_),
    )


def rs_apply_chunk(
    state: RowSetState, chunk: StreamChunk, pk_indices: Sequence[int]
):
    """Upsert/delete a chunk of rows. Returns ``(state, slots, applied)``:
    ``slots`` int32[N] per input row (capacity sentinel when invisible/
    overflowed), ``applied`` bool[N] — the winning writer rows whose values
    landed in the table (callers extend state keyed by these)."""
    cap = state.table.capacity
    pk_cols = [chunk.columns[i] for i in pk_indices]
    table, slots, _is_new, ovf = ht_lookup_or_insert(state.table, pk_cols, chunk.vis)
    n = chunk.capacity
    row_ids = jnp.arange(n, dtype=jnp.int32)
    # last-writer-wins: the highest row index targeting each slot applies
    last = jnp.full(cap, -1, jnp.int32).at[slots].max(
        jnp.where(chunk.vis, row_ids, -1), mode="drop")
    in_range = slots < cap
    applied = chunk.vis & in_range & (
        last[jnp.clip(slots, 0, cap - 1)] == row_ids)
    idx = jnp.where(applied, slots, cap)
    is_insert = (chunk.ops == OP_INSERT) | (chunk.ops == OP_UPDATE_INSERT)
    live = state.live.at[idx].set(is_insert, mode="drop")
    cols = tuple(
        Column(
            c.data.at[idx].set(src.data, mode="drop"),
            c.mask.at[idx].set(src.mask, mode="drop"),
        )
        for c, src in zip(state.cols, chunk.columns)
    )
    is_delete = (chunk.ops == OP_DELETE) | (chunk.ops == OP_UPDATE_DELETE)
    state = state.replace(
        table=table, live=live, cols=cols,
        ckpt_dirty=state.ckpt_dirty.at[idx].set(True, mode="drop"),
        overflow=state.overflow | ovf,
        saw_delete=state.saw_delete | jnp.any(chunk.vis & is_delete),
    )
    return state, slots, applied


def rs_changed(state: RowSetState, in_set: jax.Array) -> jax.Array:
    """Slots whose downstream-visible row changes: membership flips, or stays
    in-set with different values."""
    val_changed = jnp.zeros_like(state.live)
    for cur, old in zip(state.cols, state.emitted_cols):
        col_diff = (cur.mask != old.mask) | (
            cur.mask & old.mask & (cur.data != old.data))
        val_changed = val_changed | col_diff
    return (state.emitted != in_set) | (state.emitted & in_set & val_changed)


def rs_gather_delta(
    state: RowSetState, in_set: jax.Array, changed: jax.Array,
    lo: jax.Array, out_capacity: int,
) -> StreamChunk:
    """One delta chunk for changed slots with rank in [lo, lo+G), G =
    out_capacity//2 (2 slots per slot: old row / new row, vis-masked)."""
    G = out_capacity // 2
    C = out_capacity
    rank = jnp.cumsum(changed) - changed.astype(jnp.int64)
    in_win = changed & (rank >= lo) & (rank < lo + G)
    pos = (rank - lo).astype(jnp.int32)
    idx0 = jnp.where(in_win, 2 * pos, C)      # old (emitted) row
    idx1 = jnp.where(in_win, 2 * pos + 1, C)  # new (current) row

    ops = jnp.zeros(C, jnp.int8)
    ops = ops.at[idx0].set(
        jnp.where(in_set, OP_UPDATE_DELETE, OP_DELETE).astype(jnp.int8),
        mode="drop")
    ops = ops.at[idx1].set(
        jnp.where(state.emitted, OP_UPDATE_INSERT, OP_INSERT).astype(jnp.int8),
        mode="drop")
    vis = jnp.zeros(C, jnp.bool_)
    vis = vis.at[idx0].set(state.emitted, mode="drop")
    vis = vis.at[idx1].set(in_set, mode="drop")

    cols = []
    for cur, old in zip(state.cols, state.emitted_cols):
        data = jnp.zeros(C, cur.data.dtype).at[idx0].set(old.data, mode="drop")
        data = data.at[idx1].set(cur.data, mode="drop")
        mask = jnp.zeros(C, jnp.bool_).at[idx0].set(old.mask, mode="drop")
        mask = mask.at[idx1].set(cur.mask, mode="drop")
        cols.append(Column(data, mask))
    return StreamChunk(ops, vis, tuple(cols))


def rs_finish_flush(state: RowSetState, in_set: jax.Array) -> RowSetState:
    emitted_cols = tuple(
        Column(
            jnp.where(in_set, cur.data, old.data),
            jnp.where(in_set, cur.mask, old.mask),
        )
        for cur, old in zip(state.cols, state.emitted_cols)
    )
    return state.replace(emitted=in_set, emitted_cols=emitted_cols)


def rs_checkpoint(rows: RowSetState, state_table,
                       epoch: int) -> RowSetState:
    """Incremental row-set checkpoint: flush only slots touched since the
    last checkpoint (upsert live rows, delete tombstoned ones), mirroring
    the reference's dirty-delta StateTable.commit (state_table.rs:783).
    When the native row codec is available (native/rowcodec.cpp), the
    whole dirty batch key/value-encodes in one C++ call instead of a
    per-row Python loop — the reference's equivalent tier is native Rust.
    Returns the state with ckpt_dirty cleared."""
    import numpy as np
    dirty = np.asarray(rows.ckpt_dirty)
    idx = np.nonzero(dirty)[0]
    if len(idx):
        from ..native import codec as _native_codec
        codec = _native_codec()
        if codec is not None:
            datas = [np.asarray(c.data) for c in rows.cols]
            masks = [np.asarray(c.mask) for c in rows.cols]
            live = np.asarray(rows.live)
            live_idx = idx[live[idx]]
            dead_idx = idx[~live[idx]]
            types = state_table.schema.types
            pk = state_table.pk_indices
            pk_datas = [datas[i] for i in pk]
            pk_masks = [masks[i] for i in pk]
            pk_types = [types[i] for i in pk]
            keys_live = codec.encode_keys(pk_datas, pk_masks, pk_types,
                                          live_idx)
            vals_live = codec.encode_value_rows(datas, masks, types,
                                               live_idx)
            keys_dead = codec.encode_keys(pk_datas, pk_masks, pk_types,
                                          dead_idx)
            state_table.stage_encoded(dict(zip(keys_live, vals_live)),
                                      keys_dead)
        else:
            live = np.asarray(rows.live)[idx]
            datas = [np.asarray(c.data)[idx] for c in rows.cols]
            masks = [np.asarray(c.mask)[idx] for c in rows.cols]
            for r in range(len(idx)):
                row = tuple(
                    datas[c][r].item() if masks[c][r] else None
                    for c in range(len(datas)))
                if live[r]:
                    state_table.insert(row)
                else:
                    state_table.delete(row)
        state_table.commit(epoch)
    return rows.replace(ckpt_dirty=jnp.zeros_like(rows.ckpt_dirty))
