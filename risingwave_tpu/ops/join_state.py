"""Device-resident streaming hash-join state + pure join step.

TPU-native counterpart of the reference's HashJoinExecutor state machinery
(reference: src/stream/src/executor/hash_join.rs:227-270, probe/build
``eq_join_oneside`` :972; JoinHashMap = row + degree StateTables,
src/stream/src/executor/managed_state/join/mod.rs:228-258). Deliberately NOT
an LRU row-cache probed row-by-row: each side keeps ALL its rows
device-resident in a bucketed arena —

  * a DeviceHashTable maps join key -> bucket (ops/hash_table.py),
  * each bucket holds up to W rows (static bucket width) in struct-of-arrays
    ``[capacity, W]`` buffers, with per-row occupancy, tombstones, and a
    **degree** = number of condition-passing matches on the opposite side
    (the reference's degree table) driving outer/semi/anti emission with no
    re-probing.

One input chunk is joined in ONE jitted step: the opposite side is probed for
all rows at once (vectorized gathers), the serial-order effects the reference
gets from row-at-a-time processing (degree transitions when several same-key
rows arrive in one chunk) are recovered with rank/total **matmuls** over the
key-equality matrix — MXU work instead of scalar loops — and outputs land in
a fixed-capacity ``[N, 2W+1]`` lane grid (lanes 2w/2w+1 = match w's primary /
update-pair row; lane 2W = the null-padded or self row) that flattens into a
single visibility-masked chunk for downstream compaction
(common/chunk.py:gather_units_window).

A chunk is processed as two vectorized sub-passes — deletes first, then
inserts — preserving the one ordering streaming SQL relies on inside a chunk
(U- before U+ of the same key). Insert-then-delete of the same row inside one
chunk would be mis-ordered; that pattern trips the ``inconsistent`` flag
(checked on barriers) instead of silently corrupting state.

Join-key NULLs never match (SQL semantics), unlike GROUP BY: rows with a null
key are stored (for deletes / outer emission) but masked out of probing.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, Column,
    StreamChunk,
)
from ..common.types import Schema
from .hash_table import DeviceHashTable, ht_lookup, ht_lookup_or_insert, ht_new


class JoinType(enum.Enum):
    """reference: JoinTypePrimitive consts, src/stream/src/executor/hash_join.rs:83-100."""

    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"
    RIGHT_SEMI = "right_semi"
    RIGHT_ANTI = "right_anti"

    @property
    def preserves_left(self) -> bool:
        return self in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER)

    @property
    def preserves_right(self) -> bool:
        return self in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER)

    @property
    def semi_anti_side(self) -> Optional[str]:
        if self in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            return "left"
        if self in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
            return "right"
        return None

    @property
    def is_anti(self) -> bool:
        return self in (JoinType.LEFT_ANTI, JoinType.RIGHT_ANTI)


@struct.dataclass
class JoinSideState:
    ht: DeviceHashTable                 # join key -> bucket
    row_data: tuple[jax.Array, ...]     # per column: dtype[cap, W]
    row_mask: tuple[jax.Array, ...]     # per column: bool[cap, W]
    occupied: jax.Array                 # bool[cap, W]
    tomb: jax.Array                     # bool[cap, W] — deleted since last ckpt
    degree: jax.Array                   # int32[cap, W] — opposite-side matches
    ckpt_dirty: jax.Array               # bool[cap, W] — changed since last ckpt
    lru: jax.Array                      # int32[cap] — key's last-touch step
    ht_overflow: jax.Array              # bool scalar, sticky: key table full
    lane_overflow: jax.Array            # bool scalar, sticky: bucket width full
    inconsistent: jax.Array             # bool scalar, sticky


@struct.dataclass
class JoinState:
    left: JoinSideState
    right: JoinSideState


class JoinCore:
    """Static config + pure (state, chunk) -> (state, out) step for one
    streaming hash join. Shardable: runs unchanged under shard_map with
    vnode-partitioned inputs (both sides shuffled by join key)."""

    def __init__(
        self,
        left_schema: Schema,
        right_schema: Schema,
        left_keys: Sequence[int],
        right_keys: Sequence[int],
        join_type: JoinType,
        condition=None,
        key_capacity: int = 1 << 13,
        bucket_width: int = 16,
    ):
        self.left_schema = left_schema
        self.right_schema = right_schema
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.join_type = join_type
        self.condition = condition
        self.capacity = key_capacity
        self.W = bucket_width
        lkt = tuple(left_schema[i].type for i in self.left_keys)
        rkt = tuple(right_schema[i].type for i in self.right_keys)
        assert tuple(t.dtype for t in lkt) == tuple(t.dtype for t in rkt), (
            "equi-join key physical types must match (planner inserts casts)")
        self.key_types = lkt
        sa = join_type.semi_anti_side
        if sa == "left":
            self.out_schema = left_schema
        elif sa == "right":
            self.out_schema = right_schema
        else:
            self.out_schema = left_schema.concat(right_schema)

    # -- state ----------------------------------------------------------------

    def _new_side(self, schema: Schema, key_idx: Sequence[int]) -> JoinSideState:
        cap, W = self.capacity, self.W
        key_types = tuple(schema[i].type for i in key_idx)
        return JoinSideState(
            ht=ht_new(key_types, cap),
            row_data=tuple(jnp.zeros((cap, W), f.type.dtype) for f in schema),
            row_mask=tuple(jnp.zeros((cap, W), jnp.bool_) for _ in schema),
            occupied=jnp.zeros((cap, W), jnp.bool_),
            tomb=jnp.zeros((cap, W), jnp.bool_),
            degree=jnp.zeros((cap, W), jnp.int32),
            ckpt_dirty=jnp.zeros((cap, W), jnp.bool_),
            lru=jnp.zeros(cap, jnp.int32),
            ht_overflow=jnp.zeros((), jnp.bool_),
            lane_overflow=jnp.zeros((), jnp.bool_),
            inconsistent=jnp.zeros((), jnp.bool_),
        )

    def init_state(self) -> JoinState:
        return JoinState(
            left=self._new_side(self.left_schema, self.left_keys),
            right=self._new_side(self.right_schema, self.right_keys),
        )

    # -- the step --------------------------------------------------------------

    def apply_chunk(self, state: JoinState, chunk: StreamChunk, *, side: str,
                    step=None):
        """Join one chunk arriving on ``side``; returns (state, big_chunk).

        ``big_chunk`` has capacity 2*N*(2W+1) and is mostly invisible; compact
        it with gather_units_window before sending downstream.

        ``step``: optional int32 LRU stamp — when set, both the own-side
        key slot and every probed opposite-side slot are touched, so the
        two sides' stamps for one key value stay in sync (the invariant
        cold-tier eviction relies on to evict a key from BOTH arenas)."""
        is_del = chunk.vis & (
            (chunk.ops == OP_DELETE) | (chunk.ops == OP_UPDATE_DELETE))
        is_ins = chunk.vis & (
            (chunk.ops == OP_INSERT) | (chunk.ops == OP_UPDATE_INSERT))

        def run_del(st):
            return self._pass(st, chunk, is_del, False, side, step)

        def run_ins(st):
            return self._pass(st, chunk, is_ins, True, side, step)

        def skip(st):
            return st, self._empty_out(chunk.capacity)

        state, out_d = jax.lax.cond(jnp.any(is_del), run_del, skip, state)
        state, out_i = jax.lax.cond(jnp.any(is_ins), run_ins, skip, state)
        ops = jnp.concatenate([out_d[0].reshape(-1), out_i[0].reshape(-1)])
        vis = jnp.concatenate([out_d[1].reshape(-1), out_i[1].reshape(-1)])
        cols = tuple(
            Column(jnp.concatenate([d0.reshape(-1), d1.reshape(-1)]),
                   jnp.concatenate([m0.reshape(-1), m1.reshape(-1)]))
            for (d0, m0), (d1, m1) in zip(out_d[2], out_i[2])
        )
        return state, StreamChunk(ops, vis, cols)

    # -- internals -------------------------------------------------------------

    def _empty_out(self, N: int):
        L = 2 * self.W + 1
        return (
            jnp.zeros((N, L), jnp.int8),
            jnp.zeros((N, L), jnp.bool_),
            tuple(
                (jnp.zeros((N, L), f.type.dtype), jnp.zeros((N, L), jnp.bool_))
                for f in self.out_schema
            ),
        )

    def _eval_condition(self, chunk, b_datas, b_masks, side: str):
        """Evaluate the non-equi condition on all candidate pairs -> bool[N, W]."""
        N, W = chunk.capacity, self.W
        a_cols = [
            Column(jnp.repeat(c.data, W), jnp.repeat(c.mask, W))
            for c in chunk.columns
        ]
        b_cols = [
            Column(d.reshape(-1), m.reshape(-1))
            for d, m in zip(b_datas, b_masks)
        ]
        pair = a_cols + b_cols if side == "left" else b_cols + a_cols
        pseudo = StreamChunk(
            jnp.zeros(N * W, jnp.int8), jnp.ones(N * W, jnp.bool_), tuple(pair)
        )
        res = self.condition.eval(pseudo)
        return (res.data & res.mask).reshape(N, W)

    def _pass(self, state: JoinState, chunk: StreamChunk, sel: jax.Array,
              is_insert: bool, side: str, step=None):
        cap, W = self.capacity, self.W
        N = chunk.capacity
        A = state.left if side == "left" else state.right
        B = state.right if side == "left" else state.left
        a_key_idx = self.left_keys if side == "left" else self.right_keys
        a_key_cols = [chunk.columns[i] for i in a_key_idx]
        idx = jnp.arange(N)

        has_null_key = jnp.zeros(N, jnp.bool_)
        for c in a_key_cols:
            has_null_key = has_null_key | ~c.mask
        match_ok = sel & ~has_null_key

        # ---- probe the opposite side (all rows at once)
        b_slot, b_found = ht_lookup(B.ht, a_key_cols, match_ok)
        bs = jnp.where(b_found, b_slot, 0)
        occ_b = B.occupied[bs] & b_found[:, None]                      # [N, W]
        b_datas = [rd[bs] for rd in B.row_data]                        # [N, W]
        b_masks = [rm[bs] & occ_b for rm in B.row_mask]
        matches = occ_b
        if self.condition is not None:
            matches = matches & self._eval_condition(chunk, b_datas, b_masks, side)
        c_cnt = jnp.sum(matches, axis=1).astype(jnp.int32)             # [N]

        # ---- rank/total of same-key rows within this pass:
        # r[i,w] = |{j<i: key_j == key_i, (j,w) matches}|, t = same over all j.
        # On TPU the fused Pallas kernel generates the [N,N] equality
        # tiles in VMEM and feeds the MXU directly (ops/pallas_rank.py);
        # elsewhere the jnp matmul formulation runs. RWTPU_PALLAS=0/1
        # overrides the choice.
        from .pallas_rank import rank_totals
        ident = jnp.where(b_found, b_slot, -1)
        r, t = rank_totals(ident, matches)
        d0 = B.degree[bs]                                              # [N, W]

        # ---- opposite-side degree maintenance (reference join/mod.rs degrees)
        lane_w = jnp.arange(W, dtype=jnp.int32)[None, :]
        g = jnp.where(matches, bs[:, None] * W + lane_w, cap * W).reshape(-1)
        delta = jnp.where(matches, 1 if is_insert else -1, 0).astype(jnp.int32)
        # degrees are rebuilt on recovery, not persisted — no ckpt_dirty here
        B = B.replace(
            degree=B.degree.reshape(-1).at[g].add(delta.reshape(-1), mode="drop")
                    .reshape(cap, W),
        )
        if step is not None:
            B = B.replace(lru=B.lru.at[jnp.where(b_found, b_slot, cap)]
                          .max(step, mode="drop"))

        # ---- own-side arena update
        if is_insert:
            a_ht, a_slot, _, ht_ovf = ht_lookup_or_insert(A.ht, a_key_cols, sel)
            a_ok = sel & (a_slot < cap)
            as_ = jnp.where(a_ok, a_slot, 0)
            aident = jnp.where(a_ok, a_slot, -1)
            alower = ((aident[:, None] == aident[None, :])
                      & (aident >= 0)[:, None] & (idx[None, :] < idx[:, None]))
            a_rank = jnp.sum(alower, axis=1).astype(jnp.int32)
            free = ~(A.occupied | A.tomb)[as_]                         # [N, W]
            cs = jnp.cumsum(free, axis=1)
            hit = (cs == (a_rank + 1)[:, None]) & free
            lane = jnp.argmax(hit, axis=1).astype(jnp.int32)
            lane_ok = jnp.any(hit, axis=1) & a_ok
            f = jnp.where(lane_ok, as_ * W + lane, cap * W)
            A = A.replace(
                ht=a_ht,
                occupied=A.occupied.reshape(-1).at[f].set(True, mode="drop")
                          .reshape(cap, W),
                row_data=tuple(
                    rd.reshape(-1).at[f].set(c.data, mode="drop").reshape(cap, W)
                    for rd, c in zip(A.row_data, chunk.columns)),
                row_mask=tuple(
                    rm.reshape(-1).at[f].set(c.mask, mode="drop").reshape(cap, W)
                    for rm, c in zip(A.row_mask, chunk.columns)),
                degree=A.degree.reshape(-1).at[f].set(c_cnt, mode="drop")
                        .reshape(cap, W),
                ckpt_dirty=A.ckpt_dirty.reshape(-1).at[f].set(True, mode="drop")
                            .reshape(cap, W),
                ht_overflow=A.ht_overflow | ht_ovf
                            | jnp.any(sel & (a_slot >= cap)),
                lane_overflow=A.lane_overflow | jnp.any(a_ok & ~lane_ok),
            )
            if step is not None:
                A = A.replace(lru=A.lru.at[jnp.where(a_ok, a_slot, cap)]
                              .max(step, mode="drop"))
        else:
            a_slot, a_found = ht_lookup(A.ht, a_key_cols, sel)
            as_ = jnp.where(a_found, a_slot, 0)
            delmatch = A.occupied[as_] & a_found[:, None]
            for rd, rm, c in zip(A.row_data, A.row_mask, chunk.columns):
                srd, srm = rd[as_], rm[as_]
                delmatch = delmatch & (
                    (srm & c.mask[:, None] & (srd == c.data[:, None]))
                    | (~srm & ~c.mask[:, None]))
            # rank among value-identical delete rows -> distinct lanes
            roweq = sel[:, None] & sel[None, :]
            for c in chunk.columns:
                roweq = roweq & (
                    (c.mask[:, None] & c.mask[None, :]
                     & (c.data[:, None] == c.data[None, :]))
                    | (~c.mask[:, None] & ~c.mask[None, :]))
            drank = jnp.sum(roweq & (idx[None, :] < idx[:, None]), axis=1)
            cs = jnp.cumsum(delmatch, axis=1)
            hit = (cs == (drank + 1)[:, None]) & delmatch
            lane = jnp.argmax(hit, axis=1).astype(jnp.int32)
            lane_ok = jnp.any(hit, axis=1)
            f = jnp.where(lane_ok, as_ * W + lane, cap * W)
            # values stay in row_data for the durable-tier delete at checkpoint
            A = A.replace(
                occupied=A.occupied.reshape(-1).at[f].set(False, mode="drop")
                          .reshape(cap, W),
                tomb=A.tomb.reshape(-1).at[f].set(True, mode="drop")
                      .reshape(cap, W),
                ckpt_dirty=A.ckpt_dirty.reshape(-1).at[f].set(True, mode="drop")
                            .reshape(cap, W),
                inconsistent=A.inconsistent | jnp.any(sel & ~lane_ok),
            )
            if step is not None:
                A = A.replace(lru=A.lru.at[jnp.where(a_found, a_slot, cap)]
                              .max(step, mode="drop"))

        state = (state.replace(left=A, right=B) if side == "left"
                 else state.replace(left=B, right=A))

        out = self._emit(chunk, sel, is_insert, side, matches, c_cnt, r, t, d0,
                         b_datas, b_masks)
        return state, out

    def _emit(self, chunk, sel, is_insert: bool, side: str, matches, c_cnt,
              r, t, d0, b_datas, b_masks):
        """Build the [N, 2W+1] emission grid for one pass."""
        N, W = chunk.capacity, self.W
        jt = self.join_type
        sa = jt.semi_anti_side
        op_plain = OP_INSERT if is_insert else OP_DELETE

        a_outer = (jt.preserves_left if side == "left" else jt.preserves_right)
        b_outer = (jt.preserves_right if side == "left" else jt.preserves_left)

        p0 = jnp.zeros((N, W), jnp.bool_)   # lane 2w visible
        p1 = jnp.zeros((N, W), jnp.bool_)   # lane 2w+1 visible
        op0 = jnp.full((N, W), op_plain, jnp.int8)
        op1 = jnp.full((N, W), OP_UPDATE_INSERT, jnp.int8)
        pself = jnp.zeros(N, jnp.bool_)     # lane 2W visible
        # per-lane "A columns are non-null" (B cols are non-null in any pair lane)
        a0 = jnp.ones((N, W), jnp.bool_)
        a1 = jnp.ones((N, W), jnp.bool_)

        if is_insert:
            trans = matches & (d0 + r == 0)
        else:
            trans = matches & (d0 - t == 0) & (r == t - 1)

        if sa is None:
            if b_outer:
                # transition lanes emit an adjacent update pair replacing /
                # restoring the opposite side's null-padded row
                p0 = matches
                p1 = trans
                op0 = jnp.where(trans, OP_UPDATE_DELETE, op_plain).astype(jnp.int8)
                if is_insert:
                    a0 = ~trans   # U- row is (B row, A-null)
                else:
                    a1 = jnp.zeros((N, W), jnp.bool_)  # U+ row is (B row, A-null)
            else:
                p0 = matches
            if a_outer:
                pself = sel & (c_cnt == 0)
        elif sa == side:
            # input on the preserved side: emit/retract own row only
            want = (c_cnt == 0) if jt.is_anti else (c_cnt > 0)
            pself = sel & want
        else:
            # input on the non-preserved side: emit/retract opposite rows on
            # degree transitions
            p0 = trans
            if jt.is_anti:
                op0 = jnp.full((N, W), OP_DELETE if is_insert else OP_INSERT,
                               jnp.int8)
            else:
                op0 = jnp.full((N, W), OP_INSERT if is_insert else OP_DELETE,
                               jnp.int8)

        # ---- assemble ops/vis  [N, 2W+1]
        L = 2 * W + 1
        ops = jnp.zeros((N, L), jnp.int8)
        vis = jnp.zeros((N, L), jnp.bool_)
        ops = ops.at[:, 0:2 * W:2].set(op0).at[:, 1:2 * W:2].set(op1)
        ops = ops.at[:, 2 * W].set(jnp.full(N, op_plain, jnp.int8))
        vis = vis.at[:, 0:2 * W:2].set(p0).at[:, 1:2 * W:2].set(p1)
        vis = vis.at[:, 2 * W].set(pself)

        # ---- assemble output columns
        def lanes(w0_d, w0_m, w1_d, w1_m, self_d, self_m, dtype):
            d = jnp.zeros((N, L), dtype)
            m = jnp.zeros((N, L), jnp.bool_)
            d = d.at[:, 0:2 * W:2].set(w0_d).at[:, 1:2 * W:2].set(w1_d)
            d = d.at[:, 2 * W].set(self_d)
            m = m.at[:, 0:2 * W:2].set(w0_m).at[:, 1:2 * W:2].set(w1_m)
            m = m.at[:, 2 * W].set(self_m)
            return d, m

        a_col_list = []   # input side's columns in output
        for c in chunk.columns:
            bd = jnp.broadcast_to(c.data[:, None], (N, W))
            bm = jnp.broadcast_to(c.mask[:, None], (N, W))
            a_col_list.append(lanes(
                bd, bm & a0, bd, bm & a1, c.data, c.mask, c.data.dtype))
        b_col_list = []   # opposite side's columns in output (null in self lane)
        for d, m in zip(b_datas, b_masks):
            zeros_self = jnp.zeros(N, d.dtype)
            b_col_list.append(lanes(
                d, m, d, m, zeros_self, jnp.zeros(N, jnp.bool_), d.dtype))

        if sa is None:
            cols = (a_col_list + b_col_list if side == "left"
                    else b_col_list + a_col_list)
        elif sa == side:
            cols = a_col_list
        else:
            cols = b_col_list
        return ops, vis, tuple(cols)


def clean_side_below(st: JoinSideState, col_idx: int, threshold) -> JoinSideState:
    """Watermark-driven state cleaning: free rows whose ``col_idx`` value is
    below ``threshold`` (reference: interval-join inequality-watermark
    cleaning in src/stream/src/executor/hash_join.rs). Freed lanes become
    tombstones + ckpt_dirty so the next checkpoint persists their deletes;
    ``compact_side`` afterwards reclaims the hash-table slots. Opposite-side
    degrees are NOT adjusted — the watermark contract is that cleaned rows
    can never match again."""
    cleaned = st.occupied & st.row_mask[col_idx] & (st.row_data[col_idx] < threshold)
    return st.replace(
        occupied=st.occupied & ~cleaned,
        tomb=st.tomb | cleaned,
        ckpt_dirty=st.ckpt_dirty | cleaned,
    )


def compact_side(core: "JoinCore", old: JoinSideState, schema: Schema,
                 key_idx: Sequence[int]) -> JoinSideState:
    """Rebuild the side's hash table keeping only keys with live rows,
    remapping the bucket arrays — open-addressing slots cannot be freed in
    place (probe chains), so cleaning reclaims space by rebuild. Run AFTER
    the checkpoint cleared tombstones (their deletes are persisted)."""
    cap, W = core.capacity, core.W
    key_types = tuple(schema[i].type for i in key_idx)
    key_live = old.ht.occupied & jnp.any(old.occupied | old.tomb, axis=1)
    key_cols = [
        Column(kd, km) for kd, km in zip(old.ht.key_data, old.ht.key_mask)
    ]
    ht, slots, _, rebuild_ovf = ht_lookup_or_insert(
        ht_new(key_types, cap), key_cols, key_live)
    dst = jnp.where(key_live, slots, cap)

    def move(arr, fill):
        out = jnp.full((cap, W), fill, arr.dtype)
        return out.at[dst].set(arr, mode="drop")

    return JoinSideState(
        ht=ht,
        row_data=tuple(move(rd, 0) for rd in old.row_data),
        row_mask=tuple(move(rm, False) for rm in old.row_mask),
        occupied=move(old.occupied, False),
        tomb=move(old.tomb, False),
        degree=move(old.degree, 0),
        ckpt_dirty=move(old.ckpt_dirty, False),
        lru=jnp.zeros(cap, jnp.int32).at[dst].set(old.lru, mode="drop"),
        # a key that exhausts probing during rebuild would silently drop its
        # whole bucket via mode="drop" — surface it
        ht_overflow=old.ht_overflow | rebuild_ovf,
        lane_overflow=old.lane_overflow,
        inconsistent=old.inconsistent,
    )


def side_any_overflow(st: JoinSideState) -> bool:
    return bool(st.ht_overflow) | bool(st.lane_overflow)


def _side_live_keys(st: JoinSideState) -> jax.Array:
    """bool[cap]: key slots with at least one live row."""
    return st.ht.occupied & jnp.any(st.occupied, axis=1)


def _side_evictable_keys(st: JoinSideState) -> jax.Array:
    """bool[cap]: live key slots that CAN evict — null-keyed slots are
    permanently resident (their rows can't be faulted back by key
    lookup), so they must not count toward the budget either, or a
    null-heavy side could never get under budget and hot non-null keys
    would thrash."""
    live = _side_live_keys(st)
    for km in st.ht.key_mask:
        live = live & km
    return live


def join_evict_plan(state: JoinState, keep: int):
    """Pick cold keys to evict from BOTH arenas so ~``keep`` hottest
    remain per side (reference: JoinHashMap's ManagedLruCache,
    src/stream/src/executor/managed_state/join/mod.rs:228-258 +
    cache/managed_lru.rs — here eviction is whole-key: a key's buckets
    leave both sides together, so opposite-side degrees stay coherent).

    LRU stamps for one key value are kept in sync across the two sides by
    ``apply_chunk(step=...)``, so ONE threshold — the max of the two
    per-side thresholds — names a consistent key set on both sides.
    Null-keyed slots never evict (their rows can't be faulted back by key
    lookup). Returns (mask_l bool[cap], mask_r bool[cap], packed
    [n_evict_l, n_evict_r, n_live_l, n_live_r])."""
    cap = state.left.lru.shape[0]
    big = jnp.iinfo(jnp.int32).max

    def thr_of(st):
        live = _side_evictable_keys(st)
        n_live = jnp.sum(live)
        key = jnp.where(live, st.lru, big)
        skey = jnp.sort(key)
        k = jnp.clip(n_live - keep, 0, cap - 1)
        thr = jnp.where(k > 0, skey[jnp.maximum(k - 1, 0)], jnp.int32(-1))
        return thr, n_live, live

    thr_l, nl, live_l = thr_of(state.left)
    thr_r, nr, live_r = thr_of(state.right)
    thr = jnp.maximum(thr_l, thr_r)

    mask_l = live_l & (state.left.lru <= thr)
    mask_r = live_r & (state.right.lru <= thr)
    packed = jnp.stack([jnp.sum(mask_l), jnp.sum(mask_r), nl, nr])
    return mask_l, mask_r, packed


def apply_evict_side(st: JoinSideState, mask: jax.Array) -> JoinSideState:
    """Clear evicted keys' buckets WITHOUT tombstones or dirty marks: the
    durable rows (flushed by this barrier's checkpoint) ARE the cold
    copies. Call at a checkpoint barrier AFTER the flush cleared
    tomb/ckpt_dirty, BEFORE compact (which reclaims the key slots)."""
    m2 = mask[:, None]
    return st.replace(
        occupied=st.occupied & ~m2,
        row_mask=tuple(rm & ~m2 for rm in st.row_mask),
        degree=jnp.where(m2, 0, st.degree),
        lru=jnp.where(mask, 0, st.lru),
    )


def import_side(core: "JoinCore", old: JoinSideState, schema: Schema,
                key_idx: Sequence[int]) -> JoinSideState:
    """Re-layout one side's state into ``core``'s (bigger) geometry.

    Functional growth: the streaming executor applies a chunk, checks the
    overflow flags, and on overflow discards the new state, grows, and
    retries on the UNTOUCHED old state — possible only because the whole
    join state is an immutable pytree (the TPU-native analogue of the
    reference growing its hash maps on the heap).

    Width growth pads lanes; capacity growth rehashes keys into the new
    table and moves whole buckets by the slot remap. Degrees move with the
    rows (they depend only on the opposite side's content)."""
    cap, W = core.capacity, core.W
    old_cap, old_W = old.occupied.shape
    assert cap >= old_cap and W >= old_W

    def pad(a, fill=False):
        out = jnp.full((old_cap, W), fill, a.dtype)
        return out.at[:, :old_W].set(a)

    row_data = tuple(pad(rd, 0) for rd in old.row_data)
    row_mask = tuple(pad(rm) for rm in old.row_mask)
    occupied = pad(old.occupied)
    tomb = pad(old.tomb)
    degree = pad(old.degree, 0)
    ckpt_dirty = pad(old.ckpt_dirty)

    key_types = tuple(schema[i].type for i in key_idx)
    if cap == old_cap:
        ht = old.ht
        new = JoinSideState(
            ht=ht, row_data=row_data, row_mask=row_mask, occupied=occupied,
            tomb=tomb, degree=degree, ckpt_dirty=ckpt_dirty, lru=old.lru,
            ht_overflow=jnp.zeros((), jnp.bool_),
            lane_overflow=jnp.zeros((), jnp.bool_),
            inconsistent=old.inconsistent,
        )
        return new
    # rehash keys into the larger table, then move buckets by slot remap
    ht = ht_new(key_types, cap)
    key_cols = [
        Column(kd, km) for kd, km in zip(old.ht.key_data, old.ht.key_mask)
    ]
    ht, new_slots, _, ovf = ht_lookup_or_insert(ht, key_cols, old.ht.occupied)
    if bool(ovf):  # cannot happen: cap > old_cap
        raise RuntimeError("rehash overflow")
    dst = jnp.where(old.ht.occupied, new_slots, cap)

    def move(padded, init_fill):
        out = jnp.full((cap, W), init_fill, padded.dtype)
        return out.at[dst].set(padded, mode="drop")

    return JoinSideState(
        ht=ht,
        row_data=tuple(move(rd, 0) for rd in row_data),
        row_mask=tuple(move(rm, False) for rm in row_mask),
        occupied=move(occupied, False),
        tomb=move(tomb, False),
        degree=move(degree, 0),
        ckpt_dirty=move(ckpt_dirty, False),
        lru=jnp.zeros(cap, jnp.int32).at[dst].set(old.lru, mode="drop"),
        ht_overflow=jnp.zeros((), jnp.bool_),
        lane_overflow=jnp.zeros((), jnp.bool_),
        inconsistent=old.inconsistent,
    )


def import_state(core: "JoinCore", old: JoinState) -> JoinState:
    return JoinState(
        left=import_side(core, old.left, core.left_schema, core.left_keys),
        right=import_side(core, old.right, core.right_schema, core.right_keys),
    )
