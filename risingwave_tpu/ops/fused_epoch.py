"""Single-dispatch epochs: source generation → projection → aggregation
fused into ONE jitted ``lax.scan``.

The dispatch-boundary ladder this removes (BASELINE.md "residual
headroom"; VERDICT r4 item 1): generating an epoch's ChunkBatch is one
dispatch, projecting it a second, the agg scan a third — and the
intermediate [k, cap, n_cols] batch materializes in HBM between them.
Fusing the three means per-epoch host→device traffic is two scalars and
XLA fuses the generator's elementwise work and the projection directly
into the aggregation update, so no intermediate epoch batch ever exists
at HBM granularity (the scan carry is the agg state; each iteration's
chunk lives only inside the step).

This is the generic fusion surface: any traceable ``chunk_fn(start,
key) -> StreamChunk`` source (connector/nexmark.py
``DeviceBidGenerator.chunk_fn``) composes with any expression list and
any ``AggCore``. The reference has no equivalent — its engine is
interpreter-style row batches (src/stream/src/executor/hash_agg.rs);
this is what designing for a compiler buys.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..expr import Expr


def fused_source_agg_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                           core, rows_per_chunk: int,
                           donate: bool = True) -> Callable:
    """Build ``epoch(state, start_event, key, k) -> state``: one compiled
    dispatch applying ``k`` generated+projected chunks to ``core``.

    ``chunk_fn(start_event, key)``: traceable producer of ONE flat chunk
    of ``rows_per_chunk`` rows. ``exprs``: projection onto the agg input
    schema. ``core``: ops.grouped_agg.AggCore (its ``apply_chunk`` is the
    scan body's fold).
    """
    exprs = tuple(exprs)

    def epoch(state, start, key, k: int):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
            return core.apply_chunk(st, projected), None

        state, _ = jax.lax.scan(body, state,
                                jnp.arange(k, dtype=jnp.int64))
        return state

    donate_argnums = ((0,) if donate and jax.default_backend() == "tpu"
                      else ())
    return jax.jit(epoch, static_argnums=(3,),
                   donate_argnums=donate_argnums)
