"""Single-dispatch epochs: source generation → projection → stateful core
fused into ONE jitted ``lax.scan``.

The dispatch-boundary ladder this removes (BASELINE.md "residual
headroom"; VERDICT r4 item 1): generating an epoch's ChunkBatch is one
dispatch, projecting it a second, the agg scan a third — and the
intermediate [k, cap, n_cols] batch materializes in HBM between them.
Fusing the three means per-epoch host→device traffic is two scalars and
XLA fuses the generator's elementwise work and the projection directly
into the stateful update, so no intermediate epoch batch ever exists
at HBM granularity (the scan carry is the core state; each iteration's
chunk lives only inside the step).

Four fusion surfaces now exist (docs/performance.md):

* ``fused_source_agg_epoch`` — the q5 shape: source → project → AggCore.
* ``fused_source_join_epoch`` — the q7 shape: source → project → bucketed
  interval join (ops/interval_join.py), INCLUDING the barrier flush (the
  per-window max delta applied to the stored arena) so a whole epoch —
  k chunks of ingest+probe plus the build-side update — is one dispatch.
* ``fused_source_session_epoch`` — the q8 shape: source → project →
  session-gap windows (ops/session_window.py), including the
  watermark-driven close at the barrier.
* ``fused_source_q3_epoch`` — the TPC-H q3 shape: source → orders-table
  build + lineitem probe + revenue agg + top-n churn
  (ops/stream_q3.py), the whole join+agg+topn MV in one dispatch.

All take any traceable ``chunk_fn(start, key) -> StreamChunk`` source
(connector/nexmark.py ``DeviceBidGenerator.chunk_fn``, connector/tpch.py
``DeviceQ3Generator.chunk_fn``) and — where projection applies — any
expression list. The epoch *bodies* are exposed separately
(``agg_epoch_body`` etc.) so ops/fused_multi.py can ``vmap`` the exact
same computation over a leading job axis: the co-scheduled multi-job
epoch is bit-identical per job to the solo epoch because it IS the same
traced function. The reference has no equivalent — its engine is
interpreter-style row batches (src/stream/src/executor/hash_agg.rs);
this is what designing for a compiler buys.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..common.profiling import profile_dispatch
from ..expr import Expr


def _donate(donate: bool):
    return (0,) if donate and jax.default_backend() == "tpu" else ()


# ---------------------------------------------------------------------------
# epoch bodies — unjitted, shared by the solo jits below and the vmapped
# multi-job epochs (ops/fused_multi.py)
# ---------------------------------------------------------------------------


def agg_epoch_body(chunk_fn: Callable, exprs: Sequence[Expr], core,
                   rows_per_chunk: int) -> Callable:
    """``epoch(state, start_event, key, k) -> state``: ``k`` generated +
    projected chunks folded into ``core`` (ops/grouped_agg.AggCore) by
    one ``lax.scan``."""
    exprs = tuple(exprs)

    def epoch(state, start, key, k: int):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
            return core.apply_chunk(st, projected), None

        state, _ = jax.lax.scan(body, state,
                                jnp.arange(k, dtype=jnp.int64))
        return state

    return epoch


def join_epoch_body(chunk_fn: Callable, exprs: Sequence[Expr], core,
                    rows_per_chunk: int) -> Callable:
    """``epoch(state, start, key, k)`` for the q7 join shape — see
    ``fused_source_join_epoch`` for the return contract."""
    exprs = tuple(exprs)

    def epoch(state, start, key, k: int):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
            st, out = core.apply_chunk(st, projected)
            return st, out

        state, probe_out = jax.lax.scan(
            body, state, jnp.arange(k, dtype=jnp.int64))
        old_emitted_max = state.emitted_max
        del_mask, ins_mask, packed = core.flush_plan(state)
        state = core.finish_flush(state)
        packed = jnp.concatenate(
            [packed, jnp.sum(probe_out.vis).astype(jnp.int64)[None]])
        return state, probe_out, del_mask, ins_mask, old_emitted_max, packed

    return epoch


def session_epoch_body(chunk_fn: Callable, exprs: Sequence[Expr], core,
                       rows_per_chunk: int) -> Callable:
    """``epoch(state, start, key, k, watermark)`` for the q8 session
    shape — see ``fused_source_session_epoch``."""
    exprs = tuple(exprs)

    def epoch(state, start, key, k: int, watermark):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            if exprs:
                ch = ch.with_columns(tuple(e.eval(ch) for e in exprs))
            return core.apply_chunk(st, ch), None

        state, _ = jax.lax.scan(body, state,
                                jnp.arange(k, dtype=jnp.int64))
        state, packed = core.flush_plan(state, watermark)
        snapshot = core.snapshot_closed(state)
        state = core.finish_flush(state)
        return state, snapshot, packed

    return epoch


def q3_epoch_body(chunk_fn: Callable, core,
                  rows_per_chunk: int) -> Callable:
    """``epoch(state, start, key, k)`` for the TPC-H q3 shape — see
    ``fused_source_q3_epoch``."""

    def epoch(state, start, key, k: int):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            return core.apply_chunk(st, ch), None

        state, _ = jax.lax.scan(body, state,
                                jnp.arange(k, dtype=jnp.int64))
        state, out, packed = core.flush(state)
        return state, out, packed

    return epoch


# ---------------------------------------------------------------------------
# solo single-dispatch epochs
# ---------------------------------------------------------------------------


def fused_source_agg_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                           core, rows_per_chunk: int,
                           donate: bool = True) -> Callable:
    """Build ``epoch(state, start_event, key, k) -> state``: one compiled
    dispatch applying ``k`` generated+projected chunks to ``core``.

    ``chunk_fn(start_event, key)``: traceable producer of ONE flat chunk
    of ``rows_per_chunk`` rows. ``exprs``: projection onto the agg input
    schema. ``core``: ops.grouped_agg.AggCore (its ``apply_chunk`` is the
    scan body's fold).
    """
    epoch = agg_epoch_body(chunk_fn, exprs, core, rows_per_chunk)
    # counter identity for common/dispatch_count.py regressions stays
    # stable across the shared-body refactor
    epoch.__qualname__ = "fused_source_agg_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,),
                                    donate_argnums=_donate(donate)),
                            epoch.__qualname__)


def fused_source_join_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                            core, rows_per_chunk: int,
                            donate: bool = True) -> Callable:
    """Build ``epoch(state, start_event, key, k)`` for the q7 join shape:
    ONE compiled dispatch generating + projecting + probe-inserting ``k``
    chunks into ``core`` (ops/interval_join.IntervalJoinCore), then —
    still inside the same dispatch — computing the barrier flush (the
    per-window aggregate delta joined against the stored probe arena)
    and advancing the downstream-visible build rows.

    Returns ``(state, probe_out, del_mask, ins_mask, old_emitted_max,
    packed)``:

    * ``probe_out``: stacked [k, cap] StreamChunk of probe-time matches
      (a ChunkBatch-shaped pytree; flatten_shards + gather_units_window
      compact it downstream).
    * ``del_mask``/``ins_mask``/``old_emitted_max``: inputs for
      ``core.gather_flush`` (the only remaining per-epoch host work is
      reading ``packed`` and gathering output windows).
    * ``packed``: [n_flush_units, lane_overflow, ring_clobber,
      saw_delete, n_probe_units] — ONE scalar fetch per epoch covers
      every host-checked flag AND both emission counts, exactly the
      packed-probe idiom of the executor barriers.
    """
    epoch = join_epoch_body(chunk_fn, exprs, core, rows_per_chunk)
    epoch.__qualname__ = "fused_source_join_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,),
                                    donate_argnums=_donate(donate)),
                            epoch.__qualname__)


def fused_source_session_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                               core, rows_per_chunk: int,
                               donate: bool = True) -> Callable:
    """Build ``epoch(state, start_event, key, k, watermark)`` for the q8
    session-window shape (ops/session_window.SessionWindowCore): ``k``
    generated + projected chunks sessionized in one dispatch, then —
    inside the same dispatch — open sessions the ``watermark`` has
    passed close, the epoch's closed-session buffer is snapshotted for
    emission, and the buffer clears.

    Returns ``(state, snapshot, packed)``; ``packed`` = [n_closed,
    table_overflow, closed_overflow, saw_delete, out_of_order] — one
    scalar fetch per epoch; ``core.gather_closed(snapshot, n_closed, lo,
    cap)`` packs the emission windows."""
    epoch = session_epoch_body(chunk_fn, exprs, core, rows_per_chunk)
    epoch.__qualname__ = "fused_source_session_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,),
                                    donate_argnums=_donate(donate)),
                            epoch.__qualname__)


def fused_source_q3_epoch(chunk_fn: Callable, core, rows_per_chunk: int,
                          donate: bool = True) -> Callable:
    """Build ``epoch(state, start_event, key, k)`` for the TPC-H q3
    streaming-MV shape (ops/stream_q3.Q3Core): ``k`` order/lineitem
    event chunks build + probe + aggregate in one dispatch, and the
    same dispatch recomputes the top-10 and emits its churn.

    Returns ``(state, out_chunk, packed)``; ``out_chunk`` is the fixed
    [2·limit]-row delete/insert churn (already gathered — no windowed
    host drain needed at top-n cardinality); ``packed`` = [n_out,
    orders_overflow, agg_overflow, saw_delete]."""
    epoch = q3_epoch_body(chunk_fn, core, rows_per_chunk)
    epoch.__qualname__ = "fused_source_q3_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,),
                                    donate_argnums=_donate(donate)),
                            epoch.__qualname__)


#: builder registry — the single path bench.py / frontend wiring use to
#: resolve a fused surface by shape name (the q5/q7 entries predate it;
#: q8/q3 registered alongside so new surfaces are discoverable)
EPOCH_BUILDERS = {
    "source_agg": fused_source_agg_epoch,        # NEXmark q5
    "source_join": fused_source_join_epoch,      # NEXmark q7
    "source_session": fused_source_session_epoch,  # NEXmark q8
    "source_q3": fused_source_q3_epoch,          # TPC-H q3
}
