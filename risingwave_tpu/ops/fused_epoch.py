"""Single-dispatch epochs: source generation → projection → aggregation
(or windowed join) fused into ONE jitted ``lax.scan``.

The dispatch-boundary ladder this removes (BASELINE.md "residual
headroom"; VERDICT r4 item 1): generating an epoch's ChunkBatch is one
dispatch, projecting it a second, the agg scan a third — and the
intermediate [k, cap, n_cols] batch materializes in HBM between them.
Fusing the three means per-epoch host→device traffic is two scalars and
XLA fuses the generator's elementwise work and the projection directly
into the aggregation update, so no intermediate epoch batch ever exists
at HBM granularity (the scan carry is the agg state; each iteration's
chunk lives only inside the step).

Two fusion surfaces now exist (docs/performance.md):

* ``fused_source_agg_epoch`` — the q5 shape: source → project → AggCore.
* ``fused_source_join_epoch`` — the q7 shape: source → project → bucketed
  interval join (ops/interval_join.py), INCLUDING the barrier flush (the
  per-window max delta applied to the stored arena) so a whole epoch —
  k chunks of ingest+probe plus the build-side update — is one dispatch.

Both take any traceable ``chunk_fn(start, key) -> StreamChunk`` source
(connector/nexmark.py ``DeviceBidGenerator.chunk_fn``) and any
expression list. The reference has no equivalent — its engine is
interpreter-style row batches (src/stream/src/executor/hash_agg.rs);
this is what designing for a compiler buys.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..expr import Expr


def fused_source_agg_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                           core, rows_per_chunk: int,
                           donate: bool = True) -> Callable:
    """Build ``epoch(state, start_event, key, k) -> state``: one compiled
    dispatch applying ``k`` generated+projected chunks to ``core``.

    ``chunk_fn(start_event, key)``: traceable producer of ONE flat chunk
    of ``rows_per_chunk`` rows. ``exprs``: projection onto the agg input
    schema. ``core``: ops.grouped_agg.AggCore (its ``apply_chunk`` is the
    scan body's fold).
    """
    exprs = tuple(exprs)

    def epoch(state, start, key, k: int):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
            return core.apply_chunk(st, projected), None

        state, _ = jax.lax.scan(body, state,
                                jnp.arange(k, dtype=jnp.int64))
        return state

    donate_argnums = ((0,) if donate and jax.default_backend() == "tpu"
                      else ())
    return jax.jit(epoch, static_argnums=(3,),
                   donate_argnums=donate_argnums)


def fused_source_join_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                            core, rows_per_chunk: int,
                            donate: bool = True) -> Callable:
    """Build ``epoch(state, start_event, key, k)`` for the q7 join shape:
    ONE compiled dispatch generating + projecting + probe-inserting ``k``
    chunks into ``core`` (ops/interval_join.IntervalJoinCore), then —
    still inside the same dispatch — computing the barrier flush (the
    per-window aggregate delta joined against the stored probe arena)
    and advancing the downstream-visible build rows.

    Returns ``(state, probe_out, del_mask, ins_mask, old_emitted_max,
    packed)``:

    * ``probe_out``: stacked [k, cap] StreamChunk of probe-time matches
      (a ChunkBatch-shaped pytree; flatten_shards + gather_units_window
      compact it downstream).
    * ``del_mask``/``ins_mask``/``old_emitted_max``: inputs for
      ``core.gather_flush`` (the only remaining per-epoch host work is
      reading ``packed`` and gathering output windows).
    * ``packed``: [n_flush_units, lane_overflow, ring_clobber,
      saw_delete, n_probe_units] — ONE scalar fetch per epoch covers
      every host-checked flag AND both emission counts, exactly the
      packed-probe idiom of the executor barriers.
    """
    exprs = tuple(exprs)

    def epoch(state, start, key, k: int):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            projected = ch.with_columns(tuple(e.eval(ch) for e in exprs))
            st, out = core.apply_chunk(st, projected)
            return st, out

        state, probe_out = jax.lax.scan(
            body, state, jnp.arange(k, dtype=jnp.int64))
        old_emitted_max = state.emitted_max
        del_mask, ins_mask, packed = core.flush_plan(state)
        state = core.finish_flush(state)
        packed = jnp.concatenate(
            [packed, jnp.sum(probe_out.vis).astype(jnp.int64)[None]])
        return state, probe_out, del_mask, ins_mask, old_emitted_max, packed

    donate_argnums = ((0,) if donate and jax.default_backend() == "tpu"
                      else ())
    return jax.jit(epoch, static_argnums=(3,),
                   donate_argnums=donate_argnums)
