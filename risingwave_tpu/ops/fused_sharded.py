"""Mesh-sharded fused epochs — one dispatch per epoch across ALL chips.

Fusion surfaces 5 and 6 (docs/performance.md): the single-dispatch
epochs of ops/fused_epoch.py (generate → project → stateful core, one
``lax.scan``) promoted from one device to the whole mesh — the FULL solo
ladder (q5 agg, q7 interval join, q8 session windows, TPC-H q3 with its
in-dispatch GLOBAL top-n), a generic JoinCore equi-join surface, and the
co-scheduled group × shard composition (K signature-equal jobs × S
shards in one dispatch, ``build_sharded_group_epoch``). The epoch body
runs UNCHANGED per shard under ``shard_map``; the hash-partitioned
operator state — AggCore tables, IntervalJoinCore bucket rings,
SessionWindowCore key tables, Q3 orders+agg tables — lives sharded
across the mesh axis with a leading ``[n_shards]`` axis (``P('shard')``),
and rows are routed to their owner shard IN-DISPATCH with one
``lax.all_to_all`` per scan iteration, keyed by ``vnode_to_shard`` from
common/hashing.py — the exact contiguous vnode mapping remote exchange
and the executor-path sharded recovery filter use, so cross-worker
routing, in-chip sharding and durable re-sharding always agree.

Epoch anatomy (one jit call — ``common/dispatch_count.py`` counts it as
exactly ONE dispatch regardless of shard count or ``k``):

* shard ``s`` of ``n`` generates the global chunk indices ``{i·n + s}``
  (interleaved), so the union over shards of one epoch's generated chunks
  is EXACTLY the solo epoch's chunk sequence ``0..k-1`` — same start
  offsets, same ``fold_in(key, i)`` — and interleaving keeps global chunk
  order aligned with scan-iteration order, which keeps per-window lane
  fill order identical to the solo path.
* ``k`` need not divide ``n``: trailing iterations generate a chunk whose
  rows are masked invisible (``gi >= k``), which the shuffle drops.
* after projection the chunk all-to-alls by route key; the received
  ``[n·C]`` buffer is COMPACTED to ``recv_width·C`` rows (a rank/scatter
  pass) so per-shard work actually shrinks with the mesh instead of
  staying at the solo chunk cost. Hot-key skew (NEXmark's 90% hot
  auctions) can overflow the compacted width — a sticky ``route_ovf``
  flag per shard reports it, and the driver (parallel/fused.py) grows
  the width and retries the epoch on the UNTOUCHED previous state, the
  same functional grow-retry the sharded hash join uses. For that retry
  to be exact the sharded epochs never donate their state buffers.
* the barrier flush stays inside the dispatch (join) or one vmapped
  probe away (agg — ops/fused_multi.py's group-barrier steps serve the
  shard axis exactly as they serve the co-scheduler's job axis), so the
  per-epoch host fetch is ONE packed stats array covering every shard,
  not one fetch per shard.

Bit-exactness contract (tests/test_fused_sharded.py): hash partitioning
sends every group / window wholly to one shard, and the per-shard body is
the solo body, so the union over shards of group values, probe emissions
and flush churn is bit-identical to the solo fused epoch over the same
``(start, key, k)`` — including U-/U+ retraction pairs and checkpoint
round trips.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.profiling import profile_dispatch
from ..expr import Expr


def compact_chunk(chunk: StreamChunk, cap: int):
    """Compact a mostly-invisible chunk into ``cap`` rows, preserving
    visible-row order (rank = running count of visible rows). Returns
    ``(chunk[cap], overflow)`` — overflow is sticky-style: visible rows
    past ``cap`` are DROPPED and flagged, never silently lost."""
    vis = chunk.vis
    rank = jnp.cumsum(vis) - 1
    dest = jnp.where(vis & (rank < cap), rank, cap)
    ovf = jnp.sum(vis) > cap

    def mv(arr):
        return jnp.zeros((cap,), arr.dtype).at[dest].set(arr, mode="drop")

    cols = tuple(Column(mv(c.data), mv(c.mask)) for c in chunk.columns)
    return StreamChunk(mv(chunk.ops), mv(vis), cols), ovf


def _squeeze(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _unsqueeze(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


def _shard_scan_parts(mesh, recv_width: int):
    """Shared pieces of both sharded epoch builders: lazy parallel-layer
    imports (ops must stay importable without the parallel package's
    executor dependencies) and the (n, recv_cap-fn) pair."""
    from ..parallel.sharded_agg import (  # noqa: PLC0415 — layering
        SHARD_AXIS, shard_map_compat, shuffle_chunk_local,
    )
    n = mesh.devices.size
    if recv_width < 1:
        raise ValueError("recv_width must be >= 1")
    width = min(recv_width, n)
    return SHARD_AXIS, shard_map_compat, shuffle_chunk_local, n, width


def sharded_agg_epoch(chunk_fn: Callable, exprs: Sequence[Expr], core,
                      rows_per_chunk: int, mesh,
                      recv_width: int = 2) -> Callable:
    """Build ``epoch(stacked_state, start, key, k) -> (stacked_state,
    route_ovf[n])``: the q5 source+project+agg epoch sharded over
    ``mesh``. ``stacked_state`` carries a leading ``[n_shards]`` axis
    (``NamedSharding(mesh, P('shard'))``); routing key = the projected
    chunk's ``core.group_keys``. One jit dispatch per epoch."""
    from jax.sharding import PartitionSpec as P

    (axis, shard_map_compat, shuffle_chunk_local, n,
     width) = _shard_scan_parts(mesh, recv_width)
    exprs = tuple(exprs)
    gk = tuple(core.group_keys)
    recv_cap = width * rows_per_chunk

    def epoch(stacked, start, key, k: int):
        kpp = -(-k // n)

        def local(state, start, key):
            state = _squeeze(state)
            s = jax.lax.axis_index(axis)

            def body(carry, i):
                st, rovf = carry
                gi = i * n + s
                ch = chunk_fn(start + gi * rows_per_chunk,
                              jax.random.fold_in(key, gi))
                proj = ch.with_columns(tuple(e.eval(ch) for e in exprs))
                proj = StreamChunk(proj.ops, proj.vis & (gi < k),
                                   proj.columns)
                owned = shuffle_chunk_local(proj, n, gk)
                if width < n:
                    owned, ovf = compact_chunk(owned, recv_cap)
                    rovf = rovf | ovf
                return (core.apply_chunk(st, owned), rovf), None

            (state, rovf), _ = jax.lax.scan(
                body, (state, jnp.zeros((), jnp.bool_)),
                jnp.arange(kpp, dtype=jnp.int64))
            return _unsqueeze(state), rovf[None]

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(axis), P(), P()),
            out_specs=(P(axis), P(axis)))
        return mapped(stacked, start, key)

    epoch.__qualname__ = "sharded_agg_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,)),
                            epoch.__qualname__)


def sharded_join_epoch(chunk_fn: Callable, exprs: Sequence[Expr], core,
                       rows_per_chunk: int, mesh,
                       recv_width: int = 2) -> Callable:
    """Build ``epoch(stacked_state, start, key, k)`` for the q7 shape:
    source + project + bucketed interval join + per-window max flush,
    sharded over ``mesh``. Routing key = the projected window-start
    column (``core.ts_col``), so every window's probe rows and build row
    co-locate and the per-shard body is exactly the solo join epoch body
    over that shard's windows.

    Returns the solo tuple with a leading ``[n_shards]`` axis on every
    element; ``packed`` grows to ``[n, 6]`` — [n_flush, lane_overflow,
    ring_clobber, saw_delete, n_probe, route_ovf] per shard — so ONE
    fetch covers every shard's flags, emission counts AND the routing
    overflow that drives the grow-retry."""
    from jax.sharding import PartitionSpec as P

    (axis, shard_map_compat, shuffle_chunk_local, n,
     width) = _shard_scan_parts(mesh, recv_width)
    exprs = tuple(exprs)
    route = (core.ts_col,)
    recv_cap = width * rows_per_chunk

    def epoch(stacked, start, key, k: int):
        kpp = -(-k // n)

        def local(state, start, key):
            state = _squeeze(state)
            s = jax.lax.axis_index(axis)

            def body(carry, i):
                st, rovf = carry
                gi = i * n + s
                ch = chunk_fn(start + gi * rows_per_chunk,
                              jax.random.fold_in(key, gi))
                proj = ch.with_columns(tuple(e.eval(ch) for e in exprs))
                proj = StreamChunk(proj.ops, proj.vis & (gi < k),
                                   proj.columns)
                owned = shuffle_chunk_local(proj, n, route)
                if width < n:
                    owned, ovf = compact_chunk(owned, recv_cap)
                    rovf = rovf | ovf
                st, out = core.apply_chunk(st, owned)
                return (st, rovf), out

            (state, rovf), probe_out = jax.lax.scan(
                body, (state, jnp.zeros((), jnp.bool_)),
                jnp.arange(kpp, dtype=jnp.int64))
            old_emitted_max = state.emitted_max
            del_mask, ins_mask, packed = core.flush_plan(state)
            state = core.finish_flush(state)
            packed = jnp.concatenate([
                packed,
                jnp.sum(probe_out.vis).astype(jnp.int64)[None],
                rovf.astype(jnp.int64)[None],
            ])
            return (_unsqueeze(state), _unsqueeze(probe_out),
                    del_mask[None], ins_mask[None], old_emitted_max[None],
                    packed[None])

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(axis), P(), P()),
            out_specs=(P(axis),) * 6)
        return mapped(stacked, start, key)

    epoch.__qualname__ = "sharded_join_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,)),
                            epoch.__qualname__)


def sharded_session_epoch(chunk_fn: Callable, exprs: Sequence[Expr], core,
                          rows_per_chunk: int, mesh,
                          recv_width: int = 2) -> Callable:
    """Build ``epoch(stacked_state, start, key, k, watermark)`` for the
    q8 session-window shape (ops/session_window.SessionWindowCore)
    sharded over ``mesh``. Routing key = the projected session-key
    column (``core.key_col``), so every key's whole event history lands
    on one shard and the per-shard body is exactly the solo session
    body over that shard's keys — closed-session multisets and per-key
    open state are bit-identical to the solo epoch (one shard folds its
    n received chunk slices in global chunk order, and session closure
    depends only on the per-key event sequence, which that preserves).

    Returns the solo tuple with a leading ``[n_shards]`` axis on every
    element; ``packed`` grows to ``[n, 6]`` — [n_closed, table_overflow,
    closed_overflow, saw_delete, out_of_order, route_ovf] per shard —
    ONE fetch covering every shard's emission count, sticky flags AND
    the routing overflow that drives the grow-retry."""
    from jax.sharding import PartitionSpec as P

    (axis, shard_map_compat, shuffle_chunk_local, n,
     width) = _shard_scan_parts(mesh, recv_width)
    exprs = tuple(exprs)
    route = (core.key_col,)
    recv_cap = width * rows_per_chunk

    def epoch(stacked, start, key, k: int, watermark):
        kpp = -(-k // n)

        def local(state, start, key, wm):
            state = _squeeze(state)
            s = jax.lax.axis_index(axis)

            def body(carry, i):
                st, rovf = carry
                gi = i * n + s
                ch = chunk_fn(start + gi * rows_per_chunk,
                              jax.random.fold_in(key, gi))
                if exprs:
                    ch = ch.with_columns(tuple(e.eval(ch) for e in exprs))
                ch = StreamChunk(ch.ops, ch.vis & (gi < k), ch.columns)
                owned = shuffle_chunk_local(ch, n, route)
                if width < n:
                    owned, ovf = compact_chunk(owned, recv_cap)
                    rovf = rovf | ovf
                return (core.apply_chunk(st, owned), rovf), None

            (state, rovf), _ = jax.lax.scan(
                body, (state, jnp.zeros((), jnp.bool_)),
                jnp.arange(kpp, dtype=jnp.int64))
            state, packed = core.flush_plan(state, wm)
            snapshot = core.snapshot_closed(state)
            state = core.finish_flush(state)
            packed = jnp.concatenate(
                [packed, rovf.astype(jnp.int64)[None]])
            return (_unsqueeze(state), _unsqueeze(snapshot), packed[None])

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(axis), P(), P(), P()),
            out_specs=(P(axis),) * 3)
        return mapped(stacked, start, key, watermark)

    epoch.__qualname__ = "sharded_session_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,)),
                            epoch.__qualname__)


def sharded_q3_epoch(chunk_fn: Callable, core, rows_per_chunk: int, mesh,
                     recv_width: int = 2) -> Callable:
    """Build ``epoch(stacked_state, start, key, k)`` for the TPC-H q3
    streaming-MV shape (ops/stream_q3.Q3Core) sharded over ``mesh``.
    Routing key = the event's orderkey column, so an order row, its
    lineitems, and their revenue group all co-locate and the per-shard
    body is exactly the solo q3 body over that shard's orders.

    The top-``limit`` flush is GLOBAL: each shard takes the local
    top-``limit`` of its candidates (``Q3Core.topk_perm``), one
    ``lax.all_gather`` unions them (group keys are shard-disjoint, so
    the global top-``limit`` is always inside the union), and every
    shard runs the SAME ``flush_from_candidates`` the solo flush uses
    over the gathered set — the emitted buffer stays replicated across
    shards and the churn chunk is bit-identical on every shard (the
    driver reads shard 0's copy). ``packed`` = [n_out,
    orders_overflow, agg_overflow, saw_delete, route_ovf] per shard."""
    from jax.sharding import PartitionSpec as P

    (axis, shard_map_compat, shuffle_chunk_local, n,
     width) = _shard_scan_parts(mesh, recv_width)
    route = (core.okey_col,)
    recv_cap = width * rows_per_chunk

    def epoch(stacked, start, key, k: int):
        kpp = -(-k // n)

        def local(state, start, key):
            state = _squeeze(state)
            s = jax.lax.axis_index(axis)

            def body(carry, i):
                st, rovf = carry
                gi = i * n + s
                ch = chunk_fn(start + gi * rows_per_chunk,
                              jax.random.fold_in(key, gi))
                ch = StreamChunk(ch.ops, ch.vis & (gi < k), ch.columns)
                owned = shuffle_chunk_local(ch, n, route)
                if width < n:
                    owned, ovf = compact_chunk(owned, recv_cap)
                    rovf = rovf | ovf
                return (core.apply_chunk(st, owned), rovf), None

            (state, rovf), _ = jax.lax.scan(
                body, (state, jnp.zeros((), jnp.bool_)),
                jnp.arange(kpp, dtype=jnp.int64))
            okey, rev, odate, prio, live = core.flush_candidates(state)
            perm = core.topk_perm(okey, rev, live, core.limit)
            local_cand = (okey[perm], rev[perm], odate[perm], prio[perm],
                          live[perm])
            gathered = tuple(
                jax.lax.all_gather(x, axis).reshape(-1)
                for x in local_cand)
            state, out, packed = core.flush_from_candidates(
                state, *gathered)
            packed = jnp.concatenate(
                [packed, rovf.astype(jnp.int64)[None]])
            return (_unsqueeze(state), _unsqueeze(out), packed[None])

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(axis), P(), P()),
            out_specs=(P(axis),) * 3)
        return mapped(stacked, start, key)

    epoch.__qualname__ = "sharded_q3_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,)),
                            epoch.__qualname__)


def sharded_equi_join_epoch(core, mesh, left_keys: Sequence[int],
                            right_keys: Sequence[int]) -> Callable:
    """Build ``epoch(stacked_state, chunk_batch, side)`` — the GENERIC
    sharded equi-join surface (ops/join_state.JoinCore, any schema /
    join type / non-equi condition), fused to one dispatch per epoch.

    ``chunk_batch``: a StreamChunk whose leaves carry leading
    ``[n_shards, k]`` axes (``k`` same-side input chunks per shard);
    one ``lax.scan`` shuffles each chunk to its owner shard by that
    side's join-key columns and applies the UNCHANGED per-shard
    JoinCore step — k chunks of ingest+probe across the whole mesh in
    ONE dispatch, where the executor ladder previously paid one
    dispatch per chunk. Returns ``(stacked_state, emission_grids)``
    with the emission grids stacked ``[n, k, ...]``; overflow handling
    stays the caller's functional grow-retry
    (parallel/sharded_join.ShardedHashJoin.step_epoch)."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.sharded_agg import (  # noqa: PLC0415 — layering
        SHARD_AXIS, shard_map_compat, shuffle_chunk_local,
    )
    n = mesh.devices.size
    keys = {"left": tuple(left_keys), "right": tuple(right_keys)}

    def epoch(stacked, chunk_batch, side: str):
        side_keys = keys[side]

        def local(state, chunks):
            state = _squeeze(state)
            chunks = _squeeze(chunks)          # leaves [k, C]

            def body(st, ch):
                owned = shuffle_chunk_local(ch, n, side_keys)
                st, big = core.apply_chunk(st, owned, side=side)
                return st, big

            state, bigs = jax.lax.scan(body, state, chunks)
            return _unsqueeze(state), _unsqueeze(bigs)

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)))
        return mapped(stacked, chunk_batch)

    epoch.__qualname__ = "sharded_equi_join_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnames=("side",)),
                            epoch.__qualname__)


# ---------------------------------------------------------------------------
# co-scheduled groups × the shard axis: K jobs × S shards, ONE dispatch
# ---------------------------------------------------------------------------


def shuffle_group_chunks(chunks: StreamChunk, n_shards: int,
                         key_idx: Sequence[int]) -> StreamChunk:
    """Grouped in-dispatch hash shuffle: ``chunks`` leaves carry a
    leading ``[J]`` job axis (one chunk per co-scheduled job); returns
    leaves ``[J, n·C]`` — each job's owned rows after ONE all_to_all
    for the whole group. The send-buffer build (argsort + scatter,
    parallel/sharded_agg.chunk_sendbuf) vmaps per job; the collective
    is hand-batched over ``[n, J, C]``, so a K-job group pays exactly
    the single-job shuffle's collective count, and each job's receive
    buffer keeps the single-job source-shard-major row order (the
    bit-exactness anchor vs ShardedFusedAgg)."""
    from ..parallel.sharded_agg import (  # noqa: PLC0415 — layering
        SHARD_AXIS, chunk_sendbuf,
    )
    J, C = chunks.ops.shape[0], chunks.ops.shape[1]
    key_idx = tuple(key_idx)
    send = jax.vmap(lambda ch: chunk_sendbuf(ch, n_shards, key_idx))(
        chunks)                                   # leaves [J, n, C]

    def a2a(x):
        x = jnp.moveaxis(x, 1, 0)                 # [n, J, C]
        r = jax.lax.all_to_all(x, SHARD_AXIS, split_axis=0,
                               concat_axis=0, tiled=True)
        return jnp.moveaxis(r, 0, 1).reshape((J, n_shards * C))

    return jax.tree_util.tree_map(a2a, send)


def build_sharded_group_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                              core, rows_per_chunk: int, mesh,
                              recv_width: int = 2) -> Callable:
    """The sixth fusion surface (docs/performance.md): a co-scheduled
    group of K signature-equal source+agg MVs × S mesh shards in ONE
    dispatch per tick. The two existing multiplexing axes compose —
    ``build_group_epoch``'s vmap-over-jobs runs INSIDE ``shard_map``:
    per scan iteration every job generates + projects its chunk (vmap),
    the whole group's rows route in ONE hand-batched all_to_all
    (``shuffle_group_chunks``), and each (job, shard) cell folds its
    owned rows with the unchanged solo AggCore body.

    Signature: ``epoch(stacked, starts[J], base_keys[J], batch_nos[J],
    k) -> (stacked, route_ovf[n, J])``; ``stacked`` leaves carry
    ``[n_shards, J, ...]`` (``NamedSharding(mesh, P('shard'))`` on the
    leading axis). Per-job PRNG folding happens in-dispatch exactly
    like the mesh-less group epoch (ops/fused_multi.build_group_epoch),
    and shard s of job j generates that job's global chunks
    ``{i·n + s}`` exactly like the single-job sharded epochs — so every
    (job, shard) slice is bit-identical to both the solo fused path and
    ShardedFusedAgg. common/dispatch_count.py counts this as
    ``build_sharded_group_epoch.<locals>.sharded_coscheduled_epoch``."""
    from jax.sharding import PartitionSpec as P

    (axis, shard_map_compat, _shuffle, n,
     width) = _shard_scan_parts(mesh, recv_width)
    exprs = tuple(exprs)
    gk = tuple(core.group_keys)
    recv_cap = width * rows_per_chunk

    def sharded_coscheduled_epoch(stacked, starts, base_keys, batch_nos,
                                  k: int):
        kpp = -(-k // n)

        def local(state, starts, base_keys, batch_nos):
            state = _squeeze(state)               # leaves [J, ...]
            s = jax.lax.axis_index(axis)
            keys = jax.vmap(jax.random.fold_in)(base_keys, batch_nos)
            J = starts.shape[0]

            def body(carry, i):
                st, rovf = carry                  # st [J,...], rovf [J]
                gi = i * n + s

                def gen_one(start_j, key_j):
                    ch = chunk_fn(start_j + gi * rows_per_chunk,
                                  jax.random.fold_in(key_j, gi))
                    proj = ch.with_columns(
                        tuple(e.eval(ch) for e in exprs))
                    return StreamChunk(proj.ops, proj.vis & (gi < k),
                                       proj.columns)

                chunks = jax.vmap(gen_one)(starts, keys)   # leaves [J, C]
                owned = shuffle_group_chunks(chunks, n, gk)
                if width < n:
                    owned, ovf = jax.vmap(
                        lambda c: compact_chunk(c, recv_cap))(owned)
                    rovf = rovf | ovf
                return (jax.vmap(core.apply_chunk)(st, owned), rovf), None

            (state, rovf), _ = jax.lax.scan(
                body, (state, jnp.zeros((J,), jnp.bool_)),
                jnp.arange(kpp, dtype=jnp.int64))
            return _unsqueeze(state), rovf[None]           # [1, J]

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(axis), P(), P(), P()),
            out_specs=(P(axis), P(axis)))
        return mapped(stacked, starts, base_keys, batch_nos)

    sharded_coscheduled_epoch.__qualname__ = \
        "build_sharded_group_epoch.<locals>.sharded_coscheduled_epoch"
    return profile_dispatch(
        jax.jit(sharded_coscheduled_epoch, static_argnums=(4,)),
        sharded_coscheduled_epoch.__qualname__)


#: builder registry, mirroring ops/fused_epoch.EPOCH_BUILDERS — the path
#: bench.py, `ctl profile roofline` and the frontend wiring resolve a
#: sharded surface by shape, and the set rwlint's dispatch-discipline
#: closure + the registry-coverage test walk. Signatures vary by shape
#: (the solo registry has the same property: source_q3 takes no exprs);
#: resolution is by name, never positional across shapes.
SHARDED_EPOCH_BUILDERS = {
    "source_agg": sharded_agg_epoch,         # NEXmark q5 over the mesh
    "source_join": sharded_join_epoch,       # NEXmark q7 over the mesh
    "source_session": sharded_session_epoch,  # NEXmark q8 over the mesh
    "source_q3": sharded_q3_epoch,           # TPC-H q3 over the mesh
    "equi_join": sharded_equi_join_epoch,    # generic JoinCore equi-join
    "group_agg": build_sharded_group_epoch,  # K jobs × S shards
}
