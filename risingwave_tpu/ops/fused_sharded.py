"""Mesh-sharded fused epochs — one dispatch per epoch across ALL chips.

Fifth fusion surface (docs/performance.md): the single-dispatch epochs of
ops/fused_epoch.py (generate → project → stateful core, one ``lax.scan``)
promoted from one device to the whole mesh. The epoch body runs UNCHANGED
per shard under ``shard_map``; the hash-partitioned operator state —
AggCore tables, IntervalJoinCore bucket rings — lives sharded across the
mesh axis with a leading ``[n_shards]`` axis (``P('shard')``), and rows
are routed to their owner shard IN-DISPATCH with one ``lax.all_to_all``
per scan iteration, keyed by ``vnode_to_shard`` from common/hashing.py —
the exact contiguous vnode mapping remote exchange and the executor-path
sharded recovery filter use, so cross-worker routing, in-chip sharding
and durable re-sharding always agree.

Epoch anatomy (one jit call — ``common/dispatch_count.py`` counts it as
exactly ONE dispatch regardless of shard count or ``k``):

* shard ``s`` of ``n`` generates the global chunk indices ``{i·n + s}``
  (interleaved), so the union over shards of one epoch's generated chunks
  is EXACTLY the solo epoch's chunk sequence ``0..k-1`` — same start
  offsets, same ``fold_in(key, i)`` — and interleaving keeps global chunk
  order aligned with scan-iteration order, which keeps per-window lane
  fill order identical to the solo path.
* ``k`` need not divide ``n``: trailing iterations generate a chunk whose
  rows are masked invisible (``gi >= k``), which the shuffle drops.
* after projection the chunk all-to-alls by route key; the received
  ``[n·C]`` buffer is COMPACTED to ``recv_width·C`` rows (a rank/scatter
  pass) so per-shard work actually shrinks with the mesh instead of
  staying at the solo chunk cost. Hot-key skew (NEXmark's 90% hot
  auctions) can overflow the compacted width — a sticky ``route_ovf``
  flag per shard reports it, and the driver (parallel/fused.py) grows
  the width and retries the epoch on the UNTOUCHED previous state, the
  same functional grow-retry the sharded hash join uses. For that retry
  to be exact the sharded epochs never donate their state buffers.
* the barrier flush stays inside the dispatch (join) or one vmapped
  probe away (agg — ops/fused_multi.py's group-barrier steps serve the
  shard axis exactly as they serve the co-scheduler's job axis), so the
  per-epoch host fetch is ONE packed stats array covering every shard,
  not one fetch per shard.

Bit-exactness contract (tests/test_fused_sharded.py): hash partitioning
sends every group / window wholly to one shard, and the per-shard body is
the solo body, so the union over shards of group values, probe emissions
and flush churn is bit-identical to the solo fused epoch over the same
``(start, key, k)`` — including U-/U+ retraction pairs and checkpoint
round trips.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.profiling import profile_dispatch
from ..expr import Expr


def compact_chunk(chunk: StreamChunk, cap: int):
    """Compact a mostly-invisible chunk into ``cap`` rows, preserving
    visible-row order (rank = running count of visible rows). Returns
    ``(chunk[cap], overflow)`` — overflow is sticky-style: visible rows
    past ``cap`` are DROPPED and flagged, never silently lost."""
    vis = chunk.vis
    rank = jnp.cumsum(vis) - 1
    dest = jnp.where(vis & (rank < cap), rank, cap)
    ovf = jnp.sum(vis) > cap

    def mv(arr):
        return jnp.zeros((cap,), arr.dtype).at[dest].set(arr, mode="drop")

    cols = tuple(Column(mv(c.data), mv(c.mask)) for c in chunk.columns)
    return StreamChunk(mv(chunk.ops), mv(vis), cols), ovf


def _squeeze(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _unsqueeze(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


def _shard_scan_parts(mesh, recv_width: int):
    """Shared pieces of both sharded epoch builders: lazy parallel-layer
    imports (ops must stay importable without the parallel package's
    executor dependencies) and the (n, recv_cap-fn) pair."""
    from ..parallel.sharded_agg import (  # noqa: PLC0415 — layering
        SHARD_AXIS, shard_map_compat, shuffle_chunk_local,
    )
    n = mesh.devices.size
    if recv_width < 1:
        raise ValueError("recv_width must be >= 1")
    width = min(recv_width, n)
    return SHARD_AXIS, shard_map_compat, shuffle_chunk_local, n, width


def sharded_agg_epoch(chunk_fn: Callable, exprs: Sequence[Expr], core,
                      rows_per_chunk: int, mesh,
                      recv_width: int = 2) -> Callable:
    """Build ``epoch(stacked_state, start, key, k) -> (stacked_state,
    route_ovf[n])``: the q5 source+project+agg epoch sharded over
    ``mesh``. ``stacked_state`` carries a leading ``[n_shards]`` axis
    (``NamedSharding(mesh, P('shard'))``); routing key = the projected
    chunk's ``core.group_keys``. One jit dispatch per epoch."""
    from jax.sharding import PartitionSpec as P

    (axis, shard_map_compat, shuffle_chunk_local, n,
     width) = _shard_scan_parts(mesh, recv_width)
    exprs = tuple(exprs)
    gk = tuple(core.group_keys)
    recv_cap = width * rows_per_chunk

    def epoch(stacked, start, key, k: int):
        kpp = -(-k // n)

        def local(state, start, key):
            state = _squeeze(state)
            s = jax.lax.axis_index(axis)

            def body(carry, i):
                st, rovf = carry
                gi = i * n + s
                ch = chunk_fn(start + gi * rows_per_chunk,
                              jax.random.fold_in(key, gi))
                proj = ch.with_columns(tuple(e.eval(ch) for e in exprs))
                proj = StreamChunk(proj.ops, proj.vis & (gi < k),
                                   proj.columns)
                owned = shuffle_chunk_local(proj, n, gk)
                if width < n:
                    owned, ovf = compact_chunk(owned, recv_cap)
                    rovf = rovf | ovf
                return (core.apply_chunk(st, owned), rovf), None

            (state, rovf), _ = jax.lax.scan(
                body, (state, jnp.zeros((), jnp.bool_)),
                jnp.arange(kpp, dtype=jnp.int64))
            return _unsqueeze(state), rovf[None]

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(axis), P(), P()),
            out_specs=(P(axis), P(axis)))
        return mapped(stacked, start, key)

    epoch.__qualname__ = "sharded_agg_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,)),
                            epoch.__qualname__)


def sharded_join_epoch(chunk_fn: Callable, exprs: Sequence[Expr], core,
                       rows_per_chunk: int, mesh,
                       recv_width: int = 2) -> Callable:
    """Build ``epoch(stacked_state, start, key, k)`` for the q7 shape:
    source + project + bucketed interval join + per-window max flush,
    sharded over ``mesh``. Routing key = the projected window-start
    column (``core.ts_col``), so every window's probe rows and build row
    co-locate and the per-shard body is exactly the solo join epoch body
    over that shard's windows.

    Returns the solo tuple with a leading ``[n_shards]`` axis on every
    element; ``packed`` grows to ``[n, 6]`` — [n_flush, lane_overflow,
    ring_clobber, saw_delete, n_probe, route_ovf] per shard — so ONE
    fetch covers every shard's flags, emission counts AND the routing
    overflow that drives the grow-retry."""
    from jax.sharding import PartitionSpec as P

    (axis, shard_map_compat, shuffle_chunk_local, n,
     width) = _shard_scan_parts(mesh, recv_width)
    exprs = tuple(exprs)
    route = (core.ts_col,)
    recv_cap = width * rows_per_chunk

    def epoch(stacked, start, key, k: int):
        kpp = -(-k // n)

        def local(state, start, key):
            state = _squeeze(state)
            s = jax.lax.axis_index(axis)

            def body(carry, i):
                st, rovf = carry
                gi = i * n + s
                ch = chunk_fn(start + gi * rows_per_chunk,
                              jax.random.fold_in(key, gi))
                proj = ch.with_columns(tuple(e.eval(ch) for e in exprs))
                proj = StreamChunk(proj.ops, proj.vis & (gi < k),
                                   proj.columns)
                owned = shuffle_chunk_local(proj, n, route)
                if width < n:
                    owned, ovf = compact_chunk(owned, recv_cap)
                    rovf = rovf | ovf
                st, out = core.apply_chunk(st, owned)
                return (st, rovf), out

            (state, rovf), probe_out = jax.lax.scan(
                body, (state, jnp.zeros((), jnp.bool_)),
                jnp.arange(kpp, dtype=jnp.int64))
            old_emitted_max = state.emitted_max
            del_mask, ins_mask, packed = core.flush_plan(state)
            state = core.finish_flush(state)
            packed = jnp.concatenate([
                packed,
                jnp.sum(probe_out.vis).astype(jnp.int64)[None],
                rovf.astype(jnp.int64)[None],
            ])
            return (_unsqueeze(state), _unsqueeze(probe_out),
                    del_mask[None], ins_mask[None], old_emitted_max[None],
                    packed[None])

        mapped = shard_map_compat(
            local, mesh=mesh, in_specs=(P(axis), P(), P()),
            out_specs=(P(axis),) * 6)
        return mapped(stacked, start, key)

    epoch.__qualname__ = "sharded_join_epoch.<locals>.epoch"
    return profile_dispatch(jax.jit(epoch, static_argnums=(3,)),
                            epoch.__qualname__)


#: builder registry, mirroring ops/fused_epoch.EPOCH_BUILDERS — the path
#: bench.py and the frontend wiring resolve a sharded surface by shape
SHARDED_EPOCH_BUILDERS = {
    "source_agg": sharded_agg_epoch,     # NEXmark q5 over the mesh
    "source_join": sharded_join_epoch,   # NEXmark q7 over the mesh
}
