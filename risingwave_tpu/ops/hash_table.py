"""Device-resident open-addressing hash table (key → slot index).

This is the TPU-native replacement for the reference's host hash maps behind
HashAgg / HashJoin (reference: JoinHashMap over StateTables,
src/stream/src/executor/managed_state/join/mod.rs:228-258, and the per-key
AggGroup cache, src/stream/src/executor/aggregation/agg_group.rs:159). Instead
of pointer-chasing per row, a whole chunk of keys is probed **in parallel**
with XLA-friendly control flow: a bounded ``lax.while_loop`` of vectorized
gather/compare/scatter rounds with conflict resolution by scatter-min claim.

The table only maps keys to stable slot indices; callers keep their own
value arrays ``[capacity, ...]`` indexed by slot (agg lanes, join buckets).
Capacity is static (power of two); load factor should stay ≲ 0.7 — the
executor sizes it and checks the returned overflow flag on barriers.

Intra-batch duplicate keys resolve to the SAME slot (identical probe
sequences; the scatter-min claim makes one row the inserting winner, the rest
match it on the following round), so a scatter-add over the returned slots is
an exact grouped reduction even with duplicates.

Null semantics: group keys compare SQL-GROUP-BY style, i.e. NULL == NULL.
Slots are never freed (dead groups keep their key; re-insertion of the same
key reuses the slot). A rebuild-on-barrier compaction can reclaim space later
without changing this API.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import Column
from ..common.hashing import hash_columns

MAX_PROBE_ROUNDS = 128


@struct.dataclass
class DeviceHashTable:
    key_data: tuple[jax.Array, ...]   # per key column: dtype[cap]
    key_mask: tuple[jax.Array, ...]   # per key column: bool[cap] (True=non-null)
    occupied: jax.Array               # bool[cap]

    @property
    def capacity(self) -> int:
        return self.occupied.shape[0]

    def num_occupied(self) -> jax.Array:
        return jnp.sum(self.occupied)


def ht_new(key_types: Sequence, capacity: int) -> DeviceHashTable:
    """``key_types``: DataTypes of the key columns. ``capacity``: power of 2."""
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    return DeviceHashTable(
        key_data=tuple(jnp.zeros(capacity, t.dtype) for t in key_types),
        key_mask=tuple(jnp.zeros(capacity, jnp.bool_) for _ in key_types),
        occupied=jnp.zeros(capacity, jnp.bool_),
    )


def _keys_equal_at(table: DeviceHashTable, cand: jax.Array,
                   datas: Sequence[jax.Array], masks: Sequence[jax.Array]) -> jax.Array:
    """Row-wise: does the key stored at slot ``cand`` equal each probe key?"""
    eq = jnp.ones(cand.shape, jnp.bool_)
    for td, tm, d, m in zip(table.key_data, table.key_mask, datas, masks):
        sd = td[cand]
        sm = tm[cand]
        col_eq = (sm & m & (sd == d)) | (~sm & ~m)  # NULL == NULL for grouping
        eq = eq & col_eq
    return eq


def ht_lookup_or_insert(
    table: DeviceHashTable, key_cols: Sequence[Column], valid: jax.Array
):
    """Find-or-insert a batch of keys.

    Returns ``(table, slots, is_new, overflow)``:
      * ``slots`` int32[N]: slot per row (== capacity for invalid/overflow rows,
        safe to use with ``.at[slots].add(..., mode='drop')``),
      * ``is_new`` bool[N]: True for the single winning row that inserted a
        previously-absent key,
      * ``overflow`` bool: some valid row failed to find/claim a slot.
    """
    cap = table.capacity
    datas = [c.data for c in key_cols]
    masks = [c.mask for c in key_cols]
    n = valid.shape[0]
    h = (hash_columns(key_cols) & jnp.uint64(cap - 1)).astype(jnp.int32)

    def cond(state):
        _, _, _, done, _, _, it = state
        return jnp.any(~done) & (it < MAX_PROBE_ROUNDS)

    def body(state):
        occupied, key_data, key_mask, done, slot, is_new, it = state
        t = table.replace(occupied=occupied, key_data=key_data, key_mask=key_mask)
        probe = slot  # reuse: slot holds current probe offset for not-done rows
        cand = (h + probe) & (cap - 1)
        occ = occupied[cand]
        eq = occ & _keys_equal_at(t, cand, datas, masks)
        newly_found = ~done & eq
        # claim attempt on empty slots: winner = min row_id among rows
        # targeting the same empty slot, resolved by sorting (slot, row_id)
        # pairs on the CHUNK — O(n log n) on n rows, never O(capacity).
        # (A capacity-sized scatter-min claims array would memset the whole
        # table every probe round — at multi-million-slot capacities that
        # dominates the entire step.)
        want = ~done & ~occ
        cand_eff = jnp.where(want, cand, cap)
        order = jnp.argsort(cand_eff, stable=True)  # stable ⇒ min row_id first
        sorted_slot = cand_eff[order]
        first = jnp.concatenate([
            jnp.ones(1, jnp.bool_), sorted_slot[1:] != sorted_slot[:-1]])
        winner_sorted = first & (sorted_slot < cap)
        winner = jnp.zeros(n, jnp.bool_).at[order].set(winner_sorted)
        widx = jnp.where(winner, cand, cap)
        occupied = occupied.at[widx].set(True, mode="drop")
        key_data = tuple(
            kd.at[widx].set(d, mode="drop") for kd, d in zip(key_data, datas)
        )
        key_mask = tuple(
            km.at[widx].set(m, mode="drop") for km, m in zip(key_mask, masks)
        )
        settled = newly_found | winner
        # advance probe offset on true collision (occupied, different key);
        # settled and done rows never advance, freezing their final offset
        advance = ~done & occ & ~eq
        slot = probe + advance.astype(jnp.int32)
        done2 = done | settled
        is_new = is_new | winner
        return occupied, key_data, key_mask, done2, slot, is_new, it + 1

    init = (
        table.occupied, table.key_data, table.key_mask,
        ~valid, jnp.zeros(n, jnp.int32), jnp.zeros(n, jnp.bool_), jnp.int32(0),
    )
    occupied, key_data, key_mask, done, offset, is_new, _ = jax.lax.while_loop(
        cond, body, init
    )
    settled = done & valid
    slots = jnp.where(settled, (h + offset) & (cap - 1), cap).astype(jnp.int32)
    overflow = jnp.any(valid & ~done)
    new_table = table.replace(
        occupied=occupied, key_data=key_data, key_mask=key_mask
    )
    return new_table, slots, is_new & valid, overflow


def ht_lookup(table: DeviceHashTable, key_cols: Sequence[Column], valid: jax.Array):
    """Read-only probe. Returns ``(slots, found)``; slots == capacity if absent."""
    cap = table.capacity
    datas = [c.data for c in key_cols]
    masks = [c.mask for c in key_cols]
    n = valid.shape[0]
    h = (hash_columns(key_cols) & jnp.uint64(cap - 1)).astype(jnp.int32)

    def cond(state):
        done, _, _, it = state
        return jnp.any(~done) & (it < MAX_PROBE_ROUNDS)

    def body(state):
        done, offset, found, it = state
        cand = (h + offset) & (cap - 1)
        occ = table.occupied[cand]
        eq = occ & _keys_equal_at(table, cand, datas, masks)
        hit = ~done & eq
        miss = ~done & ~occ          # empty slot ⇒ key absent (no tombstones)
        done2 = done | hit | miss
        found = found | hit
        offset = offset + (~done2).astype(jnp.int32)
        return done2, offset, found, it + 1

    init = (~valid, jnp.zeros(n, jnp.int32), jnp.zeros(n, jnp.bool_), jnp.int32(0))
    done, offset, found, _ = jax.lax.while_loop(cond, body, init)
    slots = jnp.where(found, (h + offset) & (cap - 1), cap).astype(jnp.int32)
    return slots, found


def scatter_reduce(target: jax.Array, slots: jax.Array, contrib: jax.Array, op: str) -> jax.Array:
    """Grouped reduction into per-slot state: target[slot] ⊕= contrib.

    Out-of-range slots (capacity sentinel) are dropped — this is how invalid
    rows are masked out. Duplicate slots within the batch combine exactly.
    """
    if op == "add":
        return target.at[slots].add(contrib.astype(target.dtype), mode="drop")
    if op == "min":
        return target.at[slots].min(contrib.astype(target.dtype), mode="drop")
    if op == "max":
        return target.at[slots].max(contrib.astype(target.dtype), mode="drop")
    raise ValueError(op)
