"""Pure device-side grouped-aggregation core.

The functional heart shared by HashAggExecutor (single shard) and the
sharded/multi-chip path (parallel/sharded_agg.py): all logic is pure
(state, chunk) -> state / chunk, so it runs unchanged inside ``jit`` on one
chip or inside ``shard_map`` per mesh shard. See stream/hash_agg.py for the
semantics discussion and reference citations.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, Column,
    StreamChunk,
)
from ..expr.agg import AggCall
from .hash_table import DeviceHashTable, ht_lookup_or_insert, ht_new, scatter_reduce


@struct.dataclass
class AggState:
    table: DeviceHashTable
    lanes: tuple[jax.Array, ...]       # [cap] per lane; lane 0 = row count
    prev_lanes: tuple[jax.Array, ...]  # values as of last emitted flush
    dirty: jax.Array                   # bool[cap] since last barrier flush
    ckpt_dirty: jax.Array              # bool[cap] since last checkpoint
    overflow: jax.Array                # bool scalar, sticky


class AggCore:
    """Static config + pure methods for one grouped-agg operator."""

    def __init__(self, key_types: Sequence, group_keys: Sequence[int],
                 agg_calls: Sequence[AggCall], table_capacity: int,
                 out_capacity: int):
        self.key_types = tuple(key_types)
        self.group_keys = tuple(group_keys)
        self.agg_calls = tuple(agg_calls)
        self.capacity = table_capacity
        self.out_capacity = out_capacity
        self.groups_per_chunk = out_capacity // 2
        self.lane_dtypes = [jnp.int64]
        self.call_lane_ofs = []
        for c in self.agg_calls:
            self.call_lane_ofs.append(len(self.lane_dtypes))
            self.lane_dtypes.extend(c.state_dtypes())

    def init_state(self) -> AggState:
        cap = self.capacity
        init_lanes = [jnp.zeros(cap, jnp.int64)]
        for c in self.agg_calls:
            for v, dt in zip(c.init_lanes(), c.state_dtypes()):
                init_lanes.append(jnp.full(cap, v, dt))
        return AggState(
            table=ht_new(self.key_types, cap),
            lanes=tuple(init_lanes),
            prev_lanes=tuple(init_lanes),
            dirty=jnp.zeros(cap, jnp.bool_),
            ckpt_dirty=jnp.zeros(cap, jnp.bool_),
            overflow=jnp.zeros((), jnp.bool_),
        )

    # -- pure steps -----------------------------------------------------------

    def apply_chunk(self, state: AggState, chunk: StreamChunk) -> AggState:
        key_cols = [chunk.columns[i] for i in self.group_keys]
        table, slots, _is_new, ovf = ht_lookup_or_insert(
            state.table, key_cols, chunk.vis
        )
        signs = chunk.signs()
        lanes = list(state.lanes)
        lanes[0] = scatter_reduce(lanes[0], slots, signs, "add")
        for call, ofs in zip(self.agg_calls, self.call_lane_ofs):
            if call.arg >= 0:
                col = chunk.columns[call.arg]
                value, vmask = col.data, col.mask & chunk.vis
            else:
                value = jnp.zeros_like(signs)
                vmask = chunk.vis
            contribs = call.contributions(value, vmask, signs)
            for j, (contrib, op) in enumerate(zip(contribs, call.reduce_ops())):
                lanes[ofs + j] = scatter_reduce(lanes[ofs + j], slots, contrib, op)
        mark = jnp.where(chunk.vis, slots, self.capacity)
        dirty = state.dirty.at[mark].set(True, mode="drop")
        ckpt_dirty = state.ckpt_dirty.at[mark].set(True, mode="drop")
        return state.replace(
            table=table, lanes=tuple(lanes), dirty=dirty,
            ckpt_dirty=ckpt_dirty, overflow=state.overflow | ovf,
        )

    def outputs(self, lanes) -> list[tuple[jax.Array, jax.Array]]:
        live = lanes[0] > 0
        outs = []
        for call, ofs in zip(self.agg_calls, self.call_lane_ofs):
            call_lanes = [lanes[ofs + j] for j in range(call.num_lanes)]
            data, mask = call.output(call_lanes, live)
            outs.append((data.astype(call.output_type.dtype), mask))
        return outs

    def flush_rank(self, state: AggState) -> jax.Array:
        """Inclusive prefix count of dirty groups — computed ONCE per barrier
        and shared by every flush window (it is the only O(capacity) piece of
        the flush)."""
        return jnp.cumsum(state.dirty.astype(jnp.int32))

    def gather_flush_chunk(self, state: AggState, rank: jax.Array,
                           lo: jax.Array) -> StreamChunk:
        """One output chunk for dirty groups with rank in [lo, lo+G).

        Pure gather formulation: the slot of the k-th dirty group is found by
        binary search over the rank prefix sums, then every output column is
        a [G]-sized gather + interleave. No scatters — TPU scatters serialize
        per update, and the old scatter-from-[capacity] form cost ~1 s per
        window at multi-million-row capacity."""
        G = self.groups_per_chunk
        ks = lo.astype(jnp.int32) + jnp.arange(G, dtype=jnp.int32)
        pos = jnp.searchsorted(rank, ks + 1, side="left").astype(jnp.int32)
        valid = (ks + 1) <= rank[-1]
        slot = jnp.where(valid, pos, 0)

        def interleave(a, b):
            return jnp.stack([a, b], axis=-1).reshape(2 * G)

        prev_g = [l[slot] for l in state.prev_lanes]
        cur_g = [l[slot] for l in state.lanes]
        prev_live = prev_g[0] > 0
        cur_live = cur_g[0] > 0

        op0 = jnp.where(cur_live, OP_UPDATE_DELETE, OP_DELETE)   # prev row
        op1 = jnp.where(prev_live, OP_UPDATE_INSERT, OP_INSERT)  # cur row
        ops = interleave(op0, op1).astype(jnp.int8)
        vis = interleave(prev_live & valid, cur_live & valid)

        cols = []
        for kd, km in zip(state.table.key_data, state.table.key_mask):
            d, m = kd[slot], km[slot]
            cols.append(Column(interleave(d, d), interleave(m, m)))
        prev_outs = self.outputs(prev_g)
        cur_outs = self.outputs(cur_g)
        for (pd, pm), (cd, cm) in zip(prev_outs, cur_outs):
            cols.append(Column(interleave(pd.astype(cd.dtype), cd),
                               interleave(pm, cm)))
        return StreamChunk(ops, vis, tuple(cols))

    def finish_flush(self, state: AggState) -> AggState:
        prev = tuple(
            jnp.where(state.dirty, cur, prev)
            for cur, prev in zip(state.lanes, state.prev_lanes)
        )
        return state.replace(prev_lanes=prev, dirty=jnp.zeros_like(state.dirty))

    # -- watermark-driven state cleaning --------------------------------------
    # (reference: state cleaning via state-table watermarks,
    #  src/stream/src/common/table/state_table.rs:885 update_watermark;
    #  hash_agg group-key watermark handling)

    def clean_below(self, state: AggState, key_pos: int,
                    threshold) -> AggState:
        """Mark groups whose ``key_pos``-th group-key value < threshold as
        dead: lanes reset to init (row_count 0) and ckpt_dirty set so the
        next checkpoint writes durable deletes. The hash table is NOT
        touched here — freeing open-addressing slots in place would break
        probe chains; ``compact`` rebuilds it after the checkpoint."""
        kd = state.table.key_data[key_pos]
        km = state.table.key_mask[key_pos]
        dead = state.table.occupied & km & (kd < threshold)
        init = self.init_state()
        lanes = tuple(
            jnp.where(dead, il, l) for l, il in zip(state.lanes, init.lanes))
        return state.replace(
            lanes=lanes,
            ckpt_dirty=state.ckpt_dirty | dead,
            # no `dirty` mark: cleaning frees state, it does not retract
            # already-emitted results downstream
        )

    def compact(self, state: AggState) -> AggState:
        """Rebuild the hash table keeping only live groups (row_count > 0),
        remapping every lane array. Run AFTER the checkpoint that persisted
        the deletes (the delete path still needs the dead groups' keys)."""
        cap = self.capacity
        live = state.table.occupied & (state.lanes[0] > 0)
        key_cols = [
            Column(kd, km)
            for kd, km in zip(state.table.key_data, state.table.key_mask)
        ]
        ht, slots, _, rebuild_ovf = ht_lookup_or_insert(
            ht_new(self.key_types, cap), key_cols, live)
        dst = jnp.where(live, slots, cap)
        init = self.init_state()

        def move(arr, init_arr):
            return init_arr.at[dst].set(arr, mode="drop")

        return AggState(
            table=ht,
            lanes=tuple(move(l, il)
                        for l, il in zip(state.lanes, init.lanes)),
            prev_lanes=tuple(move(l, il)
                             for l, il in zip(state.prev_lanes, init.lanes)),
            dirty=move(state.dirty, init.dirty),
            ckpt_dirty=move(state.ckpt_dirty, init.ckpt_dirty),
            # a group that exhausts probing during rebuild would be silently
            # dropped by mode="drop" — surface it like every overflow path
            overflow=state.overflow | rebuild_ovf,
        )
