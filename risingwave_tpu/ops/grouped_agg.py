"""Pure device-side grouped-aggregation core.

The functional heart shared by HashAggExecutor (single shard) and the
sharded/multi-chip path (parallel/sharded_agg.py): all logic is pure
(state, chunk) -> state / chunk, so it runs unchanged inside ``jit`` on one
chip or inside ``shard_map`` per mesh shard. See stream/hash_agg.py for the
semantics discussion and reference citations.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, Column,
    StreamChunk,
)
from ..expr.agg import AggCall
from .hash_table import DeviceHashTable, ht_lookup_or_insert, ht_new, scatter_reduce


@struct.dataclass
class AggState:
    table: DeviceHashTable
    lanes: tuple[jax.Array, ...]       # [cap] per lane; lane 0 = row count
    prev_lanes: tuple[jax.Array, ...]  # values as of last emitted flush
    dirty: jax.Array                   # bool[cap] since last barrier flush
    ckpt_dirty: jax.Array              # bool[cap] since last checkpoint
    overflow: jax.Array                # bool scalar, sticky
    last_used: jax.Array               # int32[cap]: step of last touch (LRU)


class AggCore:
    """Static config + pure methods for one grouped-agg operator."""

    def __init__(self, key_types: Sequence, group_keys: Sequence[int],
                 agg_calls: Sequence[AggCall], table_capacity: int,
                 out_capacity: int):
        self.key_types = tuple(key_types)
        self.group_keys = tuple(group_keys)
        self.agg_calls = tuple(agg_calls)
        self.capacity = table_capacity
        self.out_capacity = out_capacity
        self.groups_per_chunk = out_capacity // 2
        self.lane_dtypes = [jnp.int64]
        self.call_lane_ofs = []
        for c in self.agg_calls:
            self.call_lane_ofs.append(len(self.lane_dtypes))
            self.lane_dtypes.extend(c.state_dtypes())

    def init_state(self) -> AggState:
        cap = self.capacity
        init_lanes = [jnp.zeros(cap, jnp.int64)]
        for c in self.agg_calls:
            for v, dt in zip(c.init_lanes(), c.state_dtypes()):
                init_lanes.append(jnp.full(cap, v, dt))
        return AggState(
            table=ht_new(self.key_types, cap),
            lanes=tuple(init_lanes),
            prev_lanes=tuple(init_lanes),
            dirty=jnp.zeros(cap, jnp.bool_),
            ckpt_dirty=jnp.zeros(cap, jnp.bool_),
            overflow=jnp.zeros((), jnp.bool_),
            last_used=jnp.zeros(cap, jnp.int32),
        )

    # -- pure steps -----------------------------------------------------------

    def apply_chunk(self, state: AggState, chunk: StreamChunk,
                    str_ranks=None, step=None) -> AggState:
        """``step``: monotone host counter stamped onto touched slots for
        LRU eviction ordering (None = no tracking; the sharded path and
        budget-less executors skip it)."""
        key_cols = [chunk.columns[i] for i in self.group_keys]
        table, slots, _is_new, ovf = ht_lookup_or_insert(
            state.table, key_cols, chunk.vis
        )
        signs = chunk.signs()
        lanes = list(state.lanes)
        lanes[0] = scatter_reduce(lanes[0], slots, signs, "add")
        for call, ofs in zip(self.agg_calls, self.call_lane_ofs):
            if call.arg >= 0:
                col = chunk.columns[call.arg]
                value, vmask = col.data, col.mask & chunk.vis
            else:
                value = jnp.zeros_like(signs)
                vmask = chunk.vis
            contribs = call.contributions(value, vmask, signs, str_ranks)
            for j, (contrib, op) in enumerate(zip(contribs, call.reduce_ops())):
                # string MIN/MAX: reduce in packed rank|id space, store ids
                lane = call.pack_lane(lanes[ofs + j], str_ranks)
                lanes[ofs + j] = call.unpack_lane(
                    scatter_reduce(lane, slots, contrib, op))
        mark = jnp.where(chunk.vis, slots, self.capacity)
        dirty = state.dirty.at[mark].set(True, mode="drop")
        ckpt_dirty = state.ckpt_dirty.at[mark].set(True, mode="drop")
        last_used = state.last_used
        if step is not None:
            last_used = last_used.at[mark].set(
                jnp.asarray(step, jnp.int32), mode="drop")
        return state.replace(
            table=table, lanes=tuple(lanes), dirty=dirty,
            ckpt_dirty=ckpt_dirty, overflow=state.overflow | ovf,
            last_used=last_used,
        )

    def outputs(self, lanes) -> list[tuple[jax.Array, jax.Array]]:
        live = lanes[0] > 0
        outs = []
        for call, ofs in zip(self.agg_calls, self.call_lane_ofs):
            call_lanes = [lanes[ofs + j] for j in range(call.num_lanes)]
            data, mask = call.output(call_lanes, live)
            outs.append((data.astype(call.output_type.dtype), mask))
        return outs

    def flush_rank(self, state: AggState) -> jax.Array:
        """Inclusive prefix count of dirty groups — computed ONCE per barrier
        and shared by every flush window (it is the only O(capacity) piece of
        the flush)."""
        return jnp.cumsum(state.dirty.astype(jnp.int32))

    def gather_flush_chunk(self, state: AggState, rank: jax.Array,
                           lo: jax.Array) -> StreamChunk:
        """One output chunk for dirty groups with rank in [lo, lo+G).

        Pure gather formulation: the slot of the k-th dirty group is found by
        binary search over the rank prefix sums, then every output column is
        a [G]-sized gather + interleave. No scatters — TPU scatters serialize
        per update, and the old scatter-from-[capacity] form cost ~1 s per
        window at multi-million-row capacity."""
        G = self.groups_per_chunk
        ks = lo.astype(jnp.int32) + jnp.arange(G, dtype=jnp.int32)
        pos = jnp.searchsorted(rank, ks + 1, side="left").astype(jnp.int32)
        valid = (ks + 1) <= rank[-1]
        slot = jnp.where(valid, pos, 0)

        def interleave(a, b):
            return jnp.stack([a, b], axis=-1).reshape(2 * G)

        prev_g = [l[slot] for l in state.prev_lanes]
        cur_g = [l[slot] for l in state.lanes]
        prev_live = prev_g[0] > 0
        cur_live = cur_g[0] > 0

        op0 = jnp.where(cur_live, OP_UPDATE_DELETE, OP_DELETE)   # prev row
        op1 = jnp.where(prev_live, OP_UPDATE_INSERT, OP_INSERT)  # cur row
        ops = interleave(op0, op1).astype(jnp.int8)
        vis = interleave(prev_live & valid, cur_live & valid)

        cols = []
        for kd, km in zip(state.table.key_data, state.table.key_mask):
            d, m = kd[slot], km[slot]
            cols.append(Column(interleave(d, d), interleave(m, m)))
        prev_outs = self.outputs(prev_g)
        cur_outs = self.outputs(cur_g)
        for (pd, pm), (cd, cm) in zip(prev_outs, cur_outs):
            cols.append(Column(interleave(pd.astype(cd.dtype), cd),
                               interleave(pm, cm)))
        return StreamChunk(ops, vis, tuple(cols))

    def finish_flush(self, state: AggState) -> AggState:
        prev = tuple(
            jnp.where(state.dirty, cur, prev)
            for cur, prev in zip(state.lanes, state.prev_lanes)
        )
        return state.replace(prev_lanes=prev, dirty=jnp.zeros_like(state.dirty))

    # -- watermark-driven state cleaning --------------------------------------
    # (reference: state cleaning via state-table watermarks,
    #  src/stream/src/common/table/state_table.rs:885 update_watermark;
    #  hash_agg group-key watermark handling)

    def clean_below(self, state: AggState, key_pos: int,
                    threshold) -> AggState:
        """Mark groups whose ``key_pos``-th group-key value < threshold as
        dead: lanes reset to init (row_count 0) and ckpt_dirty set so the
        next checkpoint writes durable deletes. The hash table is NOT
        touched here — freeing open-addressing slots in place would break
        probe chains; ``compact`` rebuilds it after the checkpoint."""
        kd = state.table.key_data[key_pos]
        km = state.table.key_mask[key_pos]
        dead = state.table.occupied & km & (kd < threshold)
        init = self.init_state()
        lanes = tuple(
            jnp.where(dead, il, l) for l, il in zip(state.lanes, init.lanes))
        return state.replace(
            lanes=lanes,
            ckpt_dirty=state.ckpt_dirty | dead,
            # no `dirty` mark: cleaning frees state, it does not retract
            # already-emitted results downstream
        )

    def compact(self, state: AggState) -> AggState:
        """Rebuild the hash table keeping only live groups (row_count > 0),
        remapping every lane array. Run AFTER the checkpoint that persisted
        the deletes (the delete path still needs the dead groups' keys)."""
        cap = self.capacity
        live = state.table.occupied & (state.lanes[0] > 0)
        key_cols = [
            Column(kd, km)
            for kd, km in zip(state.table.key_data, state.table.key_mask)
        ]
        ht, slots, _, rebuild_ovf = ht_lookup_or_insert(
            ht_new(self.key_types, cap), key_cols, live)
        dst = jnp.where(live, slots, cap)
        init = self.init_state()

        def move(arr, init_arr):
            return init_arr.at[dst].set(arr, mode="drop")

        return AggState(
            table=ht,
            lanes=tuple(move(l, il)
                        for l, il in zip(state.lanes, init.lanes)),
            prev_lanes=tuple(move(l, il)
                             for l, il in zip(state.prev_lanes, init.lanes)),
            dirty=move(state.dirty, init.dirty),
            ckpt_dirty=move(state.ckpt_dirty, init.ckpt_dirty),
            # a group that exhausts probing during rebuild would be silently
            # dropped by mode="drop" — surface it like every overflow path
            overflow=state.overflow | rebuild_ovf,
            last_used=move(state.last_used, init.last_used),
        )

    # -- HBM eviction to the cold tier ----------------------------------------
    # (reference: ManagedLruCache over StateTables under memory pressure,
    #  src/stream/src/cache/managed_lru.rs; JoinHashMap LRU,
    #  executor/managed_state/join/mod.rs:228-258. Device state is a CACHE
    #  over the state table: eviction frees slots whose durable copy is
    #  current, absorb() faults a key's stored value back in on access.)

    def evict_plan(self, state: AggState, keep: int):
        """Pick cold live slots to evict so ~``keep`` hottest remain.

        Returns (mask bool[cap], n_evicted). Threshold-based on the LRU
        step stamp: ties at the threshold may evict slightly more than
        asked — correctness is unaffected (cold copies are current)."""
        cap = self.capacity
        live = state.table.occupied & (state.lanes[0] > 0)
        n_live = jnp.sum(live)
        big = jnp.iinfo(jnp.int32).max
        key = jnp.where(live, state.last_used, big)
        skey = jnp.sort(key)
        k = jnp.clip(n_live - keep, 0, cap - 1)
        thr = skey[jnp.maximum(k - 1, 0)]
        mask = live & (state.last_used <= thr) & (k > 0)
        return mask, jnp.sum(mask)

    def apply_evict(self, state: AggState, mask: jax.Array) -> AggState:
        """Reset evicted slots to init WITHOUT marking ckpt_dirty: the
        durable row (just flushed by this barrier's checkpoint) IS the
        cold copy — a dirty mark would overwrite it with zeros. Call only
        at a checkpoint barrier, AFTER the flush, BEFORE compact()."""
        init = self.init_state()
        lanes = tuple(
            jnp.where(mask, il, l) for l, il in zip(state.lanes, init.lanes))
        prev = tuple(
            jnp.where(mask, il, l)
            for l, il in zip(state.prev_lanes, init.lanes))
        return state.replace(lanes=lanes, prev_lanes=prev,
                             dirty=state.dirty & ~mask,
                             ckpt_dirty=state.ckpt_dirty & ~mask)

    def absorb(self, state: AggState, key_cols, stored_lanes, valid,
               str_ranks=None) -> AggState:
        """Fault evicted groups back in: merge each stored lane into the
        (possibly freshly re-created) slot with the lane's reduce op, and
        set prev_lanes to the stored value — the value downstream last saw
        — so the next flush emits an exact U-/U+ pair, not a duplicate
        insert. ``stored_lanes``: one array per lane, [n] rows aligned
        with ``key_cols``; ``valid``: bool[n]."""
        table, slots, _, ovf = ht_lookup_or_insert(
            state.table, key_cols, valid)
        idx = jnp.where(valid, slots, self.capacity)
        lanes = list(state.lanes)
        prev = list(state.prev_lanes)

        def merge(lane, stored, op, call=None):
            if call is not None and call.is_string_minmax:
                cur = call.pack_lane(lane, str_ranks)
                sv = call.pack_lane(stored, str_ranks)
                merged = cur.at[idx].min(sv, mode="drop") if op == "min" \
                    else cur.at[idx].max(sv, mode="drop")
                return call.unpack_lane(merged)
            if op == "add":
                return lane.at[idx].add(stored, mode="drop")
            if op == "min":
                return lane.at[idx].min(stored, mode="drop")
            return lane.at[idx].max(stored, mode="drop")

        lanes[0] = merge(lanes[0], stored_lanes[0], "add")
        prev[0] = prev[0].at[idx].set(stored_lanes[0], mode="drop")
        for call, ofs in zip(self.agg_calls, self.call_lane_ofs):
            for j, op in enumerate(call.reduce_ops()):
                lanes[ofs + j] = merge(lanes[ofs + j], stored_lanes[ofs + j],
                                       op, call)
                prev[ofs + j] = prev[ofs + j].at[idx].set(
                    stored_lanes[ofs + j], mode="drop")
        dirty = state.dirty.at[idx].set(True, mode="drop")
        ckpt_dirty = state.ckpt_dirty.at[idx].set(True, mode="drop")
        return state.replace(
            table=table, lanes=tuple(lanes), prev_lanes=tuple(prev),
            dirty=dirty, ckpt_dirty=ckpt_dirty,
            overflow=state.overflow | ovf)


def load_rows_into_state(core: AggCore, state: AggState, rows) -> AggState:
    """Recovery bulk-load: fold state-table rows (keys ++ raw lanes) into
    ``state`` in 1024-row batches. Shared by the solo executor reload
    (stream/hash_agg.py) and the sharded-fused re-shard loader
    (parallel/fused.py) so the durable row layout decodes in exactly one
    place. Callers fix up ``prev_lanes`` themselves (the recovered
    snapshot is the downstream baseline)."""
    import numpy as np

    rows = list(rows)
    nk = len(core.group_keys)
    bs = 1024
    for i in range(0, len(rows), bs):
        batch = rows[i:i + bs]
        n = len(batch)
        valid = jnp.arange(bs) < n
        key_cols = []
        for c in range(nk):
            vals = [r[c] for r in batch]
            mask = np.array([v is not None for v in vals]
                            + [False] * (bs - n))
            data = np.array(
                [v if v is not None else 0 for v in vals] + [0] * (bs - n),
                dtype=core.key_types[c].np_dtype)
            key_cols.append(Column(jnp.asarray(data), jnp.asarray(mask)))
        table, slots, _, ovf = ht_lookup_or_insert(
            state.table, key_cols, valid)
        if bool(ovf):
            raise RuntimeError(
                f"agg table overflow during recovery load (capacity "
                f"{core.capacity})")
        lanes = list(state.lanes)
        for j in range(len(lanes)):
            vals = np.array(
                [r[nk + j] for r in batch] + [0] * (bs - n),
                dtype=np.dtype(core.lane_dtypes[j]))
            lanes[j] = lanes[j].at[slots].set(jnp.asarray(vals),
                                              mode="drop")
        state = state.replace(table=table, lanes=tuple(lanes))
    return state
