"""Heterogeneous fused epochs — UNEQUAL jobs in minimal dispatches.

The tick-compiler's device layer (stream/tick_compiler.py is the
host-side scheduler over these builders). ops/fused_multi.py stacks
jobs whose traces are IDENTICAL — same exprs, same capacities, same
literals — so a realistic tenant mix of hundreds of small *dissimilar*
MVs still pays one dispatch each. Two new surfaces close that gap:

* **Padded shape-class supergroups** (``build_padded_group_epoch``):
  jobs whose epoch bodies share an operator SKELETON — same projection
  structure, same agg calls, same group keys — but differ in literal
  values (window widths…) or table capacities. The literals are lifted
  out of the trace as *parameter columns* (``hetero_agg_body`` appends
  one broadcast column per skeleton hole, bit-identical to
  ``Literal.eval``'s ``jnp.full``), each member's state is re-padded to
  the class-max capacity (``repad_agg_state``; open addressing means
  the padding changes slot LAYOUT, never per-key values), and one
  vmapped trace serves the whole bucket: K unequal jobs, one dispatch.

* **The jitted mega-epoch** (``build_mega_epoch``): jobs that share no
  skeleton at all. Their solo epoch bodies — the very
  ``agg_epoch_body`` closures ops/fused_epoch.py jits — are
  concatenated SEQUENTIALLY inside one compiled dispatch over a tuple
  of heterogeneous states. XLA runs them back-to-back with no host
  round-trip between: J unequal jobs, one launch, and
  ``build_mega_agg_probe`` keeps the barrier at one packed [J, 3]
  fetch.

Both surfaces extend the equal-group packed-stats layout with a third
slot (``n_live`` — the per-job live-group census) so the profiler can
attribute cost per job INSIDE a fused dispatch
(common/profiling.per_job_attribution). Registered in
``HETERO_EPOCH_BUILDERS`` so rwlint dispatch-discipline,
common/dispatch_count.py and the profiler cover them exactly like the
solo/sharded registries.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column
from ..common.profiling import profile_dispatch
from ..expr import Expr
from .fused_epoch import _donate, agg_epoch_body
from .grouped_agg import AggCore, AggState
from .hash_table import ht_lookup_or_insert, ht_new


# ---------------------------------------------------------------------------
# skeletonized epoch body — literal holes ride as data
# ---------------------------------------------------------------------------


def hetero_agg_body(chunk_fn: Callable, skel_exprs: Sequence[Expr], core,
                    rows_per_chunk: int) -> Callable:
    """``epoch(state, start, key, params, k) -> state``: the q5 agg body
    with the projection's literal holes supplied as data.

    ``skel_exprs`` reference hole ``h`` as ``InputRef(n_source_cols +
    h)``; ``params`` is a tuple of scalars (one per hole, already in
    physical dtype). Each scan iteration appends one broadcast column
    per hole to the generated chunk — ``jnp.full`` + all-ones mask,
    exactly ``Literal.eval``'s lowering — so a padded member computes
    bit-identically to its solo epoch with the literals inlined."""
    skel_exprs = tuple(skel_exprs)

    def epoch(state, start, key, params, k: int):
        def body(st, i):
            ch = chunk_fn(start + i * rows_per_chunk,
                          jax.random.fold_in(key, i))
            cap = ch.capacity
            ones = jnp.ones(cap, jnp.bool_)
            ch = ch.append_columns(tuple(
                Column(jnp.full(cap, p), ones) for p in params))
            projected = ch.with_columns(
                tuple(e.eval(ch) for e in skel_exprs))
            return core.apply_chunk(st, projected), None

        state, _ = jax.lax.scan(body, state,
                                jnp.arange(k, dtype=jnp.int64))
        return state

    return epoch


# ---------------------------------------------------------------------------
# tier 1: padded shape-class supergroup (one vmapped trace, K unequal jobs)
# ---------------------------------------------------------------------------


def build_padded_group_epoch(chunk_fn: Callable, skel_exprs: Sequence[Expr],
                             core, rows_per_chunk: int,
                             donate: bool = True) -> Callable:
    """The tick-compiler's shape-class epoch: ``epoch(stacked,
    starts[J], base_keys[J], batch_nos[J], params, k)`` — the
    skeletonized body vmapped over the job axis, per-job PRNG folding
    inside the jit (same contract as fused_multi.build_group_epoch).
    ``params``: tuple of [J] arrays, one per skeleton hole — job j's
    literal values ride down axis 0. common/dispatch_count.py counts
    this as ``build_padded_group_epoch.<locals>.padded_epoch``."""
    body = hetero_agg_body(chunk_fn, skel_exprs, core, rows_per_chunk)
    vm = jax.vmap(body, in_axes=(0, 0, 0, 0, None))

    def padded_epoch(stacked, starts, base_keys, batch_nos, params,
                     k: int):
        keys = jax.vmap(jax.random.fold_in)(base_keys, batch_nos)
        return vm(stacked, starts, keys, params, k)

    return profile_dispatch(
        jax.jit(padded_epoch, static_argnums=(5,),
                donate_argnums=_donate(donate)),
        padded_epoch.__qualname__)


def padded_agg_probe(core) -> Callable:
    """``probe(stacked) -> (packed [J, 3], rank [J, cap])`` — the
    supergroup's barrier probe, one dispatch / one fetch. Slot 2 is the
    per-job live-group census (the [J, *] packed-stats extension): the
    profiler's per-job cost weight inside the fused dispatch."""

    def probe_one(st):
        rank = core.flush_rank(st)
        n_live = jnp.sum(st.table.occupied
                         & (st.lanes[0] > 0)).astype(jnp.int32)
        packed = jnp.stack([rank[-1], st.overflow.astype(jnp.int32),
                            n_live])
        return packed, rank

    vm = jax.vmap(probe_one)

    def padded_probe(stacked):
        return vm(stacked)

    return profile_dispatch(jax.jit(padded_probe),
                            padded_probe.__qualname__)


# ---------------------------------------------------------------------------
# tier 2: the jitted mega-epoch (heterogeneous bodies, one dispatch)
# ---------------------------------------------------------------------------


def build_mega_epoch(specs: Sequence, donate: bool = True) -> Callable:
    """Concatenate J heterogeneous jobs' epochs into ONE compiled
    dispatch: ``mega_epoch(states, starts[J], base_keys[J],
    batch_nos[J], k) -> states`` where ``states`` is a TUPLE of
    per-job state pytrees (shapes may all differ — no stacking).

    Each ``spec`` is a stream/coschedule.FusedJobSpec; the bodies are
    built here from the same ``agg_epoch_body`` the solo registry jits,
    so job j's slice is bit-exact vs its solo fused epoch by
    construction. XLA sequences the bodies inside the launch — one
    dispatch, zero host round-trips between jobs. Only ``kind ==
    "agg"`` concatenates today (the join/session/q3 epochs return
    per-epoch emission tuples whose host drain is shape-specific);
    callers route other kinds to their solo/co-scheduled surfaces.
    common/dispatch_count.py counts this as
    ``build_mega_epoch.<locals>.mega_epoch``."""
    bodies = []
    for spec in specs:
        if spec.kind != "agg":
            raise NotImplementedError(
                f"mega-epoch concatenates agg-shaped jobs only "
                f"(got kind {spec.kind!r})")
        bodies.append(agg_epoch_body(spec.chunk_fn, spec.exprs,
                                     spec.core, spec.rows_per_chunk))

    def mega_epoch(states, starts, base_keys, batch_nos, k: int):
        out = []
        for j, body in enumerate(bodies):
            kj = jax.random.fold_in(base_keys[j], batch_nos[j])
            out.append(body(states[j], starts[j], kj, k))
        return tuple(out)

    return profile_dispatch(
        jax.jit(mega_epoch, static_argnums=(4,),
                donate_argnums=_donate(donate)),
        mega_epoch.__qualname__)


def build_mega_agg_probe(cores: Sequence) -> Callable:
    """``probe(states) -> (packed [J, 3], ranks tuple)`` — the whole
    mega-group's barrier probe in one dispatch and ONE packed fetch,
    even though every job's rank array keeps its own capacity (the
    ranks tuple is ragged; only the [J, 3] stats stack)."""

    def mega_probe(states):
        packed, ranks = [], []
        for core, st in zip(cores, states):
            rank = core.flush_rank(st)
            n_live = jnp.sum(st.table.occupied
                             & (st.lanes[0] > 0)).astype(jnp.int32)
            packed.append(jnp.stack([rank[-1],
                                     st.overflow.astype(jnp.int32),
                                     n_live]))
            ranks.append(rank)
        return jnp.stack(packed), tuple(ranks)

    return profile_dispatch(jax.jit(mega_probe), mega_probe.__qualname__)


def build_mega_agg_finish(cores: Sequence) -> Callable:
    """``finish(states) -> states`` — every job's flush finish in one
    dispatch (per-core ``finish_flush`` sequenced inside the jit)."""

    def mega_finish(states):
        return tuple(core.finish_flush(st)
                     for core, st in zip(cores, states))

    return profile_dispatch(jax.jit(mega_finish),
                            mega_finish.__qualname__)


def mega_agg_gathers(cores: Sequence) -> list:
    """Per-job jitted flush-window gathers for a mega-group. Gathers
    are per-job DATA (same as the equal-group path) so they stay
    per-job dispatches; jobs sharing a core config share the jit cache
    entry via identical shapes."""
    out = []
    for core in cores:
        def gather(st, rank, lo, core=core):
            return core.gather_flush_chunk(st, rank, lo)
        gather.__qualname__ = "mega_agg_gathers.<locals>.gather"
        out.append(profile_dispatch(jax.jit(gather), gather.__qualname__))
    return out


# ---------------------------------------------------------------------------
# state re-padding (class-max capacity)
# ---------------------------------------------------------------------------


def repad_agg_state(core: AggCore, state: AggState, new_capacity: int,
                    out_capacity: int = None) -> tuple:
    """Grow an AggState to ``new_capacity`` slots: ``(class_core,
    padded_state)``. Eager/unjitted — this runs at DDL time only (the
    tick compiler's restack), never per tick. ``out_capacity``
    overrides the class core's flush-chunk width (state arrays do not
    depend on it; only gather windowing does).

    Every OCCUPIED slot moves — not just live groups: a group whose
    row count hit zero but whose delete is still ``ckpt_dirty`` must
    survive the move or the next checkpoint would miss the durable
    delete (compare ``AggCore.compact``, which intentionally keeps live
    rows only because it runs AFTER the checkpoint). Open addressing
    re-hashes every key into the larger table, so the slot LAYOUT
    changes but per-key lane values do not — flush chunks may order
    groups differently than the unpadded state, while each group's
    emitted values stay bit-exact."""
    if new_capacity < core.capacity:
        raise ValueError(
            f"repad shrinks {core.capacity} -> {new_capacity}")
    class_core = AggCore(core.key_types, core.group_keys, core.agg_calls,
                         new_capacity,
                         core.out_capacity if out_capacity is None
                         else out_capacity)
    if new_capacity == core.capacity:
        return class_core, state
    occ = state.table.occupied
    key_cols = [Column(kd, km) for kd, km in
                zip(state.table.key_data, state.table.key_mask)]
    ht, slots, _, rebuild_ovf = ht_lookup_or_insert(
        ht_new(core.key_types, new_capacity), key_cols, occ)
    dst = jnp.where(occ, slots, new_capacity)
    init = class_core.init_state()

    def move(arr, init_arr):
        return init_arr.at[dst].set(arr, mode="drop")

    return class_core, AggState(
        table=ht,
        lanes=tuple(move(l, il)
                    for l, il in zip(state.lanes, init.lanes)),
        prev_lanes=tuple(move(l, il)
                         for l, il in zip(state.prev_lanes, init.lanes)),
        dirty=move(state.dirty, init.dirty),
        ckpt_dirty=move(state.ckpt_dirty, init.ckpt_dirty),
        overflow=state.overflow | rebuild_ovf,
        last_used=move(state.last_used, init.last_used),
    )


#: builder registry — same contract as ops/fused_epoch.EPOCH_BUILDERS:
#: rwlint dispatch-discipline parses this dict literal statically and
#: walks each builder's closure; tests/test_registry_coverage.py
#: cross-checks the parse against this runtime dict and drives every
#: surface under count_dispatches + the profiler.
HETERO_EPOCH_BUILDERS = {
    "padded_agg": build_padded_group_epoch,   # tier 1: shape-class vmap
    "mega_agg": build_mega_epoch,             # tier 2: concatenated bodies
}
