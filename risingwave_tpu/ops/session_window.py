"""Session-gap window core — the NEXmark q8 shape as device-resident state.

q8 monitors user activity: events are grouped per key (bidder/seller)
into *sessions* — maximal runs of events where consecutive gaps stay
within ``gap_us`` — and a session row (key, start, end, n_events) is
emitted once the session CLOSES (a later event opens a new session, or
the watermark passes ``last_ts + gap``). Unlike tumble/hop windows the
window boundaries are data-dependent, so there is no static window id to
bucket by; instead the state is a hash table keyed by the session key
(ops/hash_table.py — the same open-addressing table AggCore uses) with
three lanes per key (open-session start / last event time / count) plus
a fixed-capacity **closed-session buffer** that accumulates emissions
between barriers.

Vectorization of the data-dependent part (reference capability:
src/expr/src/window_function/session.rs — per-partition scans; here one
chunk is segmented wholesale): rows are sorted by (key-slot, ts) — two
stable argsorts, the interval-join lane-assignment trick — and a
*segment* starts where the key changes or the within-chunk gap exceeds
``gap_us``. Segment aggregates fall out of prefix-max/count arithmetic
in sorted space; sessions close where a segment ends but its key-run
continues (a later same-key segment exists), where a key-run's first
segment does not extend the stored open session, and at flush time for
open sessions the watermark has passed. All closures append to the
closed buffer via rank-scatters; the barrier flush snapshots the buffer
and clears it.

Assumptions (enforced with sticky flags, the IntervalJoinCore idiom):

* append-only input (a delete sets ``saw_delete``; sessions cannot
  un-happen),
* per-key event time non-decreasing ACROSS chunks (the NEXmark clock is
  globally monotone; within a chunk any order is handled by the sort; a
  cross-chunk violation sets ``out_of_order`` instead of silently
  rewinding a session),
* the closed buffer outlasts one epoch's closures (``closed_overflow``
  trips otherwise — size it to the epoch's expected closure count),
* hash-table capacity bounds distinct keys ever seen (``overflow``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_INSERT, OP_UPDATE_INSERT, Column, StreamChunk,
)
from ..common.types import Field, INT64, Schema, TIMESTAMP
from .hash_table import DeviceHashTable, ht_lookup_or_insert, ht_new

_NONE = jnp.int64(-1)


@struct.dataclass
class SessionWindowState:
    table: DeviceHashTable
    sess_start: jax.Array      # int64[cap]: open session start; -1 = none
    last_ts: jax.Array         # int64[cap]: open session's last event time
    count: jax.Array           # int64[cap]: open session's event count
    closed_key: jax.Array      # int64[ccap]: closed-session buffer
    closed_start: jax.Array    # int64[ccap]
    closed_end: jax.Array      # int64[ccap]
    closed_cnt: jax.Array      # int64[ccap]
    closed_fill: jax.Array     # int32 scalar: buffer occupancy
    overflow: jax.Array        # bool scalar, sticky: key table full
    closed_overflow: jax.Array  # bool scalar, sticky: buffer full
    saw_delete: jax.Array      # bool scalar, sticky: non-insert input row
    out_of_order: jax.Array    # bool scalar, sticky: per-key time rewind


class SessionWindowCore:
    """Static config + pure steps for one session-window operator.

    ``key_col``/``ts_col``: input columns (key must be an int64 type —
    the q8 ids); ``gap_us``: the session gap. Output schema:
    (key, session_start, session_end, n_events)."""

    def __init__(self, in_schema: Schema, key_col: int, ts_col: int,
                 gap_us: int, capacity: int = 1 << 16,
                 closed_capacity: int = 1 << 16):
        if gap_us <= 0:
            raise ValueError("gap_us must be positive")
        if capacity & (capacity - 1):
            raise ValueError("capacity must be a power of two")
        self.in_schema = in_schema
        self.key_col = key_col
        self.ts_col = ts_col
        self.key_type = in_schema[key_col].type
        self.gap_us = int(gap_us)
        self.capacity = int(capacity)
        self.closed_capacity = int(closed_capacity)
        self.out_schema = Schema((
            Field(in_schema[key_col].name, self.key_type),
            Field("session_start", TIMESTAMP),
            Field("session_end", TIMESTAMP),
            Field("n_events", INT64),
        ))

    # -- state ----------------------------------------------------------------

    def init_state(self) -> SessionWindowState:
        cap, ccap = self.capacity, self.closed_capacity
        return SessionWindowState(
            table=ht_new((self.key_type,), cap),
            sess_start=jnp.full(cap, _NONE, jnp.int64),
            last_ts=jnp.zeros(cap, jnp.int64),
            count=jnp.zeros(cap, jnp.int64),
            closed_key=jnp.zeros(ccap, jnp.int64),
            closed_start=jnp.zeros(ccap, jnp.int64),
            closed_end=jnp.zeros(ccap, jnp.int64),
            closed_cnt=jnp.zeros(ccap, jnp.int64),
            closed_fill=jnp.zeros((), jnp.int32),
            overflow=jnp.zeros((), jnp.bool_),
            closed_overflow=jnp.zeros((), jnp.bool_),
            saw_delete=jnp.zeros((), jnp.bool_),
            out_of_order=jnp.zeros((), jnp.bool_),
        )

    # -- chunk step ------------------------------------------------------------

    def apply_chunk(self, state: SessionWindowState,
                    chunk: StreamChunk) -> SessionWindowState:
        cap, ccap = self.capacity, self.closed_capacity
        N = chunk.capacity
        key = chunk.columns[self.key_col]
        ts = chunk.columns[self.ts_col]
        is_ins = (chunk.ops == OP_INSERT) | (chunk.ops == OP_UPDATE_INSERT)
        saw_delete = state.saw_delete | jnp.any(chunk.vis & ~is_ins)
        valid = chunk.vis & is_ins & key.mask & ts.mask
        table, slots, _, ovf = ht_lookup_or_insert(state.table, [key], valid)
        t64 = ts.data.astype(jnp.int64)

        # ---- sort rows by (slot, ts): valid rows first, grouped per key,
        # time-ascending inside the group (two stable argsorts — the
        # interval-join lane-assignment idiom)
        sort_slot = jnp.where(valid, slots, cap).astype(jnp.int32)
        o1 = jnp.argsort(t64, stable=True)
        perm = o1[jnp.argsort(sort_slot[o1], stable=True)]
        s = sort_slot[perm]
        t = t64[perm]
        v = valid[perm]
        kv = key.data.astype(jnp.int64)[perm]
        idx = jnp.arange(N, dtype=jnp.int32)

        run_start = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), s[1:] != s[:-1]])
        t_prev = jnp.concatenate([t[:1], t[:-1]])

        safe_s = jnp.clip(s, 0, cap - 1)
        st_start = state.sess_start[safe_s]
        st_last = state.last_ts[safe_s]
        st_cnt = state.count[safe_s]
        has_open = st_start >= 0

        # segment = maximal gap-free run of one key inside this chunk
        seg_flag = v & (run_start | (t - t_prev > self.gap_us))
        continues = run_start & v & has_open & (t - st_last <= self.gap_us)
        # per-key time rewind across chunks: flagged sticky (the chunk is
        # still folded in; downstream decides whether to trust sessions)
        out_of_order = state.out_of_order | jnp.any(
            v & run_start & has_open & (t < st_last))
        seg_start_idx = jax.lax.cummax(jnp.where(seg_flag, idx, 0))
        seg_first_ts = t[seg_start_idx]
        seg_cnt = (idx - seg_start_idx + 1).astype(jnp.int64)
        # does THIS row's segment extend the stored open session? (only a
        # run's first segment can)
        seg_cont = run_start[seg_start_idx] & continues[seg_start_idx]

        nxt_v = jnp.concatenate([v[1:], jnp.zeros(1, jnp.bool_)])
        nxt_seg = jnp.concatenate([seg_flag[1:], jnp.zeros(1, jnp.bool_)])
        nxt_s = jnp.concatenate([s[1:], jnp.full(1, cap, jnp.int32)])
        seg_last = v & (~nxt_v | nxt_seg | (nxt_s != s))
        run_last = v & (~nxt_v | (nxt_s != s))

        # ---- closures: (a) the stored open session, superseded by a
        # non-extending first segment; (b) every segment followed by a
        # later same-key segment (its session can never extend again)
        close_state = v & run_start & has_open & ~continues
        close_seg = seg_last & ~run_last
        cs_start = jnp.where(seg_cont, st_start, seg_first_ts)
        cs_cnt = jnp.where(seg_cont, st_cnt + seg_cnt, seg_cnt)

        na = jnp.sum(close_state)
        ra = jnp.cumsum(close_state) - 1
        rb = na + jnp.cumsum(close_seg) - 1
        posa = jnp.where(close_state, state.closed_fill + ra, ccap)
        posb = jnp.where(close_seg, state.closed_fill + rb, ccap)

        def put(buf, va, vb):
            return buf.at[posa].set(va, mode="drop").at[posb].set(
                vb, mode="drop")

        closed_key = put(state.closed_key, kv, kv)
        closed_start = put(state.closed_start, st_start, cs_start)
        closed_end = put(state.closed_end, st_last, t)
        closed_cnt = put(state.closed_cnt, st_cnt, cs_cnt)
        n_new = na + jnp.sum(close_seg)
        closed_overflow = state.closed_overflow | (
            state.closed_fill + n_new > ccap)
        closed_fill = jnp.minimum(
            state.closed_fill + n_new, ccap).astype(jnp.int32)

        # ---- open-session update: the run's LAST segment stays open
        tgt = jnp.where(run_last, s, cap)
        sess_start = state.sess_start.at[tgt].set(
            jnp.where(seg_cont, st_start, seg_first_ts), mode="drop")
        last_ts = state.last_ts.at[tgt].set(t, mode="drop")
        count = state.count.at[tgt].set(
            jnp.where(seg_cont, st_cnt + seg_cnt, seg_cnt), mode="drop")

        return state.replace(
            table=table, sess_start=sess_start, last_ts=last_ts,
            count=count, closed_key=closed_key, closed_start=closed_start,
            closed_end=closed_end, closed_cnt=closed_cnt,
            closed_fill=closed_fill, overflow=state.overflow | ovf,
            closed_overflow=closed_overflow, saw_delete=saw_delete,
            out_of_order=out_of_order,
        )

    # -- barrier flush ---------------------------------------------------------

    def flush_plan(self, state: SessionWindowState, watermark):
        """Close open sessions the watermark has passed (``last_ts + gap
        <= watermark``) into the buffer. Returns (state, packed
        [n_closed, overflow, closed_overflow, saw_delete,
        out_of_order]) — ONE scalar fetch covers the emission count and
        every sticky flag."""
        cap, ccap = self.capacity, self.closed_capacity
        wm = jnp.asarray(watermark, jnp.int64)
        openm = (state.table.occupied & (state.sess_start >= 0)
                 & (state.last_ts + self.gap_us <= wm))
        rank = jnp.cumsum(openm) - 1
        pos = jnp.where(openm, state.closed_fill + rank, ccap)
        kv = state.table.key_data[0].astype(jnp.int64)
        closed_key = state.closed_key.at[pos].set(kv, mode="drop")
        closed_start = state.closed_start.at[pos].set(
            state.sess_start, mode="drop")
        closed_end = state.closed_end.at[pos].set(state.last_ts, mode="drop")
        closed_cnt = state.closed_cnt.at[pos].set(state.count, mode="drop")
        n = jnp.sum(openm)
        closed_overflow = state.closed_overflow | (
            state.closed_fill + n > ccap)
        closed_fill = jnp.minimum(state.closed_fill + n, ccap).astype(
            jnp.int32)
        state = state.replace(
            sess_start=jnp.where(openm, _NONE, state.sess_start),
            count=jnp.where(openm, 0, state.count),
            closed_key=closed_key, closed_start=closed_start,
            closed_end=closed_end, closed_cnt=closed_cnt,
            closed_fill=closed_fill, closed_overflow=closed_overflow,
        )
        packed = jnp.stack([
            closed_fill.astype(jnp.int64),
            state.overflow.astype(jnp.int64),
            closed_overflow.astype(jnp.int64),
            state.saw_delete.astype(jnp.int64),
            state.out_of_order.astype(jnp.int64),
        ])
        return state, packed

    def snapshot_closed(self, state: SessionWindowState):
        """The epoch's emission payload (buffer arrays; fused epochs
        return this, then ``finish_flush`` clears the buffer)."""
        return (state.closed_key, state.closed_start,
                state.closed_end, state.closed_cnt)

    def finish_flush(self, state: SessionWindowState) -> SessionWindowState:
        return state.replace(closed_fill=jnp.zeros((), jnp.int32))

    def gather_closed(self, snapshot, n_closed, lo,
                      out_capacity: int) -> StreamChunk:
        """Closed sessions with buffer rank in [lo, lo+out_capacity) as
        one INSERT chunk (session outputs are append-only — a session
        closes exactly once). Pure + shape-static; drive as
        ``for lo in range(0, n_closed, out_capacity)``."""
        ck, cs, ce, cn = snapshot
        ccap = ck.shape[0]
        j = lo + jnp.arange(out_capacity, dtype=jnp.int64)
        vis = j < jnp.asarray(n_closed, jnp.int64)
        src = jnp.clip(j, 0, ccap - 1).astype(jnp.int32)
        cols = (
            Column(ck[src].astype(self.key_type.dtype), vis),
            Column(cs[src], vis),
            Column(ce[src], vis),
            Column(cn[src], vis),
        )
        return StreamChunk(jnp.zeros(out_capacity, jnp.int8), vis, cols)

    # -- checkpoint / recovery -------------------------------------------------

    def export_host(self, state: SessionWindowState) -> dict:
        import numpy as np
        host = jax.device_get(state)
        out = {f: np.asarray(getattr(host, f)) for f in (
            "sess_start", "last_ts", "count", "closed_key", "closed_start",
            "closed_end", "closed_cnt", "closed_fill", "overflow",
            "closed_overflow", "saw_delete", "out_of_order")}
        out["table_key_data"] = [np.asarray(a) for a in host.table.key_data]
        out["table_key_mask"] = [np.asarray(a) for a in host.table.key_mask]
        out["table_occupied"] = np.asarray(host.table.occupied)
        return out

    def import_host(self, payload: dict) -> SessionWindowState:
        return SessionWindowState(
            table=DeviceHashTable(
                key_data=tuple(jnp.asarray(a)
                               for a in payload["table_key_data"]),
                key_mask=tuple(jnp.asarray(a)
                               for a in payload["table_key_mask"]),
                occupied=jnp.asarray(payload["table_occupied"])),
            **{f: jnp.asarray(payload[f]) for f in (
                "sess_start", "last_ts", "count", "closed_key",
                "closed_start", "closed_end", "closed_cnt", "closed_fill",
                "overflow", "closed_overflow", "saw_delete",
                "out_of_order")},
        )
