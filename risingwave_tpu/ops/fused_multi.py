"""Multi-job fused epochs — MANY MVs' epochs in ONE XLA dispatch.

The co-scheduling layer of the dispatch ladder (docs/performance.md):
PR 4 collapsed one pipeline's epoch into a single ``lax.scan`` dispatch;
when hundreds of small MVs tick together (the "heavy traffic from
millions of users" shape — SURVEY §2.9 pipeline scaling) each job still
paid its own dispatch, so per-tick overhead grew linearly with job
count. Here compatible jobs' states are STACKED under a leading job
axis ``[J, ...]`` and the *same epoch body* the solo path jits
(ops/fused_epoch.agg_epoch_body / join_epoch_body) is ``vmap``-ed over
that axis inside one jit: K jobs tick in exactly one dispatch, and —
because vmap batches each primitive without changing its per-slice
semantics — job j's slice of the stacked state is bit-identical to what
the solo fused epoch would have produced (tests/test_coschedule.py pins
this, including across a checkpoint export/import cycle).

Grouping contract (enforced by stream/coschedule.py): jobs stack only
when their traced computation is identical — same core config, same
projection exprs, same chunk_fn family and rows_per_chunk. Per-job
variation rides as DATA: start-event cursors ``starts[J]`` and PRNG
keys ``keys[J]``. Anything else (different window literals, different
agg calls) is a different trace → a different group (or solo fallback).

Barrier work stays O(1) dispatches in J too: ``multi_agg_probe`` /
``multi_agg_finish`` vmap the probe/finish steps, so the whole group's
packed stats arrive in ONE [J, 3] fetch. Only the per-job output
gathers remain per-job — they ARE per-job data — and
``gather_job_flush_chunk`` traces the job index, so one compiled gather
serves every job.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..common.profiling import profile_dispatch
from ..expr import Expr
from .fused_epoch import _donate, agg_epoch_body, join_epoch_body


# -- job-axis state layout ---------------------------------------------------


def stack_states(states: Sequence):
    """Per-job state pytrees → ONE stacked pytree with a leading [J]
    axis on every leaf (the co-scheduler's resident layout)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def index_state(stacked, j):
    """Job ``j``'s slice of a stacked pytree — the solo-shaped state, as
    device views (bit-exact vs the solo path; used for per-job export,
    checkpoint and group-membership changes)."""
    return jax.tree_util.tree_map(lambda x: x[j], stacked)


def unstack_states(stacked, n_jobs: int):
    return [index_state(stacked, j) for j in range(n_jobs)]


def append_state(stacked, state):
    """Grow the job axis by one (new group member)."""
    return jax.tree_util.tree_map(
        lambda xs, x: jnp.concatenate([xs, x[None]]), stacked, state)


def remove_state(stacked, j: int):
    """Drop job ``j`` from the job axis (DROP MATERIALIZED VIEW)."""
    def rm(x):
        return jnp.concatenate([x[:j], x[j + 1:]])
    return jax.tree_util.tree_map(rm, stacked)


# -- multi-job epochs ---------------------------------------------------------


def fused_multi_agg_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                          core, rows_per_chunk: int,
                          donate: bool = True) -> Callable:
    """Build ``epoch(stacked_state, starts[J], keys[J], k) ->
    stacked_state``: K source+agg jobs' epochs in ONE dispatch. The body
    is the solo epoch body vmapped over the job axis."""
    body = agg_epoch_body(chunk_fn, exprs, core, rows_per_chunk)
    vm = jax.vmap(body, in_axes=(0, 0, 0, None))

    def epoch(stacked, starts, keys, k: int):
        return vm(stacked, starts, keys, k)

    return profile_dispatch(jax.jit(epoch, static_argnums=(3,),
                                    donate_argnums=_donate(donate)),
                            epoch.__qualname__)


def fused_multi_join_epoch(chunk_fn: Callable, exprs: Sequence[Expr],
                           core, rows_per_chunk: int,
                           donate: bool = True) -> Callable:
    """Build ``epoch(stacked_state, starts[J], keys[J], k)`` for K
    source+join jobs (ops/interval_join.IntervalJoinCore): one dispatch
    runs every job's ingest AND its barrier flush plan. Returns the
    solo epoch's tuple with a leading [J] axis on every element —
    ``packed`` becomes [J, 5], so ONE scalar fetch covers the whole
    group's flags and emission counts."""
    body = join_epoch_body(chunk_fn, exprs, core, rows_per_chunk)
    vm = jax.vmap(body, in_axes=(0, 0, 0, None))

    def epoch(stacked, starts, keys, k: int):
        return vm(stacked, starts, keys, k)

    return profile_dispatch(jax.jit(epoch, static_argnums=(3,),
                                    donate_argnums=_donate(donate)),
                            epoch.__qualname__)


def build_group_epoch(kind: str, chunk_fn: Callable, exprs: Sequence[Expr],
                      core, rows_per_chunk: int, donate: bool = True):
    """The co-scheduler's production epoch (stream/coschedule.CoGroup):
    per-job PRNG-key folding + the vmapped solo body in ONE jit, so the
    fold costs zero extra dispatches and stays bit-identical to the solo
    path's host-side ``jax.random.fold_in``. Signature:
    ``epoch(stacked, starts[J], base_keys[J], batch_nos[J], k)``.
    common/dispatch_count.py counts this as
    ``build_group_epoch.<locals>.coscheduled_epoch``. The explicit-keys
    builders above are the unfolded primitives (parity tests drive them
    with host-folded keys); all share the same epoch bodies."""
    body = (agg_epoch_body if kind == "agg" else join_epoch_body)(
        chunk_fn, exprs, core, rows_per_chunk)
    vm = jax.vmap(body, in_axes=(0, 0, 0, None))

    def coscheduled_epoch(stacked, starts, base_keys, batch_nos, k: int):
        keys = jax.vmap(jax.random.fold_in)(base_keys, batch_nos)
        return vm(stacked, starts, keys, k)

    return profile_dispatch(
        jax.jit(coscheduled_epoch, static_argnums=(4,),
                donate_argnums=_donate(donate)),
        coscheduled_epoch.__qualname__)


# -- group barrier steps (agg shape) ------------------------------------------


def multi_agg_probe(core) -> Callable:
    """``probe(stacked) -> (packed [J, 3], rank [J, cap])`` — the whole
    group's barrier probe in one dispatch / one fetch."""

    def probe_one(st):
        rank = core.flush_rank(st)
        packed = jnp.stack([rank[-1],
                            st.overflow.astype(jnp.int32),
                            jnp.zeros((), jnp.int32)])
        return packed, rank

    vm = jax.vmap(probe_one)

    def probe(stacked):
        return vm(stacked)

    return profile_dispatch(jax.jit(probe), probe.__qualname__)


def multi_agg_finish(core) -> Callable:
    """``finish(stacked) -> stacked`` — every job's flush finish in one
    dispatch."""
    vm = jax.vmap(core.finish_flush)

    def finish(stacked):
        return vm(stacked)

    return profile_dispatch(jax.jit(finish), finish.__qualname__)


def gather_job_flush_chunk(core) -> Callable:
    """``gather(stacked, ranks, j, lo) -> StreamChunk`` — job ``j``'s
    flush window [lo, lo+G). ``j`` is traced, so ONE compiled function
    serves every job in the group."""

    def gather(stacked, ranks, j, lo):
        st = index_state(stacked, j)
        return core.gather_flush_chunk(st, ranks[j], lo)

    return profile_dispatch(jax.jit(gather), gather.__qualname__)
