"""TopN ranking over a device row set: full-sort membership computation.

The reference maintains TopN incrementally through a 3-segment cache over a
state table (src/stream/src/executor/top_n/top_n_cache.rs:43 — low/middle/
high segments, per-row cache walks). The TPU-native design instead recomputes
the rank window *wholesale* at flush time: one lexicographic sort of all
slots (XLA sorts are fast and fusible; there is no pointer-chasing win on a
vector machine), then a vectorized per-group rank and a membership mask.
Correct under arbitrary insert/delete churn because membership is derived
from the full row set every flush, not patched incrementally.

``OrderSpec``: (column index, desc, nulls_last) per sort key — the order-by
clause (reference: PG ORDER BY semantics, defaults nulls last for ASC,
nulls first for DESC).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column
from .row_set import RowSetState


@dataclasses.dataclass(frozen=True)
class OrderSpec:
    col: int
    desc: bool = False
    nulls_last: bool = True
    # VARCHAR/BYTEA columns: physical data is insertion-ordered dictionary
    # ids, so the sort key is the id's lexicographic rank looked up in the
    # dictionary's rank table (``str_ranks``), never the raw id. Executors
    # set this from the column's logical type.
    is_string: bool = False


def _sort_key(c: Column, spec: OrderSpec,
              str_ranks: jax.Array | None = None) -> jax.Array:
    """Column → ascending-sortable f64/i64 key honoring desc/nulls order.

    int64 keys stay int64 (exact); everything else lowers to float64
    (float32/bool/int32 fit exactly)."""
    d = c.data
    if spec.is_string:
        if str_ranks is None:
            raise ValueError(
                "ordering on a VARCHAR column requires the dictionary rank "
                "table (str_ranks)")
        d = str_ranks[jnp.clip(d.astype(jnp.int32), 0,
                               str_ranks.shape[0] - 1)]
    if d.dtype == jnp.int64:
        k = d
        big = jnp.iinfo(jnp.int64).max
        small = jnp.iinfo(jnp.int64).min
    else:
        k = d.astype(jnp.float64)
        big = jnp.inf
        small = -jnp.inf
    if spec.desc:
        k = -k
    # nulls position is relative to the *output* order; after desc negation
    # the key is ascending, so nulls_last => +big, nulls_first => small
    null_sent = big if spec.nulls_last else small
    return jnp.where(c.mask, k, null_sent)


def topn_order(state: RowSetState, gid: jax.Array,
               order: Sequence[OrderSpec],
               str_ranks: jax.Array | None = None) -> jax.Array:
    """Stable lexicographic permutation: (live-first is NOT applied here;
    dead slots are routed to the end via gid), gid, then order keys, then
    slot index (total order tiebreak via stable sort)."""
    cap = state.live.shape[0]
    dead_gid = jnp.iinfo(jnp.int64).max
    gid_eff = jnp.where(state.live, gid.astype(jnp.int64), dead_gid)
    perm = jnp.arange(cap, dtype=jnp.int32)
    for spec in reversed(list(order)):
        key = _sort_key(state.cols[spec.col], spec, str_ranks)
        perm = perm[jnp.argsort(key[perm], stable=True)]
    perm = perm[jnp.argsort(gid_eff[perm], stable=True)]
    return perm


def _key_sentinels(dtype):
    if jnp.dtype(dtype) == jnp.dtype(jnp.int64):
        return (jnp.asarray(jnp.iinfo(jnp.int64).max, jnp.int64),
                jnp.asarray(jnp.iinfo(jnp.int64).min, jnp.int64))
    return (jnp.asarray(jnp.inf, jnp.float64),
            jnp.asarray(-jnp.inf, jnp.float64))


def key0_dtype(state: RowSetState, spec: OrderSpec):
    """Dtype of the leading sort key (threshold scalar storage)."""
    if spec.is_string:
        return jnp.int64          # rank-table keys are int64
    return (jnp.int64 if state.cols[spec.col].data.dtype == jnp.int64
            else jnp.float64)


def topn_candidate_flush(
    state: RowSetState,
    order: Sequence[OrderSpec],
    offset: int,
    limit: int,
    cand: jax.Array,          # bool[cap] candidate slots
    cand_cap: int,            # compact buffer size (static)
    cand_keep: int,           # candidates retained after shrink
    t1: jax.Array,            # scalar: best leading key among forgotten rows
    str_ranks: jax.Array | None = None,
):
    """Incremental TopN flush (plain TopN fast path): sort only the
    candidate subset, O(cand_cap log cand_cap) instead of a full-capacity
    sort — the TPU analogue of the reference's low/middle/high TopNCache
    (top_n_cache.rs:43): candidates ≈ low+middle segments, the full row set
    ≈ the high segment re-read on a miss.

    Correctness gate: rows dropped from the candidate set ("forgotten")
    are remembered only through ``t1`` — the best (ascending-sort) leading
    key ever dropped. The result is valid only when the window's worst
    leading key stays strictly below ``t1``; otherwise the caller must run
    the full-sort refill. Returns
    ``(in_set, new_cand, new_t1, bad)`` — ``bad`` = overflow / underflow /
    threshold breach, conservatively forcing a refill."""
    cap = state.live.shape[0]
    spec0 = order[0]
    big0, small0 = _key_sentinels(key0_dtype(state, spec0))

    cidx = jnp.nonzero(cand, size=cand_cap, fill_value=cap)[0].astype(jnp.int32)
    valid = cidx < cap
    safe = jnp.clip(cidx, 0, cap - 1)
    live_m = valid & state.live[safe]

    perm = jnp.arange(cand_cap, dtype=jnp.int32)
    for spec in reversed(list(order)):
        keyf = _sort_key(state.cols[spec.col], spec, str_ranks)
        big, _ = _key_sentinels(keyf.dtype)
        keym = jnp.where(valid, keyf[safe], big)
        perm = perm[jnp.argsort(keym[perm], stable=True)]
    # dead/filler last (stable => key order preserved within live)
    perm = perm[jnp.argsort(~live_m[perm], stable=True)]

    rank = jnp.arange(cand_cap, dtype=jnp.int64)
    live_sorted = live_m[perm]
    in_win_sorted = live_sorted & (rank >= offset) & (rank < offset + limit)
    keep_sorted = live_sorted & (rank < cand_keep)

    n_cand = jnp.sum(cand)
    n_live_cand = jnp.sum(live_m)
    n_live = jnp.sum(state.live)
    overflow = n_cand > cand_cap
    underflow = (n_live_cand < offset + limit) & (n_live > n_live_cand)

    key0_full = _sort_key(state.cols[spec0.col], spec0,
                          str_ranks).astype(big0.dtype)
    key0_sorted = jnp.where(valid, key0_full[safe], big0)[perm]
    nwin = jnp.minimum(offset + limit, n_live_cand)
    worst_win = jnp.where(
        nwin > 0, key0_sorted[jnp.clip(nwin - 1, 0, cand_cap - 1)], small0)
    stale = worst_win >= t1
    drop_exists = n_live_cand > cand_keep
    drop_key = key0_sorted[jnp.clip(cand_keep, 0, cand_cap - 1)]
    new_t1 = jnp.where(drop_exists, jnp.minimum(t1, drop_key), t1)
    bad = overflow | underflow | stale

    in_win_orig = jnp.zeros(cand_cap, jnp.bool_).at[perm].set(in_win_sorted)
    keep_orig = jnp.zeros(cand_cap, jnp.bool_).at[perm].set(keep_sorted)
    tgt = jnp.where(valid, cidx, cap)
    in_set = jnp.zeros(cap, jnp.bool_).at[tgt].set(in_win_orig, mode="drop")
    new_cand = jnp.zeros(cap, jnp.bool_).at[tgt].set(keep_orig, mode="drop")
    return in_set, new_cand, new_t1, bad


def topn_refill(
    state: RowSetState,
    gid: jax.Array,
    order: Sequence[OrderSpec],
    offset: int,
    limit: int,
    cand_keep: int,
    str_ranks: jax.Array | None = None,
):
    """Full-sort recompute + candidate reseed: one permutation yields the
    rank window, the new candidate set (global top-``cand_keep``), and the
    forget threshold (leading key of the first dropped row)."""
    cap = state.live.shape[0]
    spec0 = order[0]
    big0, _ = _key_sentinels(key0_dtype(state, spec0))
    perm = topn_order(state, gid, order, str_ranks)
    live_sorted = state.live[perm]
    # dead slots were routed last by topn_order's gid pass (gid=0 for plain)
    rank = jnp.arange(cap, dtype=jnp.int64)
    in_win_sorted = live_sorted & (rank >= offset) & (rank < offset + limit)
    keep_sorted = live_sorted & (rank < cand_keep)
    key0 = _sort_key(state.cols[spec0.col], spec0,
                     str_ranks).astype(big0.dtype)[perm]
    n_live = jnp.sum(state.live)
    t1 = jnp.where(n_live > cand_keep,
                   key0[jnp.clip(cand_keep, 0, cap - 1)], big0)
    in_set = jnp.zeros(cap, jnp.bool_).at[perm].set(in_win_sorted)
    cand = jnp.zeros(cap, jnp.bool_).at[perm].set(keep_sorted)
    return in_set, cand, t1


def topn_in_set(
    state: RowSetState,
    gid: jax.Array,
    order: Sequence[OrderSpec],
    offset: int,
    limit: int,
    with_ties: bool = False,
    n_tie_keys: int | None = None,
    str_ranks: jax.Array | None = None,
) -> jax.Array:
    """bool[cap]: slot is in its group's [offset, offset+limit) rank window
    (plus ties with the window's last row when ``with_ties``).

    ``n_tie_keys``: how many leading order keys define a WITH TIES tie —
    callers append pk tiebreak keys to ``order`` for deterministic totality,
    and those must NOT participate in tie equality (default: all keys)."""
    cap = state.live.shape[0]
    perm = topn_order(state, gid, order, str_ranks)
    dead_gid = jnp.iinfo(jnp.int64).max
    gid_eff = jnp.where(state.live, gid.astype(jnp.int64), dead_gid)
    sgid = gid_eff[perm]
    pos = jnp.arange(cap, dtype=jnp.int64)
    is_start = jnp.concatenate([
        jnp.ones(1, jnp.bool_), sgid[1:] != sgid[:-1]])
    start = jax.lax.cummax(jnp.where(is_start, pos, 0))
    rank = pos - start
    slive = state.live[perm]
    in_win = slive & (rank >= offset) & (rank < offset + limit)
    if with_ties:
        # rows past the window tie-in if their sort key equals the key of the
        # window's last row (rank offset+limit-1) in the same group
        bpos = jnp.clip(start + offset + limit - 1, 0, cap - 1)
        tie = slive & (rank >= offset + limit) & (sgid == sgid[bpos])
        tie_specs = list(order)[: (len(order) if n_tie_keys is None
                                   else n_tie_keys)]
        for spec in tie_specs:
            key = _sort_key(state.cols[spec.col], spec, str_ranks)[perm]
            tie = tie & (key == key[bpos])
        in_win = in_win | tie
    return jnp.zeros(cap, jnp.bool_).at[perm].set(in_win)
