"""TopN ranking over a device row set: full-sort membership computation.

The reference maintains TopN incrementally through a 3-segment cache over a
state table (src/stream/src/executor/top_n/top_n_cache.rs:43 — low/middle/
high segments, per-row cache walks). The TPU-native design instead recomputes
the rank window *wholesale* at flush time: one lexicographic sort of all
slots (XLA sorts are fast and fusible; there is no pointer-chasing win on a
vector machine), then a vectorized per-group rank and a membership mask.
Correct under arbitrary insert/delete churn because membership is derived
from the full row set every flush, not patched incrementally.

``OrderSpec``: (column index, desc, nulls_last) per sort key — the order-by
clause (reference: PG ORDER BY semantics, defaults nulls last for ASC,
nulls first for DESC).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column
from .row_set import RowSetState


@dataclasses.dataclass(frozen=True)
class OrderSpec:
    col: int
    desc: bool = False
    nulls_last: bool = True


def _sort_key(c: Column, spec: OrderSpec) -> jax.Array:
    """Column → ascending-sortable f64/i64 key honoring desc/nulls order.

    int64 keys stay int64 (exact); everything else lowers to float64
    (float32/bool/int32 fit exactly)."""
    d = c.data
    if d.dtype == jnp.int64:
        k = d
        big = jnp.iinfo(jnp.int64).max
        small = jnp.iinfo(jnp.int64).min
    else:
        k = d.astype(jnp.float64)
        big = jnp.inf
        small = -jnp.inf
    if spec.desc:
        k = -k
    # nulls position is relative to the *output* order; after desc negation
    # the key is ascending, so nulls_last => +big, nulls_first => small
    null_sent = big if spec.nulls_last else small
    return jnp.where(c.mask, k, null_sent)


def topn_order(state: RowSetState, gid: jax.Array,
               order: Sequence[OrderSpec]) -> jax.Array:
    """Stable lexicographic permutation: (live-first is NOT applied here;
    dead slots are routed to the end via gid), gid, then order keys, then
    slot index (total order tiebreak via stable sort)."""
    cap = state.live.shape[0]
    dead_gid = jnp.iinfo(jnp.int64).max
    gid_eff = jnp.where(state.live, gid.astype(jnp.int64), dead_gid)
    perm = jnp.arange(cap, dtype=jnp.int32)
    for spec in reversed(list(order)):
        key = _sort_key(state.cols[spec.col], spec)
        perm = perm[jnp.argsort(key[perm], stable=True)]
    perm = perm[jnp.argsort(gid_eff[perm], stable=True)]
    return perm


def topn_in_set(
    state: RowSetState,
    gid: jax.Array,
    order: Sequence[OrderSpec],
    offset: int,
    limit: int,
    with_ties: bool = False,
    n_tie_keys: int | None = None,
) -> jax.Array:
    """bool[cap]: slot is in its group's [offset, offset+limit) rank window
    (plus ties with the window's last row when ``with_ties``).

    ``n_tie_keys``: how many leading order keys define a WITH TIES tie —
    callers append pk tiebreak keys to ``order`` for deterministic totality,
    and those must NOT participate in tie equality (default: all keys)."""
    cap = state.live.shape[0]
    perm = topn_order(state, gid, order)
    dead_gid = jnp.iinfo(jnp.int64).max
    gid_eff = jnp.where(state.live, gid.astype(jnp.int64), dead_gid)
    sgid = gid_eff[perm]
    pos = jnp.arange(cap, dtype=jnp.int64)
    is_start = jnp.concatenate([
        jnp.ones(1, jnp.bool_), sgid[1:] != sgid[:-1]])
    start = jax.lax.cummax(jnp.where(is_start, pos, 0))
    rank = pos - start
    slive = state.live[perm]
    in_win = slive & (rank >= offset) & (rank < offset + limit)
    if with_ties:
        # rows past the window tie-in if their sort key equals the key of the
        # window's last row (rank offset+limit-1) in the same group
        bpos = jnp.clip(start + offset + limit - 1, 0, cap - 1)
        tie = slive & (rank >= offset + limit) & (sgid == sgid[bpos])
        tie_specs = list(order)[: (len(order) if n_tie_keys is None
                                   else n_tie_keys)]
        for spec in tie_specs:
            key = _sort_key(state.cols[spec.col], spec)[perm]
            tie = tie & (key == key[bpos])
        in_win = in_win | tie
    return jnp.zeros(cap, jnp.bool_).at[perm].set(in_win)
