"""Pallas TPU kernel: fused same-key rank/total accumulation for the
streaming join probe.

The join's chunk pass needs, per probe row i (reference semantics:
eq_join_oneside's per-row match bookkeeping, hash_join.rs:972 — here
vectorized over the whole chunk):

    r[i, w] = |{ j < i : ident[j] == ident[i], matches[j, w] }|
    t[i, w] = |{ j     : ident[j] == ident[i], matches[j, w] }|

The jnp formulation (ops/join_state.py) builds ``eqf``/``lower`` as
[N, N] float32 matrices in HBM and runs two [N,N]·[N,W] matmuls — at the
bench shapes (N=4096, W=128) that is 2×64 MB of HBM traffic per chunk
pass just for the masks. This kernel fuses mask GENERATION into the
matmul: the [TI, TJ] equality tile is computed in VMEM from two [T]
slices of ``ident`` and fed straight to the MXU, so the [N, N] matrices
never exist in memory (SURVEY.md §7 stage 3: "hash probe … rank/degree
updates" is the named Pallas target).

Grid: (N/TI, N/TJ); j is the reduction dimension — TPU grid cells run
sequentially, so the output tile accumulates across the j sweep
(initialized at j == 0). Both outputs ride the same equality tile.

``rank_totals`` picks the implementation: the Pallas kernel on TPU (or
when RWTPU_PALLAS=1 forces it, e.g. interpret mode in tests), the jnp
matmul formulation elsewhere. Both produce bit-identical int32 results —
``tests/test_pallas_kernels.py`` asserts parity.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

TILE_I = 256
TILE_J = 256


def rank_totals_jnp(ident: jax.Array, matches: jax.Array):
    """Reference jnp formulation (the pre-kernel code path)."""
    n = ident.shape[0]
    idx = jnp.arange(n)
    eqf = (ident[:, None] == ident[None, :]) & (ident >= 0)[:, None]
    lower = eqf & (idx[None, :] < idx[:, None])
    mf = matches.astype(jnp.float32)
    r = jnp.round(lower.astype(jnp.float32) @ mf).astype(jnp.int32)
    t = jnp.round(eqf.astype(jnp.float32) @ mf).astype(jnp.int32)
    return r, t


def _kernel(ident_i_ref, ident_j_ref, m_ref, r_ref, t_ref):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        r_ref[:] = jnp.zeros_like(r_ref)
        t_ref[:] = jnp.zeros_like(t_ref)

    ti = ident_i_ref.shape[0]
    tj = ident_j_ref.shape[0]
    i0 = pl.program_id(0) * ti
    j0 = j * tj
    ident_i = ident_i_ref[:]
    ident_j = ident_j_ref[:]
    # the [TI, TJ] equality tile, generated in VMEM — never materialized
    # at [N, N]
    eq = (ident_i[:, None] == ident_j[None, :]) & (ident_i >= 0)[:, None]
    row_i = i0 + jax.lax.broadcasted_iota(jnp.int32, (ti, tj), 0)
    col_j = j0 + jax.lax.broadcasted_iota(jnp.int32, (ti, tj), 1)
    lower = eq & (col_j < row_i)
    mf = m_ref[:].astype(jnp.float32)
    r_ref[:] += jnp.dot(
        lower.astype(jnp.float32), mf,
        preferred_element_type=jnp.float32)
    t_ref[:] += jnp.dot(
        eq.astype(jnp.float32), mf,
        preferred_element_type=jnp.float32)


def rank_totals_pallas_call(ident: jax.Array, matches: jax.Array,
                            interpret: bool = False):
    """The raw pallas_call — no backend guard. Callers guarantee the tile
    divisibility; the compile CI proxy (tests/test_pallas_compile.py)
    lowers THIS for TPU from any host to catch kernel breakage without a
    chip."""
    from jax.experimental import pallas as pl

    n, w = matches.shape
    ti = min(TILE_I, n)
    tj = min(TILE_J, n)
    grid = (n // ti, n // tj)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((ti,), lambda i, j: (i,)),
            pl.BlockSpec((tj,), lambda i, j: (j,)),
            pl.BlockSpec((tj, w), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((ti, w), lambda i, j: (i, 0)),
            pl.BlockSpec((ti, w), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, w), jnp.float32),
            jax.ShapeDtypeStruct((n, w), jnp.float32),
        ],
        interpret=interpret,
    )(ident, ident, matches)


@functools.partial(jax.jit, static_argnames=("interpret",))
def rank_totals_pallas(ident: jax.Array, matches: jax.Array,
                       interpret: bool = False):
    n, w = matches.shape
    ti = min(TILE_I, n)
    tj = min(TILE_J, n)
    if (n % ti or n % tj
            or (not interpret and jax.default_backend() != "tpu")):
        # ragged capacities, or a backend with no Pallas lowering, fall
        # back to the jnp formulation (identical results)
        return rank_totals_jnp(ident, matches)
    r, t = rank_totals_pallas_call(ident, matches, interpret=interpret)
    return (jnp.round(r).astype(jnp.int32),
            jnp.round(t).astype(jnp.int32))


def _use_pallas() -> bool:
    mode = os.environ.get("RWTPU_PALLAS", "auto").lower()
    if mode in ("1", "on", "true"):
        return True
    if mode in ("0", "off", "false"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:   # noqa: BLE001 — backend probe must never break eval
        return False


def rank_totals(ident: jax.Array, matches: jax.Array):
    """r[i,w], t[i,w] as int32 — kernel on TPU, jnp elsewhere.
    RWTPU_PALLAS=0 forces the jnp path (escape hatch if a backend
    rejects the kernel); =1 forces Pallas (interpret on CPU)."""
    if _use_pallas():
        interpret = jax.default_backend() != "tpu"
        return rank_totals_pallas(ident, matches, interpret=interpret)
    return rank_totals_jnp(ident, matches)
