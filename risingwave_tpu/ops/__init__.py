from .hash_table import (  # noqa: F401
    DeviceHashTable, ht_lookup, ht_lookup_or_insert, ht_new, scatter_reduce,
)
from .interval_join import (  # noqa: F401
    IntervalJoinCore, IntervalJoinState,
)
from .join_state import JoinCore, JoinState, JoinType  # noqa: F401
from .session_window import (  # noqa: F401
    SessionWindowCore, SessionWindowState,
)
from .stream_q3 import Q3Core, Q3State  # noqa: F401
