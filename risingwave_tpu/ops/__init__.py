from .hash_table import (  # noqa: F401
    DeviceHashTable, ht_lookup, ht_lookup_or_insert, ht_new, scatter_reduce,
)
from .interval_join import (  # noqa: F401
    IntervalJoinCore, IntervalJoinState,
)
from .join_state import JoinCore, JoinState, JoinType  # noqa: F401
