"""Bucketed interval/window join core — the q7 hot path as O(N·W) work.

The generic streaming hash join (ops/join_state.py) recovers serial-order
semantics with [N, N] all-pairs compares per chunk (rank/total matmuls) —
correct for arbitrary equi-joins under retraction, but ~23× too slow for
the q7 shape, where the join key is a TIME WINDOW and the build side is a
per-window aggregate. This core exploits both structural facts:

  * **Bucketing**: both sides are bucketed by window id
    (``ts // window_us``) into a ring of ``n_buckets`` slots. Event time
    advances monotonically, so a slot is reclaimed by the next window that
    hashes onto it long after the old window went cold; no hash table, no
    probing — a bucket index is ONE modulo.
  * **Aggregate build side**: q7's build input is MAX(price) per window —
    at most ONE live build row per key. Probing is a [N] gather + compare,
    not a [N, W] candidate scan, and no degree bookkeeping exists (the
    join is INNER).
  * **Band filter**: stored rows join bucket-equal pairs; an optional band
    (``band_col``/``band_us``) further restricts matches to rows whose raw
    timestamp lies in ``[win_start, win_start + band_us)`` — the interval
    part of an interval join, applied per lane, never per pair-of-rows.

Per chunk the work is O(N log N) (a sort assigns same-bucket lanes) +
O(N) scatters; the epoch flush is O(n_buckets · W) ONCE per barrier —
the O(N²) all-pairs compare is gone. The flush match grid is an
MXU/VPU-friendly [n_buckets, W] tile computation: ``interval_match``
lowers to a Pallas TPU kernel (the ops/pallas_rank.py pattern — tiles
generated in VMEM, jnp fallback elsewhere, RWTPU_PALLAS override,
bit-identical results; int64 values ride as hi/lo int32 halves because
Mosaic has no native s64 compare).

Emission parity with the executor pipeline (HashAgg max → HashJoin) is
exact, including the churn the executor produces: its agg flush emits
U-/U+ for every TOUCHED group (even when the max did not change), and the
join then retracts + re-emits every matching stored row. The flush here
keys on a ``touched`` bitmask for the same reason — bit-exact output
multisets, verified by tests/test_interval_join.py.

The probe side is **append-only** (q7 bids). A delete arriving on the
probe side sets the sticky ``saw_delete`` flag instead of corrupting
state; retraction still flows through the OUTPUT (max changes retract
previously emitted matches) — that is the retraction surface q7 needs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_INSERT, Column, StreamChunk,
)
from ..common.types import Field, Schema

# Pallas tile: TB buckets per grid cell; the lane axis (W) rides whole.
TILE_B = 256

_NEG = jnp.iinfo(jnp.int64).min


@struct.dataclass
class IntervalJoinState:
    win_id: jax.Array       # int64[nb]: window id resident in slot; -1 empty
    fill: jax.Array         # int32[nb]: stored probe rows (lanes 0..fill-1)
    row_data: tuple[jax.Array, ...]   # per probe column: dtype[nb, W]
    row_mask: tuple[jax.Array, ...]   # per probe column: bool[nb, W]
    touched: jax.Array      # bool[nb]: bucket hit since last flush
    cur_max: jax.Array      # int64[nb]: running MAX incl. unflushed chunks
    cur_cnt: jax.Array      # int64[nb]: contributing rows (liveness)
    emitted_max: jax.Array  # int64[nb]: build value downstream last saw
    emitted_live: jax.Array  # bool[nb]: build row exists downstream
    lane_overflow: jax.Array  # bool scalar, sticky: bucket lane width full
    ring_clobber: jax.Array   # bool scalar, sticky: slot reused while dirty
    saw_delete: jax.Array     # bool scalar, sticky: delete on probe side


class IntervalJoinCore:
    """Static config + pure steps for one bucketed interval join.

    ``probe_schema``: schema of the (already projected) probe input.
    ``ts_col``: probe column holding the window start (tumble_start
    output — any value with ``value // window_us`` == window id works).
    ``val_col``: probe column compared against the build aggregate
    (q7: price == MAX(price) OVER window).
    ``band_col``/``band_us``: optional interval band — rows only match
    while ``band_col`` value ∈ [win_start, win_start + band_us).

    Output schema = probe columns ++ (window_start, agg value) — exactly
    the inner-join output of the executor pipeline."""

    def __init__(self, probe_schema: Schema, ts_col: int, val_col: int,
                 window_us: int, n_buckets: int = 1 << 15,
                 lane_width: int = 128,
                 band_col: Optional[int] = None,
                 band_us: Optional[int] = None):
        if window_us <= 0:
            raise ValueError("window_us must be positive")
        if (band_col is None) != (band_us is None):
            raise ValueError("band_col and band_us come together")
        self.probe_schema = probe_schema
        self.ts_col = ts_col
        self.val_col = val_col
        self.window_us = int(window_us)
        self.n_buckets = int(n_buckets)
        self.W = int(lane_width)
        self.band_col = band_col
        self.band_us = band_us
        self.out_schema = probe_schema.concat(Schema((
            Field("window_start", probe_schema[ts_col].type),
            Field("agg_val", probe_schema[val_col].type),
        )))

    # -- state ----------------------------------------------------------------

    def init_state(self) -> IntervalJoinState:
        nb, W = self.n_buckets, self.W
        return IntervalJoinState(
            win_id=jnp.full(nb, -1, jnp.int64),
            fill=jnp.zeros(nb, jnp.int32),
            row_data=tuple(jnp.zeros((nb, W), f.type.dtype)
                           for f in self.probe_schema),
            row_mask=tuple(jnp.zeros((nb, W), jnp.bool_)
                           for _ in self.probe_schema),
            touched=jnp.zeros(nb, jnp.bool_),
            cur_max=jnp.full(nb, _NEG, jnp.int64),
            cur_cnt=jnp.zeros(nb, jnp.int64),
            emitted_max=jnp.full(nb, _NEG, jnp.int64),
            emitted_live=jnp.zeros(nb, jnp.bool_),
            lane_overflow=jnp.zeros((), jnp.bool_),
            ring_clobber=jnp.zeros((), jnp.bool_),
            saw_delete=jnp.zeros((), jnp.bool_),
        )

    # -- chunk step ------------------------------------------------------------

    def apply_chunk(self, state: IntervalJoinState, chunk: StreamChunk):
        """Insert one probe chunk, emit matches against the build rows the
        downstream has already seen (``emitted_*`` — build updates land at
        the next ``flush``, mirroring the executor where the agg flushes
        at barriers only). Returns (state, out_chunk) with out capacity =
        chunk capacity (≤1 build row per window ⇒ ≤1 match per probe row).
        """
        nb, W = self.n_buckets, self.W
        N = chunk.capacity
        ts = chunk.columns[self.ts_col]
        val = chunk.columns[self.val_col]
        is_ins = (chunk.ops == OP_INSERT) | (chunk.ops == OP_UPDATE_INSERT)
        saw_delete = state.saw_delete | jnp.any(chunk.vis & ~is_ins)
        valid = chunk.vis & is_ins & ts.mask & val.mask
        wid = ts.data.astype(jnp.int64) // self.window_us
        slot = (wid % nb).astype(jnp.int32)

        # ---- ring turnover: the newest window id claims its slot. A slot
        # whose resident still had an unflushed delta loses emissions —
        # sticky ring_clobber (size n_buckets past one epoch's window span
        # and this can never fire).
        claim = jnp.where(valid, wid, jnp.int64(-1))
        win_id = state.win_id.at[jnp.where(valid, slot, nb)].max(
            claim, mode="drop")
        turned = win_id != state.win_id
        cur_live = state.cur_cnt > 0
        slot_dirty = state.touched & (
            (cur_live != state.emitted_live)
            | (cur_live & (state.cur_max != state.emitted_max)))
        # rows whose slot now belongs to a NEWER window (ring wrapped
        # within one chunk) cannot be stored — flagged, then dropped
        stale = valid & (win_id[slot] != wid)
        ring_clobber = (state.ring_clobber
                        | jnp.any(turned & slot_dirty) | jnp.any(stale))
        ok = valid & ~stale

        fill = jnp.where(turned, 0, state.fill)
        touched = jnp.where(turned, False, state.touched)
        cur_max = jnp.where(turned, _NEG, state.cur_max)
        cur_cnt = jnp.where(turned, 0, state.cur_cnt)
        emitted_max = jnp.where(turned, _NEG, state.emitted_max)
        emitted_live = jnp.where(turned, False, state.emitted_live)

        # ---- lane assignment: rank among same-slot rows of this chunk by
        # a stable sort (O(N log N) — the [N, N] all-pairs rank is gone),
        # then lane = bucket fill + rank.
        sort_key = jnp.where(ok, slot, nb)
        order = jnp.argsort(sort_key, stable=True)
        ks = sort_key[order]
        idx = jnp.arange(N, dtype=jnp.int32)
        run_start = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), ks[1:] != ks[:-1]])
        rank_sorted = idx - jax.lax.cummax(
            jnp.where(run_start, idx, 0))
        rank = jnp.zeros(N, jnp.int32).at[order].set(rank_sorted)

        lane = fill[slot] + rank
        lane_ok = ok & (lane < W)
        lane_overflow = state.lane_overflow | jnp.any(ok & (lane >= W))
        f = jnp.where(lane_ok, slot * W + lane, nb * W)
        s_ok = jnp.where(lane_ok, slot, nb)

        row_data = tuple(
            rd.reshape(-1).at[f].set(c.data, mode="drop").reshape(nb, W)
            for rd, c in zip(state.row_data, chunk.columns))
        row_mask = tuple(
            rm.reshape(-1).at[f].set(c.mask, mode="drop").reshape(nb, W)
            for rm, c in zip(state.row_mask, chunk.columns))
        one = jnp.where(lane_ok, 1, 0)
        fill = fill.at[s_ok].add(one.astype(jnp.int32), mode="drop")
        touched = touched.at[s_ok].set(True, mode="drop")
        v = val.data.astype(jnp.int64)
        cur_max = cur_max.at[s_ok].max(jnp.where(lane_ok, v, _NEG),
                                       mode="drop")
        cur_cnt = cur_cnt.at[s_ok].add(one.astype(jnp.int64), mode="drop")

        # ---- probe emission against the flushed build rows
        match = lane_ok & emitted_live[slot] & (v == emitted_max[slot])
        if self.band_col is not None:
            bts = chunk.columns[self.band_col].data.astype(jnp.int64)
            ws = wid * self.window_us
            match = match & (bts >= ws) & (bts < ws + self.band_us)
        out = self._emit_probe(chunk, slot, wid, emitted_max, match)

        return state.replace(
            win_id=win_id, fill=fill, row_data=row_data, row_mask=row_mask,
            touched=touched, cur_max=cur_max, cur_cnt=cur_cnt,
            emitted_max=emitted_max, emitted_live=emitted_live,
            lane_overflow=lane_overflow, ring_clobber=ring_clobber,
            saw_delete=saw_delete,
        ), out

    def _emit_probe(self, chunk, slot, wid, emitted_max, match):
        ts_dtype = self.probe_schema[self.ts_col].type.dtype
        val_dtype = self.probe_schema[self.val_col].type.dtype
        win_start = (wid * self.window_us).astype(ts_dtype)
        bmax = emitted_max[slot].astype(val_dtype)
        cols = tuple(chunk.columns) + (
            Column(win_start, match),
            Column(bmax, match),
        )
        return StreamChunk(jnp.zeros(chunk.capacity, jnp.int8), match, cols)

    # -- barrier flush ---------------------------------------------------------

    def _occ_band(self, state: IntervalJoinState) -> jax.Array:
        """bool[nb, W]: stored lanes that are live AND inside the band."""
        occ = (jnp.arange(self.W, dtype=jnp.int32)[None, :]
               < state.fill[:, None])
        if self.band_col is not None:
            bts = state.row_data[self.band_col].astype(jnp.int64)
            ws = (state.win_id * self.window_us)[:, None]
            occ = occ & (bts >= ws) & (bts < ws + self.band_us)
        return occ

    def flush_plan(self, state: IntervalJoinState):
        """Match grids for the epoch flush: the build-side delta applied to
        the stored probe arena. DELETE matches against the OLD emitted max,
        INSERT matches against the new one — for every TOUCHED bucket,
        exactly the churn the executor's dirty-set agg flush produces.
        Returns (del_mask [nb, W], ins_mask [nb, W], packed
        [n_units, lane_ovf, ring_clobber, saw_delete])."""
        occ = self._occ_band(state)
        vals = state.row_data[self.val_col].astype(jnp.int64)
        cur_live = state.cur_cnt > 0
        del_mask, ins_mask = interval_match(
            vals, occ,
            state.emitted_max, state.touched & state.emitted_live,
            state.cur_max, state.touched & cur_live)
        packed = jnp.stack([
            jnp.sum(del_mask) + jnp.sum(ins_mask),
            state.lane_overflow.astype(jnp.int64),
            state.ring_clobber.astype(jnp.int64),
            state.saw_delete.astype(jnp.int64),
        ])
        return del_mask, ins_mask, packed

    def gather_flush(self, state: IntervalJoinState, del_mask, ins_mask,
                     old_emitted_max, lo, out_capacity: int) -> StreamChunk:
        """Pack flush units with global rank in [lo, lo+out_capacity) into
        one output chunk — deletes (vs ``old_emitted_max``) rank first,
        inserts (vs the new ``cur_max``) after, preserving the executor's
        delete-pass-before-insert-pass order. Pure + shape-static; drive
        as ``for lo in range(0, n_units, out_capacity)``.

        Gather formulation: the in-window unit POSITIONS are extracted
        with a fixed-size nonzero, then every output column is a
        [out_capacity]-sized gather — per-window cost is a few linear
        passes over the [nb·W] masks plus tiny gathers. (The first cut
        scattered FROM the full [nb·W] arena per window: ~25 scatter
        passes over 4M cells each, ~3 s per window on the CPU stand-in —
        the same scatter-vs-gather lesson as AggCore.gather_flush_chunk.)
        """
        nb, W = self.n_buckets, self.W
        cap = out_capacity
        dflat = del_mask.reshape(-1)
        iflat = ins_mask.reshape(-1)
        n_del = jnp.sum(dflat)
        drank = jnp.cumsum(dflat) - 1
        irank = n_del + jnp.cumsum(iflat) - 1
        d_in = dflat & (drank >= lo) & (drank < lo + cap)
        i_in = iflat & (irank >= lo) & (irank < lo + cap)
        # ascending-index nonzero == ascending rank, so output slot j holds
        # delete unit lo+j for j < d_n, then insert units in rank order
        (d_idx,) = jnp.nonzero(d_in, size=cap, fill_value=nb * W)
        (i_idx,) = jnp.nonzero(i_in, size=cap, fill_value=nb * W)
        d_n = jnp.sum(d_in)
        j = jnp.arange(cap)
        take_del = j < d_n
        src = jnp.where(take_del, d_idx,
                        i_idx[jnp.clip(j - d_n, 0, cap - 1)])
        vis = src < nb * W
        src = jnp.where(vis, src, 0)
        bucket = src // W

        ops = jnp.where(take_del, OP_DELETE, OP_INSERT).astype(jnp.int8)
        cols = []
        for rd, rm in zip(state.row_data, state.row_mask):
            cols.append(Column(rd.reshape(-1)[src],
                               rm.reshape(-1)[src] & vis))
        ts_dtype = self.probe_schema[self.ts_col].type.dtype
        val_dtype = self.probe_schema[self.val_col].type.dtype
        ws = (state.win_id[bucket] * self.window_us).astype(ts_dtype)
        bval = jnp.where(take_del, old_emitted_max[bucket],
                         state.cur_max[bucket]).astype(val_dtype)
        cols.append(Column(ws, vis))
        cols.append(Column(bval, vis))
        return StreamChunk(ops, vis, tuple(cols))

    def finish_flush(self, state: IntervalJoinState) -> IntervalJoinState:
        """Advance the downstream-visible build rows to the current agg and
        clear the touched set — the fused analogue of the executor's agg
        ``finish_flush`` + the join arena absorbing the U-/U+ chunk."""
        cur_live = state.cur_cnt > 0
        return state.replace(
            emitted_max=jnp.where(state.touched, state.cur_max,
                                  state.emitted_max),
            emitted_live=jnp.where(state.touched, cur_live,
                                   state.emitted_live),
            touched=jnp.zeros_like(state.touched),
        )

    # -- checkpoint / recovery -------------------------------------------------

    def export_host(self, state: IntervalJoinState) -> dict:
        """Device state → named numpy arrays (the checkpoint payload). One
        transfer; the arrays round-trip bit-exactly through import_host."""
        import numpy as np
        host = jax.device_get(state)
        out = {f: getattr(host, f) for f in (
            "win_id", "fill", "touched", "cur_max", "cur_cnt",
            "emitted_max", "emitted_live", "lane_overflow",
            "ring_clobber", "saw_delete")}
        out["row_data"] = [np.asarray(a) for a in host.row_data]
        out["row_mask"] = [np.asarray(a) for a in host.row_mask]
        return out

    def import_host(self, payload: dict) -> IntervalJoinState:
        """Recovery: numpy checkpoint payload → fresh device state."""
        return IntervalJoinState(
            win_id=jnp.asarray(payload["win_id"]),
            fill=jnp.asarray(payload["fill"]),
            row_data=tuple(jnp.asarray(a) for a in payload["row_data"]),
            row_mask=tuple(jnp.asarray(a) for a in payload["row_mask"]),
            touched=jnp.asarray(payload["touched"]),
            cur_max=jnp.asarray(payload["cur_max"]),
            cur_cnt=jnp.asarray(payload["cur_cnt"]),
            emitted_max=jnp.asarray(payload["emitted_max"]),
            emitted_live=jnp.asarray(payload["emitted_live"]),
            lane_overflow=jnp.asarray(payload["lane_overflow"]),
            ring_clobber=jnp.asarray(payload["ring_clobber"]),
            saw_delete=jnp.asarray(payload["saw_delete"]),
        )


# ---------------------------------------------------------------------------
# The bucketed match kernel: [nb, W] tiles, Pallas on TPU, jnp elsewhere
# ---------------------------------------------------------------------------


def interval_match_jnp(vals, occ, old_max, old_live, new_max, new_live):
    """Reference formulation: per (bucket, lane) delete/insert matches of
    the flush. All inputs int64/bool; outputs (bool[nb, W], bool[nb, W])."""
    del_mask = occ & old_live[:, None] & (vals == old_max[:, None])
    ins_mask = occ & new_live[:, None] & (vals == new_max[:, None])
    return del_mask, ins_mask


def _split64(a: jax.Array):
    """int64 → (lo, hi) int32 halves (Mosaic has no native s64 compare;
    equality of both halves == equality of the 64-bit value)."""
    lo = (a & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32).astype(jnp.int32)
    hi = (a >> 32).astype(jnp.int32)
    return lo, hi


def _match_kernel(vlo_ref, vhi_ref, occ_ref, olo_ref, ohi_ref, olive_ref,
                  nlo_ref, nhi_ref, nlive_ref, del_ref, ins_ref):
    """One [TB, W] tile: the equality grids are generated in VMEM from the
    [TB] per-bucket vectors and never exist at [nb, W] intermediate
    granularity beyond the output masks themselves."""
    vlo = vlo_ref[:]
    vhi = vhi_ref[:]
    occ = occ_ref[:] != 0
    eq_old = ((vlo == olo_ref[:][:, None]) & (vhi == ohi_ref[:][:, None])
              & (olive_ref[:] != 0)[:, None])
    eq_new = ((vlo == nlo_ref[:][:, None]) & (vhi == nhi_ref[:][:, None])
              & (nlive_ref[:] != 0)[:, None])
    del_ref[:] = (occ & eq_old).astype(jnp.int32)
    ins_ref[:] = (occ & eq_new).astype(jnp.int32)


def interval_match_pallas_call(vals, occ, old_max, old_live,
                               new_max, new_live, interpret: bool = False):
    """The raw pallas_call — no backend guard (compile CI proxy entry,
    like ops/pallas_rank.rank_totals_pallas_call)."""
    from jax.experimental import pallas as pl

    nb, w = vals.shape
    tb = min(TILE_B, nb)
    vlo, vhi = _split64(vals)
    olo, ohi = _split64(old_max)
    nlo, nhi = _split64(new_max)
    grid = (nb // tb,)
    vec = pl.BlockSpec((tb,), lambda i: (i,))
    mat = pl.BlockSpec((tb, w), lambda i: (i, 0))
    return pl.pallas_call(
        _match_kernel,
        grid=grid,
        in_specs=[mat, mat, mat, vec, vec, vec, vec, vec, vec],
        out_specs=[mat, mat],
        out_shape=[jax.ShapeDtypeStruct((nb, w), jnp.int32),
                   jax.ShapeDtypeStruct((nb, w), jnp.int32)],
        interpret=interpret,
    )(vlo, vhi, occ.astype(jnp.int32), olo, ohi,
      old_live.astype(jnp.int32), nlo, nhi, new_live.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def interval_match_pallas(vals, occ, old_max, old_live, new_max, new_live,
                          interpret: bool = False):
    nb, w = vals.shape
    tb = min(TILE_B, nb)
    if (nb % tb
            or (not interpret and jax.default_backend() != "tpu")):
        return interval_match_jnp(vals, occ, old_max, old_live,
                                  new_max, new_live)
    d, ins = interval_match_pallas_call(vals, occ, old_max, old_live,
                                        new_max, new_live,
                                        interpret=interpret)
    return d != 0, ins != 0


def interval_match(vals, occ, old_max, old_live, new_max, new_live):
    """Flush match grids — Pallas kernel on TPU, jnp elsewhere; both
    bit-identical (tests/test_interval_join.py asserts parity).
    RWTPU_PALLAS=0 forces jnp; =1 forces Pallas (interpret off-TPU) —
    ONE gate shared with the rank kernel so the two can never disagree
    about when Pallas is active."""
    from .pallas_rank import _use_pallas
    if _use_pallas():
        interpret = jax.default_backend() != "tpu"
        return interval_match_pallas(vals, occ, old_max, old_live,
                                     new_max, new_live,
                                     interpret=interpret)
    return interval_match_jnp(vals, occ, old_max, old_live,
                              new_max, new_live)
