"""Datagen source: deterministic per-split field generators.

Counterpart of the reference's datagen connector + field generators
(reference: src/connector/src/source/datagen/,
src/common/src/field_generator/ — sequence and random generators per
column). Every value is a pure function of (column, split, offset), so
``seek`` is O(1) and replay after recovery reproduces the exact stream —
the property the split-state checkpoint contract requires.

Options (WITH clause), mirroring the reference's naming:
  * ``datagen.split.num``       — number of splits (default 1)
  * ``datagen.rows.per.chunk``  — rows per emitted chunk (default 256)
  * ``datagen.max.rows``        — total rows per split (default unbounded)
  * per-field: ``fields.<name>.kind`` = ``sequence`` (default for integral
    types) | ``random``; ``fields.<name>.start``/``end`` bounds.
"""

from __future__ import annotations

import numpy as np
from typing import Dict, List, Optional

from ..common.chunk import Column, StreamChunk, make_chunk
from ..common.types import Schema, TypeKind
from .base import SplitReader

import jax.numpy as jnp


def _field_values(field, kind: str, start: int, end: int,
                  split: int, n_splits: int, lo: int, hi: int) -> np.ndarray:
    """Values for rows [lo, hi) of one split — pure function of position.
    Sequence fields interleave across splits (split s gets start + s,
    start + s + n_splits, …) so the union over splits is the contiguous
    sequence, as in the reference's datagen split scheme."""
    idx = np.arange(lo, hi, dtype=np.int64)
    t = field.type
    if kind == "sequence":
        vals = start + split + idx * n_splits
        if end > start:
            vals = start + (vals - start) % (end - start + 1)
        return vals
    # random: splitmix64 of the global position — stable across runs
    x = (idx * np.int64(n_splits) + np.int64(split)).astype(np.uint64)
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    x = x ^ (x >> np.uint64(31))
    if t.is_float:
        return (x >> np.uint64(11)).astype(np.float64) / float(1 << 53) \
            * (end - start) + start
    span = max(1, end - start + 1)
    return (x % np.uint64(span)).astype(np.int64) + start


class DatagenReader(SplitReader):
    def __init__(self, schema: Schema, options: Optional[dict] = None):
        options = options or {}
        self.schema = schema
        self.n_splits = int(options.get("datagen.split.num", 1))
        self.rows_per_chunk = int(options.get("datagen.rows.per.chunk",
                                              options.get("rows_per_chunk", 256)))
        mr = options.get("datagen.max.rows")
        self.max_rows = int(mr) if mr is not None else None
        self._offsets: Dict[str, int] = {str(s): 0 for s in range(self.n_splits)}
        self._fields = []
        for f in schema:
            kind = str(options.get(f"fields.{f.name}.kind",
                                   "sequence" if f.type.is_integral
                                   else "random"))
            start = int(options.get(f"fields.{f.name}.start", 0))
            end = int(options.get(f"fields.{f.name}.end", 0))
            self._fields.append((f, kind, start, end))

    def splits(self) -> List[str]:
        return list(self._offsets)

    @property
    def offsets(self) -> Dict[str, int]:
        return dict(self._offsets)

    def seek(self, offsets: Dict[str, int]) -> None:
        for s, o in offsets.items():
            if s in self._offsets:
                self._offsets[s] = int(o)

    def next_chunk(self) -> Optional[StreamChunk]:
        # serve the most-behind split first: deterministic given offsets
        # alone, so seek() needs no extra cursor state
        for split in sorted(range(self.n_splits),
                            key=lambda s: (self._offsets[str(s)], s)):
            sid = str(split)
            lo = self._offsets[sid]
            hi = lo + self.rows_per_chunk
            if self.max_rows is not None:
                hi = min(hi, self.max_rows)
            if hi <= lo:
                continue
            self._offsets[sid] = hi
            n = hi - lo
            cols = []
            for f, kind, start, end in self._fields:
                vals = _field_values(f, kind, start, end, split,
                                     self.n_splits, lo, hi)
                if f.type.kind == TypeKind.VARCHAR:
                    from ..common.types import GLOBAL_STRING_DICT
                    vals = np.array([GLOBAL_STRING_DICT.intern(
                        f"{f.name}_{int(v)}") for v in vals], np.int32)
                arr = np.zeros(self.rows_per_chunk, f.type.np_dtype)
                arr[:n] = vals.astype(f.type.np_dtype)
                mask = np.zeros(self.rows_per_chunk, bool)
                mask[:n] = True
                cols.append(Column(jnp.asarray(arr), jnp.asarray(mask)))
            ops = jnp.zeros(self.rows_per_chunk, jnp.int8)
            vis = jnp.asarray(mask)
            return StreamChunk(ops, vis, tuple(cols))
        return None
