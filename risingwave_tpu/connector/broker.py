"""Broker-shaped source: a partitioned append-log server + split reader.

Counterpart of the reference's Kafka-style broker sources (reference:
src/connector/src/source/base.rs:295-340 — SplitImpl::Kafka,
src/connector/src/source/kafka/). The in-tree ``BrokerServer`` is the
environment's stand-in for an external broker (no Kafka in the image): a
TCP server holding N append-only partitions per topic, with at-least-once
durable segments on disk, speaking a minimal line protocol:

    PUB <topic> <part> <b64>      -> OK <offset>
    FETCH <topic> <part> <off> <max> -> MSGS <n>\\n<b64>*n
    META <topic>                  -> PARTS <n>
    LEN <topic> <part>            -> OK <n>
    QUIT

``BrokerClient`` is fault-tolerant: every command transparently
reconnects with backoff (common/retry.py policy) when the broker drops
the connection or is briefly down. FETCH/META/LEN are idempotent and
simply retried; PUB replays after a lost reply are deduplicated by
offset position (``LEN`` tells the client how many of its unacked
messages landed — exact under the one-producer-per-partition discipline
the broker sink keeps).

``BrokerSourceReader`` implements the SplitReader contract over it: one
split per partition (``{topic}-{part}``), offsets are per-partition
sequence numbers, and ``seek`` makes replay deterministic — which is what
plugs it into the existing split-state checkpointing for exactly-once
resume (connector/base.py).

Payload formats: ``json`` (one object per message) and ``avro`` (binary
datum against an Avro record schema — connector/avro.py).
"""

from __future__ import annotations

import base64
import os
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional

from ..common.chunk import StreamChunk, make_chunk
from ..common.types import Schema
from .base import SplitReader
from .parsers import parse_json_line


class _Partition:
    __slots__ = ("messages", "path", "lock")

    def __init__(self, path: Optional[str]):
        self.messages: list[bytes] = []
        self.path = path
        self.lock = threading.Lock()
        if path is not None and os.path.exists(path):
            with open(path, "rb") as f:
                for line in f.read().splitlines():
                    if line:
                        self.messages.append(base64.b64decode(line))

    def append(self, payload: bytes) -> int:
        with self.lock:
            self.messages.append(payload)
            if self.path is not None:
                with open(self.path, "ab") as f:
                    f.write(base64.b64encode(payload) + b"\n")
                    f.flush()
                    os.fsync(f.fileno())
            return len(self.messages) - 1

    def read(self, offset: int, max_n: int) -> list[bytes]:
        with self.lock:
            return self.messages[offset:offset + max_n]

    def length(self) -> int:
        with self.lock:
            return len(self.messages)


class BrokerServer:
    """Append-log broker. ``data_dir=None`` keeps topics in memory only;
    with a directory, every partition is an fsynced base64-line segment
    that survives broker restarts."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 n_partitions: int = 2, data_dir: Optional[str] = None):
        self.n_partitions = n_partitions
        self.data_dir = data_dir
        self._topics: Dict[str, list[_Partition]] = {}
        self._lock = threading.Lock()
        # live handler connections: a broker RESTART must drop them (like
        # a real broker process dying) or clients would keep talking to a
        # zombie handler thread serving the closed server's partitions
        self._conns: set = set()
        broker = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                with broker._lock:
                    broker._conns.add(self.connection)
                try:
                    while True:
                        line = self.rfile.readline()
                        if not line:
                            return
                        try:
                            reply = broker._command(line.decode().strip())
                        except Exception as e:  # malformed input must not
                            reply = f"ERR {e}"  # kill the acceptor thread
                        if reply is None:
                            return
                        self.wfile.write(reply.encode() + b"\n")
                        self.wfile.flush()
                finally:
                    with broker._lock:
                        broker._conns.discard(self.connection)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # sever live client connections (process-death semantics): their
        # next command fails and the fault-tolerant client reconnects —
        # to whatever serves this address then
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- protocol -------------------------------------------------------------

    def _topic(self, name: str) -> list[_Partition]:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                paths = [None] * self.n_partitions
                if self.data_dir is not None:
                    os.makedirs(self.data_dir, exist_ok=True)
                    paths = [os.path.join(self.data_dir, f"{name}.{p}.log")
                             for p in range(self.n_partitions)]
                t = self._topics[name] = [
                    _Partition(p) for p in paths]
            return t

    def _command(self, line: str) -> Optional[str]:
        parts = line.split(" ")
        cmd = parts[0].upper() if parts else ""
        if cmd == "PUB":
            _, topic, part, b64 = parts
            off = self._topic(topic)[int(part)].append(
                base64.b64decode(b64))
            return f"OK {off}"
        if cmd == "FETCH":
            _, topic, part, off, max_n = parts
            msgs = self._topic(topic)[int(part)].read(int(off), int(max_n))
            return "\n".join([f"MSGS {len(msgs)}"] + [
                base64.b64encode(m).decode() for m in msgs])
        if cmd == "META":
            return f"PARTS {len(self._topic(parts[1]))}"
        if cmd == "LEN":
            _, topic, part = parts
            return f"OK {self._topic(topic)[int(part)].length()}"
        if cmd == "QUIT":
            return None
        raise ValueError(f"unknown command {cmd!r}")

    # -- local producer convenience (tests / sinks) ---------------------------

    def publish(self, topic: str, partition: int, payload: bytes) -> int:
        return self._topic(topic)[partition].append(payload)


class BrokerClient:
    """Line-protocol client used by the reader, the broker sink, and
    tests' producers. Fault-tolerant: a dropped connection (broker
    restart, transient socket error) is survived by transparent
    reconnect-with-backoff instead of leaving the client permanently
    dead. FETCH/META/LEN retry blindly (idempotent); PUB replays are
    deduplicated by offset position (see ``publish_many``)."""

    def __init__(self, address: str, timeout: float = 10.0,
                 reconnect_policy=None):
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._rf = None
        if reconnect_policy is None:
            # single source of default numbers: the FaultConfig dataclass
            # (a bare client matches a fault-config-less session exactly)
            from ..common.config import FaultConfig
            reconnect_policy = FaultConfig().broker_retry_policy()
        self._policy = reconnect_policy
        #: next expected offset per (topic, partition) this client has
        #: published to — the publish-replay dedup cursor
        self._next_off: Dict[tuple, int] = {}
        # eager connect, but UNDER the reconnect policy: a broker that is
        # briefly down at construction time (restart racing a CREATE
        # SOURCE/SINK or recovery) is absorbed; a truly bad address still
        # surfaces once the budget is spent
        self._policy.run("broker.connect", self._ensure_conn)

    # -- connection management ------------------------------------------------

    def _ensure_conn(self) -> None:
        if self._sock is not None:
            return
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        self._rf = self._sock.makefile("rb")

    def _drop_conn(self) -> None:
        if self._rf is not None:
            try:
                self._rf.close()
            except OSError:
                pass
            self._rf = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _readline(self) -> bytes:
        line = self._rf.readline()
        if not line:
            raise ConnectionError("broker closed the connection")
        return line

    def _roundtrip(self, line: str) -> str:
        """One request/reply on the current connection; connection-shaped
        failures drop the socket so the caller's retry reconnects."""
        try:
            self._ensure_conn()
            self._sock.sendall(line.encode() + b"\n")
            return self._readline().decode().strip()
        except (OSError, ConnectionError):
            self._drop_conn()
            raise

    def _rpc(self, line: str, site: str) -> str:
        """Idempotent command under the reconnect policy."""
        return self._policy.run(site, self._roundtrip, line)

    # -- commands --------------------------------------------------------------

    def publish(self, topic: str, partition: int, payload: bytes) -> int:
        return self.publish_many(topic, partition, [payload])

    def partition_len(self, topic: str, partition: int) -> int:
        """Current message count of a partition (the LEN command)."""
        r = self._rpc(f"LEN {topic} {partition}", "broker.len")
        if not r.startswith("OK "):
            raise RuntimeError(f"broker error: {r}")
        return int(r.split(" ")[1])

    def published_through(self, topic: str,
                          partition: int) -> Optional[int]:
        """This client's publish cursor (next expected offset) for a
        partition, maintained even across mid-batch failures — the
        broker sink's dedup bookkeeping reads it."""
        return self._next_off.get((topic, partition))

    def _settled_len(self, topic: str, partition: int) -> int:
        """Partition length AFTER the broker stops absorbing in-flight
        appends. A dropped connection's buffered PUB lines may still be
        draining server-side (the close sent FIN, not an abort), so a
        single LEN probe could undercount landed messages and cause a
        duplicate resend — poll until two reads agree."""
        n = self.partition_len(topic, partition)
        for _ in range(20):
            time.sleep(0.02)
            n2 = self.partition_len(topic, partition)
            if n2 == n:
                return n
            n = n2
        return n

    def publish_many(self, topic: str, partition: int,
                     payloads: list) -> int:
        """Pipelined publish: all PUB lines sent, then all replies read —
        one RTT per batch, not per message. Returns the last offset.

        Replay dedup: if the connection dies mid-batch, some messages may
        have been appended without their OK reaching us. After
        reconnecting, ``LEN`` reveals how many landed past our cursor —
        those are treated as acked and only the remainder is resent, so a
        broker restart never duplicates messages (exact under the
        one-producer-per-partition discipline the broker sink keeps;
        concurrent foreign producers would make any dedup unsound)."""
        if not payloads:
            return -1
        key = (topic, partition)
        unacked = [bytes(p) for p in payloads]
        if key not in self._next_off:
            # first publish on this partition: anchor the dedup cursor
            self._next_off[key] = self.partition_len(topic, partition)
        last = self._next_off[key] - 1

        def attempt() -> int:
            nonlocal last
            if not unacked:
                return last
            try:
                self._ensure_conn()
                lines = b"".join(
                    f"PUB {topic} {partition} "
                    f"{base64.b64encode(p).decode()}\n".encode()
                    for p in unacked)
                self._sock.sendall(lines)
                n_acked = 0
                try:
                    for _ in range(len(unacked)):
                        r = self._readline().decode().strip()
                        if not r.startswith("OK "):
                            # the rest of the batch's replies are still
                            # buffered: a reused client would consume
                            # them as later commands' replies — drop the
                            # connection before surfacing the error
                            self._drop_conn()
                            raise RuntimeError(f"broker error: {r}")
                        last = int(r.split(" ")[1])
                        self._next_off[key] = last + 1
                        n_acked += 1
                finally:
                    del unacked[:n_acked]
                return last
            except (OSError, ConnectionError):
                self._drop_conn()
                # dedup-by-offset: messages appended before the drop are
                # exactly those past our cursor (settled probe: the old
                # connection's buffered PUBs may still be draining). If
                # the broker is STILL down past the LEN sub-budget,
                # surface it as a connection error so the OUTER publish
                # policy keeps its own reconnect attempts (a RetryError
                # would be non-retryable and collapse the budget).
                from ..common.retry import RetryError
                try:
                    n = self._settled_len(topic, partition)  # reconnects
                except RetryError as re:
                    raise ConnectionError(
                        f"broker still unreachable probing replay "
                        f"position: {re}") from re
                landed = min(max(0, n - self._next_off[key]), len(unacked))
                del unacked[:landed]
                self._next_off[key] = n
                if unacked:
                    raise               # policy retries the remainder
                last = n - 1
                return last

        return self._policy.run("broker.publish", attempt)

    def fetch(self, topic: str, partition: int, offset: int,
              max_n: int) -> list[bytes]:
        def attempt() -> list[bytes]:
            try:
                self._ensure_conn()
                self._sock.sendall(
                    f"FETCH {topic} {partition} {offset} {max_n}\n"
                    .encode())
                r = self._readline().decode().strip()
                if not r.startswith("MSGS "):
                    raise RuntimeError(f"broker error: {r}")
                n = int(r.split(" ")[1])
                return [base64.b64decode(self._readline().strip())
                        for _ in range(n)]
            except (OSError, ConnectionError):
                self._drop_conn()     # idempotent: whole fetch re-runs
                raise

        return self._policy.run("broker.fetch", attempt)

    def n_partitions(self, topic: str) -> int:
        r = self._rpc(f"META {topic}", "broker.meta")
        if not r.startswith("PARTS "):
            raise RuntimeError(f"broker error: {r}")
        return int(r.split(" ")[1])

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.sendall(b"QUIT\n")
            except OSError:
                pass
        self._drop_conn()


def parse_broker_options(options: dict) -> tuple:
    """Shared WITH-option extraction for the broker source AND sink so
    the two cannot drift: returns (address, topic)."""
    address = options.get("broker.address",
                          options.get("bootstrap.servers"))
    topic = options.get("topic")
    if not address or not topic:
        raise ValueError(
            "broker connector requires broker.address and topic options")
    return str(address), str(topic)


class BrokerSourceReader(SplitReader):
    """SplitReader over a broker topic: split ``{topic}-{p}`` per
    partition, offset = next message sequence number. Satisfies the
    deterministic-seek contract: the broker log is append-only, so
    re-fetching [o, o+n) always yields the same payloads."""

    def __init__(self, schema: Schema, address: str, topic: str,
                 fmt: str = "json", avro_schema: Optional[str] = None,
                 avro_framing: str = "raw", rows_per_chunk: int = 256,
                 reconnect_policy=None):
        self.schema = schema
        self.topic = topic
        self.fmt = fmt.lower()
        self.rows_per_chunk = rows_per_chunk
        self._client = BrokerClient(address,
                                    reconnect_policy=reconnect_policy)
        self._n_parts = self._client.n_partitions(topic)
        self._offsets: Dict[str, int] = {
            f"{topic}-{p}": 0 for p in range(self._n_parts)}
        self._rr = 0
        self.dropped_events = 0
        if self.fmt == "avro":
            from .avro import AvroCodec
            if not avro_schema:
                raise ValueError("avro format requires an avro.schema "
                                 "option (the record schema JSON)")
            self._avro = AvroCodec(avro_schema, framing=avro_framing)
        elif self.fmt != "json":
            raise ValueError(f"unsupported broker format {self.fmt!r}")

    def splits(self) -> List[str]:
        return list(self._offsets)

    @property
    def offsets(self) -> Dict[str, int]:
        return dict(self._offsets)

    def seek(self, offsets: Dict[str, int]) -> None:
        for s, o in offsets.items():
            if s in self._offsets:
                self._offsets[s] = int(o)

    def _decode(self, payload: bytes) -> Optional[tuple]:
        """payload → PHYSICAL row tuple (strings interned), or None for
        undecodable messages (counted in dropped_events, offset still
        advances — a poisoned message must not wedge the source)."""
        if self.fmt == "avro":
            try:
                rec = self._avro.decode(payload)
            except Exception:
                self.dropped_events += 1
                return None
            vals = [rec.get(f.name) for f in self.schema]
        else:
            try:
                row = parse_json_line(payload.decode("utf-8", "replace"),
                                      self.schema)
            except (ValueError, TypeError):
                self.dropped_events += 1
                return None
            if row is None:
                return None
            vals = list(row)
        return tuple(
            None if v is None else f.type.to_physical(v)
            for f, v in zip(self.schema, vals))

    def next_chunk(self) -> Optional[StreamChunk]:
        """Round-robin over partitions; one chunk per non-empty fetch."""
        for _ in range(self._n_parts):
            p = self._rr
            self._rr = (self._rr + 1) % self._n_parts
            split = f"{self.topic}-{p}"
            off = self._offsets[split]
            msgs = self._client.fetch(self.topic, p, off,
                                      self.rows_per_chunk)
            if not msgs:
                continue
            rows = []
            for m in msgs:
                r = self._decode(m)
                if r is not None:
                    rows.append(r)
            self._offsets[split] = off + len(msgs)
            if not rows:
                continue
            return make_chunk(self.schema, rows,
                              capacity=max(self.rows_per_chunk, len(rows)),
                              physical=True)
        return None

    def close(self) -> None:
        self._client.close()
