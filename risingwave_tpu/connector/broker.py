"""Broker-shaped source: a partitioned append-log server + split reader.

Counterpart of the reference's Kafka-style broker sources (reference:
src/connector/src/source/base.rs:295-340 — SplitImpl::Kafka,
src/connector/src/source/kafka/). The in-tree ``BrokerServer`` is the
environment's stand-in for an external broker (no Kafka in the image): a
TCP server holding N append-only partitions per topic, with at-least-once
durable segments on disk, speaking a minimal line protocol:

    PUB <topic> <part> <b64>      -> OK <offset>
    FETCH <topic> <part> <off> <max> -> MSGS <n>\\n<b64>*n
    META <topic>                  -> PARTS <n>
    QUIT

``BrokerSourceReader`` implements the SplitReader contract over it: one
split per partition (``{topic}-{part}``), offsets are per-partition
sequence numbers, and ``seek`` makes replay deterministic — which is what
plugs it into the existing split-state checkpointing for exactly-once
resume (connector/base.py).

Payload formats: ``json`` (one object per message) and ``avro`` (binary
datum against an Avro record schema — connector/avro.py).
"""

from __future__ import annotations

import base64
import os
import socket
import socketserver
import threading
from typing import Dict, List, Optional

from ..common.chunk import StreamChunk, make_chunk
from ..common.types import Schema
from .base import SplitReader
from .parsers import parse_json_line


class _Partition:
    __slots__ = ("messages", "path", "lock")

    def __init__(self, path: Optional[str]):
        self.messages: list[bytes] = []
        self.path = path
        self.lock = threading.Lock()
        if path is not None and os.path.exists(path):
            with open(path, "rb") as f:
                for line in f.read().splitlines():
                    if line:
                        self.messages.append(base64.b64decode(line))

    def append(self, payload: bytes) -> int:
        with self.lock:
            self.messages.append(payload)
            if self.path is not None:
                with open(self.path, "ab") as f:
                    f.write(base64.b64encode(payload) + b"\n")
                    f.flush()
                    os.fsync(f.fileno())
            return len(self.messages) - 1

    def read(self, offset: int, max_n: int) -> list[bytes]:
        with self.lock:
            return self.messages[offset:offset + max_n]


class BrokerServer:
    """Append-log broker. ``data_dir=None`` keeps topics in memory only;
    with a directory, every partition is an fsynced base64-line segment
    that survives broker restarts."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 n_partitions: int = 2, data_dir: Optional[str] = None):
        self.n_partitions = n_partitions
        self.data_dir = data_dir
        self._topics: Dict[str, list[_Partition]] = {}
        self._lock = threading.Lock()
        broker = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        reply = broker._command(line.decode().strip())
                    except Exception as e:  # malformed input must not
                        reply = f"ERR {e}"  # kill the acceptor thread
                    if reply is None:
                        return
                    self.wfile.write(reply.encode() + b"\n")
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- protocol -------------------------------------------------------------

    def _topic(self, name: str) -> list[_Partition]:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                paths = [None] * self.n_partitions
                if self.data_dir is not None:
                    os.makedirs(self.data_dir, exist_ok=True)
                    paths = [os.path.join(self.data_dir, f"{name}.{p}.log")
                             for p in range(self.n_partitions)]
                t = self._topics[name] = [
                    _Partition(p) for p in paths]
            return t

    def _command(self, line: str) -> Optional[str]:
        parts = line.split(" ")
        cmd = parts[0].upper() if parts else ""
        if cmd == "PUB":
            _, topic, part, b64 = parts
            off = self._topic(topic)[int(part)].append(
                base64.b64decode(b64))
            return f"OK {off}"
        if cmd == "FETCH":
            _, topic, part, off, max_n = parts
            msgs = self._topic(topic)[int(part)].read(int(off), int(max_n))
            return "\n".join([f"MSGS {len(msgs)}"] + [
                base64.b64encode(m).decode() for m in msgs])
        if cmd == "META":
            return f"PARTS {len(self._topic(parts[1]))}"
        if cmd == "QUIT":
            return None
        raise ValueError(f"unknown command {cmd!r}")

    # -- local producer convenience (tests / sinks) ---------------------------

    def publish(self, topic: str, partition: int, payload: bytes) -> int:
        return self._topic(topic)[partition].append(payload)


class BrokerClient:
    """Line-protocol client used by the reader, the broker sink, and
    tests' producers."""

    def __init__(self, address: str, timeout: float = 10.0):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._rf = self._sock.makefile("rb")

    def _roundtrip(self, line: str) -> str:
        self._sock.sendall(line.encode() + b"\n")
        reply = self._rf.readline()
        if not reply:
            raise ConnectionError("broker closed the connection")
        return reply.decode().strip()

    def publish(self, topic: str, partition: int, payload: bytes) -> int:
        r = self._roundtrip(
            f"PUB {topic} {partition} "
            f"{base64.b64encode(payload).decode()}")
        if not r.startswith("OK "):
            raise RuntimeError(f"broker error: {r}")
        return int(r.split(" ")[1])

    def publish_many(self, topic: str, partition: int,
                     payloads: list) -> int:
        """Pipelined publish: all PUB lines sent, then all replies read —
        one RTT per batch, not per message. Returns the last offset."""
        if not payloads:
            return -1
        lines = b"".join(
            f"PUB {topic} {partition} "
            f"{base64.b64encode(p).decode()}\n".encode()
            for p in payloads)
        self._sock.sendall(lines)
        last = -1
        for _ in payloads:
            r = self._rf.readline().decode().strip()
            if not r.startswith("OK "):
                raise RuntimeError(f"broker error: {r}")
            last = int(r.split(" ")[1])
        return last

    def fetch(self, topic: str, partition: int, offset: int,
              max_n: int) -> list[bytes]:
        r = self._roundtrip(f"FETCH {topic} {partition} {offset} {max_n}")
        if not r.startswith("MSGS "):
            raise RuntimeError(f"broker error: {r}")
        n = int(r.split(" ")[1])
        out = []
        for _ in range(n):
            out.append(base64.b64decode(self._rf.readline().strip()))
        return out

    def n_partitions(self, topic: str) -> int:
        r = self._roundtrip(f"META {topic}")
        if not r.startswith("PARTS "):
            raise RuntimeError(f"broker error: {r}")
        return int(r.split(" ")[1])

    def close(self) -> None:
        try:
            self._sock.sendall(b"QUIT\n")
        except OSError:
            pass
        self._rf.close()
        self._sock.close()


def parse_broker_options(options: dict) -> tuple:
    """Shared WITH-option extraction for the broker source AND sink so
    the two cannot drift: returns (address, topic)."""
    address = options.get("broker.address",
                          options.get("bootstrap.servers"))
    topic = options.get("topic")
    if not address or not topic:
        raise ValueError(
            "broker connector requires broker.address and topic options")
    return str(address), str(topic)


class BrokerSourceReader(SplitReader):
    """SplitReader over a broker topic: split ``{topic}-{p}`` per
    partition, offset = next message sequence number. Satisfies the
    deterministic-seek contract: the broker log is append-only, so
    re-fetching [o, o+n) always yields the same payloads."""

    def __init__(self, schema: Schema, address: str, topic: str,
                 fmt: str = "json", avro_schema: Optional[str] = None,
                 avro_framing: str = "raw", rows_per_chunk: int = 256):
        self.schema = schema
        self.topic = topic
        self.fmt = fmt.lower()
        self.rows_per_chunk = rows_per_chunk
        self._client = BrokerClient(address)
        self._n_parts = self._client.n_partitions(topic)
        self._offsets: Dict[str, int] = {
            f"{topic}-{p}": 0 for p in range(self._n_parts)}
        self._rr = 0
        self.dropped_events = 0
        if self.fmt == "avro":
            from .avro import AvroCodec
            if not avro_schema:
                raise ValueError("avro format requires an avro.schema "
                                 "option (the record schema JSON)")
            self._avro = AvroCodec(avro_schema, framing=avro_framing)
        elif self.fmt != "json":
            raise ValueError(f"unsupported broker format {self.fmt!r}")

    def splits(self) -> List[str]:
        return list(self._offsets)

    @property
    def offsets(self) -> Dict[str, int]:
        return dict(self._offsets)

    def seek(self, offsets: Dict[str, int]) -> None:
        for s, o in offsets.items():
            if s in self._offsets:
                self._offsets[s] = int(o)

    def _decode(self, payload: bytes) -> Optional[tuple]:
        """payload → PHYSICAL row tuple (strings interned), or None for
        undecodable messages (counted in dropped_events, offset still
        advances — a poisoned message must not wedge the source)."""
        if self.fmt == "avro":
            try:
                rec = self._avro.decode(payload)
            except Exception:
                self.dropped_events += 1
                return None
            vals = [rec.get(f.name) for f in self.schema]
        else:
            try:
                row = parse_json_line(payload.decode("utf-8", "replace"),
                                      self.schema)
            except (ValueError, TypeError):
                self.dropped_events += 1
                return None
            if row is None:
                return None
            vals = list(row)
        return tuple(
            None if v is None else f.type.to_physical(v)
            for f, v in zip(self.schema, vals))

    def next_chunk(self) -> Optional[StreamChunk]:
        """Round-robin over partitions; one chunk per non-empty fetch."""
        for _ in range(self._n_parts):
            p = self._rr
            self._rr = (self._rr + 1) % self._n_parts
            split = f"{self.topic}-{p}"
            off = self._offsets[split]
            msgs = self._client.fetch(self.topic, p, off,
                                      self.rows_per_chunk)
            if not msgs:
                continue
            rows = []
            for m in msgs:
                r = self._decode(m)
                if r is not None:
                    rows.append(r)
            self._offsets[split] = off + len(msgs)
            if not rows:
                continue
            return make_chunk(self.schema, rows,
                              capacity=max(self.rows_per_chunk, len(rows)),
                              physical=True)
        return None

    def close(self) -> None:
        self._client.close()
