"""Minimal Avro binary codec (no external dependency).

Counterpart of the reference's Avro parser family (reference:
src/connector/src/parser/avro/ — schema-resolved binary datum decode; the
schema-registry wire envelope is the 5-byte magic+id header,
src/connector/src/parser/schema_registry/). Implements the Avro 1.11
binary encoding for the subset streaming ingestion needs:

* records of primitive fields: null, boolean, int, long, float, double,
  string, bytes
* unions (encoded as zigzag branch index + value) — the common
  ``["null", T]`` nullable-field shape
* enums (index → symbol string) and logical types passing through their
  base primitive (timestamp-micros arrives as long, which matches the
  engine's µs TIMESTAMP physical type)

``decode`` accepts either a raw datum or a Confluent-framed message
(magic byte 0x00 + 4-byte schema id), ignoring the id — single-schema
sources, the common case for this engine's broker source.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any, Dict


class AvroError(ValueError):
    pass


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _read_varint(buf: io.BytesIO) -> int:
    shift = 0
    out = 0
    while True:
        b = buf.read(1)
        if not b:
            raise AvroError("truncated varint")
        out |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            return out
        shift += 7
        if shift > 70:
            raise AvroError("varint too long")


def _write_varint(out: bytearray, n: int) -> None:
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_long(buf: io.BytesIO) -> int:
    return _zigzag_decode(_read_varint(buf))


def _write_long(out: bytearray, v: int) -> None:
    _write_varint(out, ((v << 1) ^ (v >> 63)) & ((1 << 64) - 1))


class AvroCodec:
    """Encode/decode datums of one Avro RECORD schema. ``framing``:
    'raw' = bare binary datum; 'confluent' = magic 0x00 + 4-byte
    schema-registry id prefix (stripped on decode, id unchecked — a
    single-schema source). Framing must be DECLARED, not sniffed: a raw
    datum whose first field is a zero varint is byte-identical to the
    magic byte."""

    def __init__(self, schema_json: str, framing: str = "raw"):
        schema = json.loads(schema_json) if isinstance(schema_json, str) \
            else schema_json
        if not (isinstance(schema, dict) and schema.get("type") == "record"):
            raise AvroError("top-level Avro schema must be a record")
        if framing not in ("raw", "confluent"):
            raise AvroError(f"unknown framing {framing!r}")
        self.name = schema.get("name", "record")
        self.framing = framing
        self.fields = [(f["name"], f["type"]) for f in schema["fields"]]

    # -- decode ---------------------------------------------------------------

    def decode(self, payload: bytes) -> Dict[str, Any]:
        if self.framing == "confluent":
            if len(payload) < 5 or payload[0] != 0:
                raise AvroError("missing Confluent wire-format header")
            payload = payload[5:]
        buf = io.BytesIO(payload)
        out = {name: self._read(buf, t) for name, t in self.fields}
        return out

    def _read(self, buf: io.BytesIO, t) -> Any:
        if isinstance(t, list):                       # union
            branch = _read_long(buf)
            if not 0 <= branch < len(t):
                raise AvroError(f"union branch {branch} out of range")
            return self._read(buf, t[branch])
        if isinstance(t, dict):
            if t.get("type") == "enum":
                idx = _read_long(buf)
                symbols = t.get("symbols", [])
                if not 0 <= idx < len(symbols):
                    raise AvroError(f"enum index {idx} out of range")
                return symbols[idx]
            # logical types decode as their base primitive
            return self._read(buf, t.get("type"))
        if t == "null":
            return None
        if t == "boolean":
            b = buf.read(1)
            if not b:
                raise AvroError("truncated boolean")
            return b[0] != 0
        if t in ("int", "long"):
            return _read_long(buf)
        if t == "float":
            raw = buf.read(4)
            if len(raw) != 4:
                raise AvroError("truncated float")
            return struct.unpack("<f", raw)[0]
        if t == "double":
            raw = buf.read(8)
            if len(raw) != 8:
                raise AvroError("truncated double")
            return struct.unpack("<d", raw)[0]
        if t in ("string", "bytes"):
            n = _read_long(buf)
            if n < 0:
                raise AvroError("negative length")
            raw = buf.read(n)
            if len(raw) != n:
                raise AvroError("truncated string/bytes")
            return raw.decode("utf-8") if t == "string" else raw
        raise AvroError(f"unsupported Avro type {t!r}")

    # -- encode (producers in tests / sinks) ----------------------------------

    def encode(self, record: Dict[str, Any]) -> bytes:
        out = bytearray()
        for name, t in self.fields:
            self._write(out, t, record.get(name))
        return bytes(out)

    def _write(self, out: bytearray, t, v) -> None:
        if isinstance(t, list):
            for i, branch in enumerate(t):
                if (branch == "null") == (v is None):
                    _write_long(out, i)
                    return self._write(out, branch, v)
            raise AvroError(f"no union branch for {v!r} in {t}")
        if isinstance(t, dict):
            if t.get("type") == "enum":
                _write_long(out, t["symbols"].index(v))
                return
            return self._write(out, t.get("type"), v)
        if t == "null":
            if v is not None:
                raise AvroError("non-null value for null type")
            return
        if t == "boolean":
            out.append(1 if v else 0)
            return
        if t in ("int", "long"):
            _write_long(out, int(v))
            return
        if t == "float":
            out.extend(struct.pack("<f", float(v)))
            return
        if t == "double":
            out.extend(struct.pack("<d", float(v)))
            return
        if t == "string":
            raw = str(v).encode("utf-8")
            _write_long(out, len(raw))
            out.extend(raw)
            return
        if t == "bytes":
            _write_long(out, len(v))
            out.extend(v)
            return
        raise AvroError(f"unsupported Avro type {t!r}")
