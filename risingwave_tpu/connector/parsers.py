"""Format parsers: encoded source bytes → typed rows.

Counterpart of the reference's parser layer
(reference: src/connector/src/parser/ — JSON, CSV, Debezium et al.). The
parse boundary is also the string-interning boundary: VARCHAR values become
dictionary ids here so the device columns stay integral (SURVEY.md §7
"Varlen strings on device").
"""

from __future__ import annotations

import csv as _csv
import io
import json
from typing import Any, List, Optional, Sequence

from ..common.types import Schema, TypeKind


def _coerce(v: Any, kind: TypeKind, dtype=None) -> Any:
    if v is None:
        return None
    if kind == TypeKind.STRUCT and dtype is not None:
        # nested JSON object -> field tuple in declared order
        if isinstance(v, dict):
            return tuple(
                _coerce(v.get(fn), fk) for fn, fk in dtype.struct_fields)
        return None
    if kind == TypeKind.LIST:
        if isinstance(v, (list, tuple)):
            ek = dtype.elem_kind if dtype is not None else None
            return tuple(_coerce(e, ek) if ek is not None else e
                         for e in v)
        return None
    if kind == TypeKind.JSONB and not isinstance(v, str):
        import json as _json
        return _json.dumps(v, separators=(",", ":"), sort_keys=True)
    if kind in (TypeKind.INT16, TypeKind.INT32, TypeKind.INT64,
                TypeKind.SERIAL, TypeKind.DATE, TypeKind.TIME,
                TypeKind.TIMESTAMP, TypeKind.INTERVAL):
        return int(v)
    if kind in (TypeKind.FLOAT32, TypeKind.FLOAT64):
        return float(v)
    if kind == TypeKind.BOOL:
        if isinstance(v, str):
            return v.strip().lower() in ("t", "true", "1", "yes")
        return bool(v)
    return str(v)


def parse_json_line(line: str, schema: Schema) -> Optional[tuple]:
    """One JSON object → row tuple in schema order; unknown keys ignored,
    missing keys NULL. Returns None for blank lines."""
    line = line.strip()
    if not line:
        return None
    obj = json.loads(line)
    return tuple(_coerce(obj.get(f.name), f.type.kind, f.type)
                 for f in schema)


def parse_json_lines(text: str, schema: Schema) -> List[tuple]:
    rows = []
    for line in text.splitlines():
        r = parse_json_line(line, schema)
        if r is not None:
            rows.append(r)
    return rows


def parse_csv_lines(text: str, schema: Schema,
                    has_header: bool = True,
                    delimiter: str = ",") -> List[tuple]:
    """CSV text → rows. With a header, columns are matched by name;
    without, by position."""
    reader = _csv.reader(io.StringIO(text), delimiter=delimiter)
    rows: List[tuple] = []
    col_order: Optional[Sequence[int]] = None
    first = True
    for rec in reader:
        if not rec:
            continue
        if first and has_header:
            name_to_pos = {n.strip(): i for i, n in enumerate(rec)}
            col_order = [name_to_pos.get(f.name, -1) for f in schema]
            first = False
            continue
        first = False
        if col_order is None:
            col_order = list(range(len(schema)))
        vals = []
        for f, pos in zip(schema, col_order):
            raw = rec[pos] if 0 <= pos < len(rec) else None
            vals.append(None if raw in (None, "") else _coerce(raw, f.type.kind))
        rows.append(tuple(vals))
    return rows


def parse_debezium_line(line: str,
                        schema: Schema) -> List[tuple]:
    """One Debezium-JSON change event → [(op, row), ...] changelog entries
    (reference: src/connector/src/parser/debezium/ — the CDC envelope
    {before, after, op}).

    op mapping: c/r (create/snapshot-read) → Insert(after);
    u (update) → UpdateDelete(before) + UpdateInsert(after);
    d (delete) → Delete(before). Both the flat envelope and the Kafka
    Connect wrapper ({"payload": {...}}) are accepted."""
    from ..common.chunk import (
        OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
    )
    line = line.strip()
    if not line:
        return []
    obj = json.loads(line)
    if not isinstance(obj, dict):
        raise ValueError(f"debezium event is not an object: {line[:40]!r}")
    payload = obj.get("payload", obj)
    if not isinstance(payload, dict):
        raise ValueError("debezium payload is not an object")

    def row_of(img):
        if not isinstance(img, dict):
            raise ValueError("debezium row image is not an object")
        return tuple(
            _coerce(img.get(f.name), f.type.kind, f.type) for f in schema)

    op = payload.get("op")
    before, after = payload.get("before"), payload.get("after")
    if op in ("c", "r") and after is not None:
        return [(OP_INSERT, row_of(after))]
    if op == "u" and after is not None:
        if before is None:
            # REPLICA IDENTITY DEFAULT emits updates without a before
            # image: surface as an upsert insert (the reference's
            # debezium-upsert mode; pk-keyed downstream dedups)
            return [(OP_INSERT, row_of(after))]
        return [(OP_UPDATE_DELETE, row_of(before)),
                (OP_UPDATE_INSERT, row_of(after))]
    if op == "d" and before is not None:
        return [(OP_DELETE, row_of(before))]
    raise ValueError(
        f"malformed debezium event: op={op!r}, "
        f"before={'set' if before is not None else None}, "
        f"after={'set' if after is not None else None}")


def parse_debezium_lines(text: str, schema: Schema) -> List[tuple]:
    """Debezium-JSON lines → [(op, row), ...] changelog."""
    out: List[tuple] = []
    for line in text.splitlines():
        out.extend(parse_debezium_line(line, schema))
    return out
