"""Connector reader factory shared by the frontend session and worker
processes (reference: SplitReaderImpl dispatch,
src/connector/src/source/base.rs:326 — one construction point per
connector, used by every compute node)."""

from __future__ import annotations

from typing import Optional


class ConnectorError(ValueError):
    pass


DEBEZIUM_NEEDS_PK = (
    "format 'debezium_json' requires a source PRIMARY KEY, which "
    "sources do not support yet; the parser is available via "
    "connector.parsers/FileSourceReader")


def make_reader(connector: str, options: dict, schema,
                chunk_capacity: int, seed: int = 42,
                fault=None) -> Optional[object]:
    """Instantiate a connector's SplitReader; None for declared-schema
    sources fed only by tests (empty connector string). ``fault`` (a
    FaultConfig) tunes boundary retry policies, e.g. the broker client's
    reconnect budget."""
    if connector == "nexmark":
        from .nexmark_split import NexmarkReader
        table = str(options.get("nexmark_table",
                                options.get("table", "bid"))).lower()
        rate = options.get("rows_per_chunk")
        cap = int(rate) if rate else chunk_capacity
        return NexmarkReader(table, chunk_capacity=cap, seed=seed)
    if connector == "datagen":
        from .datagen import DatagenReader
        opts = dict(options)
        opts.setdefault("datagen.rows.per.chunk",
                        opts.get("rows_per_chunk", chunk_capacity))
        return DatagenReader(schema, opts)
    if connector in ("file", "posix_fs", "fs"):
        from .filesource import FileSourceReader
        path = options.get("path", options.get("posix_fs.root"))
        if not path:
            raise ConnectorError("file source requires path option")
        fmt = str(options.get("format", "jsonl")).lower()
        if fmt in ("debezium", "debezium_json"):
            # CDC retractions need a pk-keyed source stream; generated
            # row-id sources cannot route Deletes
            raise ConnectorError(DEBEZIUM_NEEDS_PK)
        return FileSourceReader(schema, str(path), fmt=fmt,
                                rows_per_chunk=chunk_capacity)
    if connector in ("broker", "kafka"):
        from .broker import BrokerSourceReader, parse_broker_options
        address, topic = parse_broker_options(options)
        fmt = str(options.get("format", "json")).lower()
        return BrokerSourceReader(
            schema, address, topic, fmt=fmt,
            avro_schema=options.get("avro.schema"),
            avro_framing=str(options.get("avro.framing", "raw")),
            rows_per_chunk=chunk_capacity,
            reconnect_policy=(fault.broker_retry_policy()
                              if fault is not None else None))
    if connector == "":
        return None
    raise ConnectorError(f"unsupported connector {connector!r}")
