"""NEXmark event generator — the benchmark source.

Counterpart of the reference's NEXmark connector
(reference: src/connector/src/source/nexmark/source/reader.rs:41; schemas
from src/tests/simulation/src/nexmark/create_source.sql). Generation is
vectorized numpy on the host (a whole chunk per call — there is no per-event
loop), producing device chunks directly. Distributions follow the NEXmark
spec shape: event ratio person:auction:bid = 1:3:46, hot-auction/hot-bidder
skew, price ~ geometric, monotonically advancing event time.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..common.chunk import StreamChunk, make_chunk, Column
from ..common.types import (
    GLOBAL_STRING_DICT, INT64, Schema, TIMESTAMP, VARCHAR,
)
import jax.numpy as jnp

BID_SCHEMA = Schema.of(
    ("auction", INT64), ("bidder", INT64), ("price", INT64),
    ("channel", VARCHAR), ("url", VARCHAR), ("date_time", TIMESTAMP),
    ("extra", VARCHAR),
)

AUCTION_SCHEMA = Schema.of(
    ("id", INT64), ("item_name", VARCHAR), ("description", VARCHAR),
    ("initial_bid", INT64), ("reserve", INT64), ("date_time", TIMESTAMP),
    ("expires", TIMESTAMP), ("seller", INT64), ("category", INT64),
    ("extra", VARCHAR),
)

PERSON_SCHEMA = Schema.of(
    ("id", INT64), ("name", VARCHAR), ("email_address", VARCHAR),
    ("credit_card", VARCHAR), ("city", VARCHAR), ("state", VARCHAR),
    ("date_time", TIMESTAMP), ("extra", VARCHAR),
)

# NEXmark spec constants (mirroring the generator config semantics in
# src/connector/src/source/nexmark/mod.rs)
PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
TOTAL_PROPORTION = PERSON_PROPORTION + AUCTION_PROPORTION + BID_PROPORTION
FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10
HOT_AUCTION_RATIO = 100
HOT_BIDDER_RATIO = 100
NUM_CATEGORIES = 5

_CHANNELS = ["Google", "Facebook", "Baidu", "Apple"]
_US_STATES = ["AZ", "CA", "ID", "OR", "WY"]
_CITIES = ["Phoenix", "Los Angeles", "San Francisco", "Boise", "Portland"]


@dataclasses.dataclass
class NexmarkConfig:
    chunk_capacity: int = 1024
    events_per_second: int = 10_000   # drives event-time spacing
    active_people: int = 1000
    in_flight_auctions: int = 100
    start_time_us: int = 1_600_000_000_000_000


class NexmarkGenerator:
    """Generates Bid / Auction / Person chunks with a shared event clock."""

    def __init__(self, config: NexmarkConfig = NexmarkConfig(), seed: int = 42):
        self.cfg = config
        self.rng = np.random.default_rng(seed)
        self.events_so_far = 0
        # pre-intern the small string vocabularies
        self._channel_ids = np.array(
            [GLOBAL_STRING_DICT.intern(c) for c in _CHANNELS], np.int32)
        self._url_ids = np.array(
            [GLOBAL_STRING_DICT.intern(f"https://www.nexmark.com/item{i}")
             for i in range(64)], np.int32)
        self._city_ids = np.array(
            [GLOBAL_STRING_DICT.intern(c) for c in _CITIES], np.int32)
        self._state_ids = np.array(
            [GLOBAL_STRING_DICT.intern(s) for s in _US_STATES], np.int32)
        self._name_ids = np.array(
            [GLOBAL_STRING_DICT.intern(f"person-{i}") for i in range(997)],
            np.int32)
        self._item_ids = np.array(
            [GLOBAL_STRING_DICT.intern(f"item-{i}") for i in range(499)],
            np.int32)
        self._empty = GLOBAL_STRING_DICT.intern("")

    # -- event-time / id helpers ---------------------------------------------

    def _advance(self, n: int) -> np.ndarray:
        """Event timestamps (us) for the next n events of this stream's clock."""
        ids = np.arange(self.events_so_far, self.events_so_far + n, dtype=np.int64)
        self.events_so_far += n
        us_per_event = 1_000_000 // max(self.cfg.events_per_second, 1)
        return self.cfg.start_time_us + ids * max(us_per_event, 1), ids

    def _last_auction_id(self, event_ids: np.ndarray) -> np.ndarray:
        epoch = event_ids // TOTAL_PROPORTION
        return FIRST_AUCTION_ID + epoch * AUCTION_PROPORTION

    def _last_person_id(self, event_ids: np.ndarray) -> np.ndarray:
        epoch = event_ids // TOTAL_PROPORTION
        return FIRST_PERSON_ID + epoch * PERSON_PROPORTION

    def _mk_col(self, data: np.ndarray, dtype) -> Column:
        return Column(jnp.asarray(data.astype(dtype)),
                      jnp.ones(len(data), jnp.bool_))

    def _chunk(self, schema: Schema, arrays: list[np.ndarray], n: int) -> StreamChunk:
        cap = self.cfg.chunk_capacity
        cols = []
        for arr, field in zip(arrays, schema):
            buf = np.zeros(cap, field.type.np_dtype)
            buf[:n] = arr.astype(field.type.np_dtype)
            cols.append(Column(jnp.asarray(buf), jnp.asarray(np.arange(cap) < n)))
        ops = jnp.zeros(cap, jnp.int8)  # all Insert (append-only source)
        vis = jnp.asarray(np.arange(cap) < n)
        return StreamChunk(ops, vis, tuple(cols))

    # -- streams --------------------------------------------------------------

    def next_bid_chunk(self, n: Optional[int] = None) -> StreamChunk:
        n = n or self.cfg.chunk_capacity
        ts, eids = self._advance(n)
        last_auction = self._last_auction_id(eids)
        last_person = self._last_person_id(eids)
        hot = self.rng.random(n) < 0.9  # hot auctions get ~90% of bids (spec ratio)
        hot_auction = (last_auction // HOT_AUCTION_RATIO) * HOT_AUCTION_RATIO
        cold_auction = last_auction - self.rng.integers(
            0, self.cfg.in_flight_auctions, n)
        auction = np.where(hot, hot_auction, cold_auction)
        hot_b = self.rng.random(n) < 0.9
        hot_bidder = (last_person // HOT_BIDDER_RATIO) * HOT_BIDDER_RATIO + 1
        cold_bidder = np.maximum(
            last_person - self.rng.integers(0, self.cfg.active_people, n),
            FIRST_PERSON_ID)
        bidder = np.where(hot_b, hot_bidder, cold_bidder)
        price = (100 * np.exp(self.rng.random(n) * np.log(1000.0))).astype(np.int64)
        channel = self._channel_ids[self.rng.integers(0, len(self._channel_ids), n)]
        url = self._url_ids[self.rng.integers(0, len(self._url_ids), n)]
        extra = np.full(n, self._empty, np.int32)
        return self._chunk(
            BID_SCHEMA, [auction, bidder, price, channel, url, ts, extra], n)

    def next_auction_chunk(self, n: Optional[int] = None) -> StreamChunk:
        n = n or self.cfg.chunk_capacity
        ts, eids = self._advance(n)
        ids = FIRST_AUCTION_ID + np.arange(n, dtype=np.int64) + (
            self._last_auction_id(eids[:1])[0] - FIRST_AUCTION_ID)
        item = self._item_ids[self.rng.integers(0, len(self._item_ids), n)]
        desc = np.full(n, self._empty, np.int32)
        initial = self.rng.integers(1, 1000, n).astype(np.int64)
        reserve = initial + self.rng.integers(0, 1000, n)
        expires = ts + self.rng.integers(1_000_000, 60_000_000, n)
        seller = self._last_person_id(eids)
        category = FIRST_CATEGORY_ID + self.rng.integers(0, NUM_CATEGORIES, n)
        extra = np.full(n, self._empty, np.int32)
        return self._chunk(
            AUCTION_SCHEMA,
            [ids, item, desc, initial, reserve, ts, expires, seller, category, extra],
            n)

    def next_person_chunk(self, n: Optional[int] = None) -> StreamChunk:
        n = n or self.cfg.chunk_capacity
        ts, eids = self._advance(n)
        ids = self._last_person_id(eids)
        name = self._name_ids[self.rng.integers(0, len(self._name_ids), n)]
        email = np.full(n, self._empty, np.int32)
        card = np.full(n, self._empty, np.int32)
        city = self._city_ids[self.rng.integers(0, len(self._city_ids), n)]
        state = self._state_ids[self.rng.integers(0, len(self._state_ids), n)]
        extra = np.full(n, self._empty, np.int32)
        return self._chunk(
            PERSON_SCHEMA, [ids, name, email, card, city, state, ts, extra], n)


class DeviceBidGenerator:
    """Bid ChunkBatches generated ON DEVICE inside one jitted step.

    The host generator above feeds correctness tests; this one is the
    benchmark/throughput source: the datagen *is* a compute kernel, so the
    only per-epoch host→device traffic is two scalars (start event id +
    PRNG key) — closing the acknowledged host→device ingest bottleneck
    (BASELINE.md "known headroom"; VERDICT r3 item 1c). Distributions match
    the host generator (NEXmark spec shape: 1:3:46 event ratio arithmetic
    for id clocks, hot-auction/hot-bidder 90% skew, price ~ 100·1000^U,
    event time advancing at events_per_second), using counter-based threefry
    keys so generation is deterministic and replayable from (seed, batch_no)
    alone (reference generator semantics:
    src/connector/src/source/nexmark/source/reader.rs:41)."""

    def __init__(self, config: NexmarkConfig = NexmarkConfig(),
                 seed: int = 42):
        import jax
        self.cfg = config
        self.events_so_far = 0
        self._batch_no = 0
        self._seed = seed
        self._channel_ids = jnp.asarray(
            [GLOBAL_STRING_DICT.intern(c) for c in _CHANNELS], jnp.int32)
        self._url_ids = jnp.asarray(
            [GLOBAL_STRING_DICT.intern(f"https://www.nexmark.com/item{i}")
             for i in range(64)], jnp.int32)
        self._empty = GLOBAL_STRING_DICT.intern("")
        self._gen = jax.jit(self._gen_impl, static_argnums=(2,))

    def _gen_impl(self, start, key, k: int) -> StreamChunk:
        import jax
        cfg = self.cfg
        cap = cfg.chunk_capacity
        n = k * cap
        eids = start + jnp.arange(n, dtype=jnp.int64)
        us_per_event = max(1_000_000 // max(cfg.events_per_second, 1), 1)
        ts = cfg.start_time_us + eids * us_per_event
        epoch = eids // TOTAL_PROPORTION
        last_auction = FIRST_AUCTION_ID + epoch * AUCTION_PROPORTION
        last_person = FIRST_PERSON_ID + epoch * PERSON_PROPORTION
        ks = jax.random.split(key, 7)
        hot = jax.random.uniform(ks[0], (n,)) < 0.9
        hot_auction = (last_auction // HOT_AUCTION_RATIO) * HOT_AUCTION_RATIO
        cold_auction = last_auction - jax.random.randint(
            ks[1], (n,), 0, cfg.in_flight_auctions).astype(jnp.int64)
        auction = jnp.where(hot, hot_auction, cold_auction)
        hot_b = jax.random.uniform(ks[2], (n,)) < 0.9
        hot_bidder = (last_person // HOT_BIDDER_RATIO) * HOT_BIDDER_RATIO + 1
        cold_bidder = jnp.maximum(
            last_person - jax.random.randint(
                ks[3], (n,), 0, cfg.active_people).astype(jnp.int64),
            FIRST_PERSON_ID)
        bidder = jnp.where(hot_b, hot_bidder, cold_bidder)
        price = (100.0 * jnp.exp(
            jax.random.uniform(ks[4], (n,)) * jnp.log(1000.0))
        ).astype(jnp.int64)
        channel = self._channel_ids[jax.random.randint(
            ks[5], (n,), 0, self._channel_ids.shape[0])]
        url = self._url_ids[jax.random.randint(
            ks[6], (n,), 0, self._url_ids.shape[0])]
        extra = jnp.full(n, self._empty, jnp.int32)

        full = jnp.ones((k, cap), jnp.bool_)

        def col(a, dtype):
            return Column(a.astype(dtype).reshape(k, cap), full)

        cols = (col(auction, jnp.int64), col(bidder, jnp.int64),
                col(price, jnp.int64), col(channel, jnp.int32),
                col(url, jnp.int32), col(ts, jnp.int64),
                col(extra, jnp.int32))
        ops = jnp.zeros((k, cap), jnp.int8)   # append-only source
        return StreamChunk(ops, full, cols)

    def next_batch(self, k: int):
        """One ChunkBatch of k full chunks, generated on device."""
        import jax
        from ..common.chunk import ChunkBatch
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed),
                                 self._batch_no)
        self._batch_no += 1
        start = self.events_so_far
        self.events_so_far += k * self.cfg.chunk_capacity
        return ChunkBatch(self._gen(jnp.int64(start), key, k))

    def chunk_fn(self):
        """Traceable ``(start_event_id, key) -> StreamChunk`` producing ONE
        flat chunk — the fusion surface for single-dispatch epochs
        (ops/fused_epoch.py): callers compose it INSIDE their own jit, so
        generation fuses with downstream projection/aggregation — or with
        BOTH sides of the q7 windowed join (fused_source_join_epoch): the
        bucketed interval join derives its probe rows AND its per-window
        aggregate build side from the same generated chunk, where the
        executor bench path needs two same-seed generators producing the
        stream twice."""
        def fn(start, key):
            ch = self._gen_impl(start, key, 1)
            return StreamChunk(
                ch.ops[0], ch.vis[0],
                tuple(Column(c.data[0], c.mask[0]) for c in ch.columns))
        return fn
