from .nexmark import (  # noqa: F401
    AUCTION_SCHEMA, BID_SCHEMA, PERSON_SCHEMA, NexmarkConfig, NexmarkGenerator,
)
from .base import SplitReader  # noqa: F401
from .datagen import DatagenReader  # noqa: F401
from .filesource import FileSourceReader  # noqa: F401
from .nexmark_split import NexmarkReader  # noqa: F401
from .sinks import BlackHoleSink, FileSink, Sink, build_sink  # noqa: F401
